"""Cluster topology, membership, anti-entropy, and resize.

Reference: cluster.go + gossip/ + broadcast.go (SURVEY.md §2 #13–15,
§3.5). Semantics preserved:

- fixed 256 hash partitions; partition = hash(index, shard) % 256; each
  partition maps to ``replica_n`` nodes by walking a ring ordered by node
  id hash;
- a coordinator (lowest node id) owns schema/translation primacy and
  drives resize;
- schema deltas broadcast synchronously to every node (SendSync); node
  liveness via lightweight HTTP heartbeats instead of memberlist UDP
  gossip (the data plane that made gossip latency-critical in the
  reference is gone — intra-slice reduces ride ICI, and the control plane
  tolerates HTTP);
- anti-entropy: per replicated fragment, diff 100-row checksum blocks
  against peers and union-merge differing blocks; attr stores diff their
  own blocks the same way.

The TPU division of labor: this layer decides which *host* owns which
fragment files; inside a host, shards map onto the device mesh
(pilosa_tpu.parallel.mesh) and queries reduce over ICI, so cluster fan-out
only happens across hosts (DCN), exactly where the reference used HTTP.
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time
import uuid

from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.roaring import kernels
from pilosa_tpu.testing import faults
from pilosa_tpu.utils.pool import concurrent_map

PARTITION_N = 256

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"
STATE_DEGRADED = "DEGRADED"

# Consecutive failed heartbeats before the acting coordinator declares a
# node dead and re-replicates its shards (memberlist suspect→dead in the
# reference — SURVEY.md §2 #14, §5.3).
DEAD_HEARTBEATS = 3

# Control messages fenced by the cluster epoch: a copy stamped with an
# epoch older than the receiver's is rejected unapplied. These are the
# messages a partitioned ex-coordinator could otherwise use to un-gate
# queries, re-trigger resizes, or delete fragments with commands minted
# before the partition (docs/OPERATIONS.md failure model). Schema
# deltas and shard announcements stay unfenced — they are idempotent
# and monotonic, and fencing them would wedge mixed-epoch metadata.
FENCED_MESSAGES = frozenset(
    {"cluster-state", "resize-instruction", "resize-cleanup",
     "node-leave", "placement-update", "drain-update", "drain-leave"}
)

# Drain state machine (autopilot/elastic.py): the states a drain record
# moves through, gossiped cluster-wide so any failover coordinator can
# resume mid-drain. ACTIVE states block a second coordinated actuator
# (autopilot pass, another drain) from minting dueling resizes.
DRAIN_ACTIVE_STATES = frozenset({"pending", "moving", "handoff", "leaving"})


class RouteStats:
    """Process-wide write-routing counters (``routing_range_*`` series
    on /metrics — docs/OBSERVABILITY.md). Plain int adds, no lock:
    dashboards, not invariants."""

    __slots__ = ("range_slices", "range_fallbacks", "union_writes",
                 "wire_bytes")

    def __init__(self):
        self.range_slices = 0     # write slices narrowed to span owners
        self.range_fallbacks = 0  # eligible slices forced back to union
        self.union_writes = 0     # write sends routed by union fan-out
        self.wire_bytes = 0       # payload bytes shipped to remote owners

    def metrics(self) -> dict:
        return {
            "routing_range_slices_total": self.range_slices,
            "routing_range_fallback_total": self.range_fallbacks,
            "routing_range_union_writes_total": self.union_writes,
            "routing_range_wire_bytes_total": self.wire_bytes,
        }


_ROUTE_STATS = RouteStats()


def global_route_stats() -> RouteStats:
    return _ROUTE_STATS


class ClusterDegradedError(Exception):
    """This node cannot reach a majority of the member list (minority
    side of a partition): coordination and writes are refused, locally-
    owned reads still serve. Maps to HTTP 503 + Retry-After at the API
    edge (server/api.py)."""

    retry_after = 5.0


class Node:
    def __init__(self, id: str, uri: str):
        self.id = id
        self.uri = uri.rstrip("/")
        self.state = STATE_NORMAL

    def to_json(self) -> dict:
        return {"id": self.id, "uri": self.uri, "state": self.state}

    def __repr__(self):
        return f"Node({self.id}, {self.uri})"


def _hash64(data: str) -> int:
    return int.from_bytes(hashlib.blake2b(data.encode(), digest_size=8).digest(), "big")


class PlacementTable:
    """Epoch-stamped (index, shard) → owner-node-id override map — the
    autopilot's actuator surface, living BESIDE the hash ring rather
    than replacing it.

    The contract that makes mixed-version clusters safe: an EMPTY table
    leaves every ownership decision byte-identical to the pure hash
    walk, and an entry only applies while every listed owner is a live
    member — otherwise the shard falls back to hash placement, which is
    the view an override-unaware (older) node computes anyway. Entries
    are stamped with the cluster epoch the coordinator minted when it
    installed them; a stale copy (gossiped by a healed ex-coordinator)
    loses to any newer table. Persisted beside ``cluster.epoch`` with
    the same tmp+fsync+replace discipline; a corrupt file starts empty
    and the table is re-adopted from gossip (/status, placement-update
    messages) — same recovery posture as the epoch file."""

    def __init__(self, path: str | None = None, logger=None):
        self._lock = threading.Lock()
        self._overrides: dict[tuple[str, int], tuple[str, ...]] = {}
        # Sub-shard range splits (elastic plane): (index, shard) →
        # ((lo, hi, owner-ids), ...) column ranges, sorted by lo. A
        # split ALWAYS travels with a whole-shard override equal to the
        # union of its range owners, so an override-unaware (older)
        # peer — whose from_wire drops the separate "ranges" key —
        # computes the identical data placement from overrides alone;
        # ranges only refine which owner a range-aware reader PREFERS.
        # Empty ⇒ byte-identical to the plain override/hash behavior.
        self._ranges: dict[
            tuple[str, int], tuple[tuple[int, int, tuple[str, ...]], ...]
        ] = {}
        self.epoch = 0
        self._path = path
        self.logger = logger
        self.updates_applied = 0
        self.updates_rejected = 0
        self._load()

    def __len__(self) -> int:
        with self._lock:
            return len(self._overrides)

    def get(self, index: str, shard: int) -> tuple[str, ...] | None:
        with self._lock:
            return self._overrides.get((index, int(shard)))

    def snapshot(self) -> dict[tuple[str, int], tuple[str, ...]]:
        """Point-in-time copy, for callers that make several ownership
        decisions against ONE view (cleanup_unowned's frozen walk)."""
        with self._lock:
            return dict(self._overrides)

    def get_ranges(self, index: str, shard: int
                   ) -> tuple[tuple[int, int, tuple[str, ...]], ...] | None:
        with self._lock:
            return self._ranges.get((index, int(shard)))

    def ranges_snapshot(self) -> dict:
        with self._lock:
            return dict(self._ranges)

    @property
    def range_count(self) -> int:
        with self._lock:
            return sum(len(rs) for rs in self._ranges.values())

    @staticmethod
    def _clean_ranges(ranges) -> dict:
        cleaned: dict[
            tuple[str, int], tuple[tuple[int, int, tuple[str, ...]], ...]
        ] = {}
        for (index, shard), spans in (ranges or {}).items():
            rs = []
            for lo, hi, ids in spans or ():
                lo, hi = int(lo), int(hi)
                ids = tuple(str(i) for i in ids)
                if lo < hi and ids:
                    rs.append((lo, hi, ids))
            if rs:
                rs.sort(key=lambda r: r[0])
                cleaned[(str(index), int(shard))] = tuple(rs)
        return cleaned

    def replace(self, overrides: dict, epoch: int,
                ranges: dict | None = None) -> bool:
        """Install a whole new table stamped ``epoch``. Applies only
        when the stamp beats the current one (strictly newer — the
        coordinator mints a fresh epoch per change, so ties mean a
        duplicate delivery of the same table). ``ranges`` rides the
        same stamp: a table replaced without them (an older coordinator
        or a plain move plan) drops every split — correct, because the
        matching union overrides are gone too. Returns applied?"""
        cleaned: dict[tuple[str, int], tuple[str, ...]] = {}
        for (index, shard), ids in (overrides or {}).items():
            ids = tuple(str(i) for i in ids)
            if ids:
                cleaned[(str(index), int(shard))] = ids
        cleaned_ranges = self._clean_ranges(ranges)
        with self._lock:
            if int(epoch) <= self.epoch:
                self.updates_rejected += 1
                return False
            self._overrides = cleaned
            self._ranges = cleaned_ranges
            self.epoch = int(epoch)
            self.updates_applied += 1
            self._persist_locked()
        return True

    # ------------------------------------------------------------- wire

    @staticmethod
    def wire_entries(overrides: dict) -> list[dict]:
        return [
            {"index": index, "shard": shard, "nodes": list(ids)}
            for (index, shard), ids in sorted(overrides.items())
        ]

    @staticmethod
    def from_wire(entries) -> dict:
        out: dict[tuple[str, int], tuple[str, ...]] = {}
        for e in entries or []:
            try:
                key = (str(e["index"]), int(e["shard"]))
                ids = tuple(str(i) for i in e.get("nodes", []))
            except (KeyError, TypeError, ValueError):
                continue  # one malformed entry must not poison the rest
            if ids:
                out[key] = ids
        return out

    @staticmethod
    def wire_ranges(ranges: dict) -> list[dict]:
        return [
            {"index": index, "shard": shard,
             "spans": [{"lo": lo, "hi": hi, "nodes": list(ids)}
                       for lo, hi, ids in spans]}
            for (index, shard), spans in sorted(ranges.items())
        ]

    @staticmethod
    def ranges_from_wire(entries) -> dict:
        out: dict = {}
        for e in entries or []:
            try:
                key = (str(e["index"]), int(e["shard"]))
                spans = tuple(
                    (int(s["lo"]), int(s["hi"]),
                     tuple(str(i) for i in s.get("nodes", [])))
                    for s in e.get("spans", [])
                )
            except (KeyError, TypeError, ValueError):
                continue  # one malformed entry must not poison the rest
            spans = tuple(s for s in spans if s[0] < s[1] and s[2])
            if spans:
                out[key] = spans
        return out

    def to_json(self) -> dict:
        with self._lock:
            out = {
                "epoch": self.epoch,
                "overrides": self.wire_entries(self._overrides),
            }
            if self._ranges:
                # separate key: an override-unaware peer's from_wire
                # ignores it and still computes identical placement
                # from the union overrides above
                out["ranges"] = self.wire_ranges(self._ranges)
            return out

    # ------------------------------------------------------ persistence

    def _load(self) -> None:
        if self._path is None:
            return
        import json

        try:
            with open(self._path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        except OSError:
            return
        try:
            d = json.loads(raw)
            epoch = int(d.get("epoch", 0) or 0)
            overrides = self.from_wire(d.get("overrides", []))
            ranges = self.ranges_from_wire(d.get("ranges", []))
        except (ValueError, TypeError, AttributeError):
            # corrupt/torn file: start empty, re-adopt from gossip —
            # an override table is always reconstructible cluster state
            if self.logger is not None:
                self.logger.error(
                    "corrupt placement table %r: starting empty "
                    "(re-adopted from gossip)", self._path,
                )
            return
        self._overrides = overrides
        self._ranges = ranges
        self.epoch = epoch

    def _persist_locked(self) -> None:
        if self._path is None:
            return
        import json

        tmp = self._path + ".tmp"
        payload = {"epoch": self.epoch,
                   "overrides": self.wire_entries(self._overrides)}
        if self._ranges:
            payload["ranges"] = self.wire_ranges(self._ranges)
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
        except OSError:  # table still applies in memory; gossip
            pass         # re-seeds it after a restart


class Cluster:
    """Shard→node assignment + membership + schema broadcast."""

    # How long the coordinator holds RESIZING waiting for peers'
    # resize-complete reports before releasing stragglers to anti-entropy
    # repair (tests shrink this).
    RESIZE_COMPLETE_TIMEOUT = 120.0

    def __init__(self, local: Node, peers: list[Node] | None = None,
                 replica_n: int = 1, holder=None, api=None,
                 insecure_tls: bool = False, pool_size: int = 8):
        self.local = local
        self.nodes: dict[str, Node] = {local.id: local}
        for p in peers or []:
            self.nodes[p.id] = p
        self.replica_n = replica_n
        self.holder = holder
        self.api = api  # set by Server after API construction
        self.client = InternalClient(insecure_tls=insecure_tls,
                                     pool_size=pool_size)
        self._state = STATE_NORMAL
        self._state_normal = threading.Event()
        self._state_normal.set()
        self._lock = threading.RLock()
        # bytes of the coordinator's translate log already applied locally;
        # resets on restart (re-apply is idempotent)
        self._translate_offset = 0
        # shards learned from peers' create-shard broadcasts (reference
        # CreateShardMessage): new remote shards become visible to queries
        # immediately instead of after a catalog-poll TTL
        self.known_shards: dict[str, set[int]] = {}
        self._announced_shards: dict[str, set[int]] = {}
        self._heartbeat_failures: dict[str, int] = {}
        self._resize_lock = threading.Lock()
        # async resize-job tracking (coordinator side): peers ack the
        # instruction immediately, fetch in a worker, and report
        # resize-complete; the coordinator holds RESIZING until every
        # pending peer reports (or the straggler timeout passes)
        self._resize_cv = threading.Condition()
        self._resize_job: str | None = None
        self._resize_pending: set[str] = set()
        self._resize_deadline = 0.0
        # Local fetch-job gate: while this node is pulling fragments it
        # does not yet have (self-join pull, resize-instruction worker),
        # it must stay RESIZING — a concurrently finishing resize path
        # (the coordinator's NORMAL broadcast, another local job's
        # completion) must not un-gate queries mid-fetch. The counter
        # tracks jobs in flight; _commanded_state remembers the last
        # externally commanded state so the final job restores it.
        self._gate_lock = threading.Lock()
        self._local_fetch_jobs = 0
        self._commanded_state = STATE_NORMAL
        self.logger = None  # set by Server; failures fall back to stderr
        # Anti-entropy pipeline width (ServerConfig sync-workers): owned
        # fragments diff/fetch/apply concurrently, so a pass tracks the
        # slowest peer's RTTs, not the sum over fragments — which also
        # shrinks the gated self-join window that rides sync_holder.
        self.sync_workers = 8
        # ---- partition tolerance (docs/OPERATIONS.md failure model) ----
        # Monotonic cluster epoch: minted by the acting coordinator (with
        # quorum) at each coordinated action, stamped on every fenced
        # control message, persisted as the highest epoch SEEN — so a
        # partitioned ex-coordinator healing back cannot act with
        # commands minted before the partition. Bare clusters (no holder
        # data dir) keep it in memory only.
        self._epoch_path = None
        data_dir = getattr(holder, "data_dir", None) if holder else None
        if data_dir:
            self._epoch_path = os.path.join(data_dir, "cluster.epoch")
        self.epoch = self._load_epoch()
        # Heat-weighted placement overrides (autopilot actuator): empty
        # table ⇒ byte-identical to the pure hash ring. Persisted beside
        # the epoch file; bare clusters keep it in memory only.
        self.placement = PlacementTable(
            path=(os.path.join(data_dir, "cluster.placement")
                  if data_dir else None),
        )
        # Ring memoization: _frozen_ring re-sorted (and re-blake2b'd
        # every node id) per shard per query fan-out. The generation
        # counter bumps at every membership mutation; the hash memo
        # never invalidates (a node id's hash is immutable), only
        # bounded. Belt-and-braces validation against a missed bump:
        # the cached ring must also match the live dict's identity and
        # size (membership changes always change one or the other,
        # except same-id object replacement — covered by the bump).
        self._ring_gen = 0
        self._ring_cache: tuple[int, int, int, list[Node]] | None = None
        self._ring_hash_memo: dict[str, int] = {}
        if getattr(self, "_epoch_file_corrupt", False):
            # rewrite the corrupt file NOW so the next restart reads a
            # clean value instead of re-diagnosing the same garbage
            self._persist_epoch_locked()
        # True while this node cannot reach a member-list majority: the
        # minority side of a partition serves locally-owned reads only
        # (writes shed 503, no resize, no cleanup, no death declaring).
        self.degraded = False
        # Tight dedicated timeout for liveness probes (heartbeat, quorum
        # checks, death corroboration): a hung peer's socket must not
        # stall the whole heartbeat loop and delay detection of OTHER
        # failures. ServerConfig heartbeat-timeout.
        self.heartbeat_timeout = 2.0
        # (epoch, action) every time THIS node acted as coordinator —
        # the chaos harness's ≤1-coordinator-per-epoch oracle reads it.
        # Bounded deques: on a long-lived server under churn these are
        # observability rings, not unbounded history (the harness
        # drains them between schedules, far below the caps).
        import collections as _collections

        self.acted_epochs = _collections.deque(maxlen=4096)
        # every cleanup_unowned decision (epoch, quorum, removed count)
        # — the no-deletion-without-quorum oracle reads it
        self.cleanup_log = _collections.deque(maxlen=1024)
        self._rejoin_lock = threading.Lock()
        self._left = False  # leave() called: never auto-rejoin
        # peers this node declared dead (id → uri): a node that ends up
        # SOLO probes them on heartbeat — if one answers, the "deaths"
        # were a partition and the sides reunite instead of serving as
        # split-brained 1-node clusters forever
        self._forgotten: dict[str, str] = {}
        # observability counters (api.cluster_metrics → /metrics)
        self.stale_epoch_rejects = 0
        self.heartbeat_probes = 0
        self.heartbeat_probe_failures = 0
        self.deaths_declared = 0
        self.deaths_vetoed = 0
        self.quorum_denials = 0
        self.rejoins = 0
        self.cleanups_deferred = 0
        # ---- elastic membership plane (autopilot/elastic.py) ----
        # The cluster-wide drain record: epoch-stamped at drain start,
        # rev-bumped per state change, gossiped via /status and
        # drain-update messages so a failover coordinator resumes the
        # state machine where the dead one left it. Empty = no drain
        # has ever run.
        self.drain_record: dict = {}
        # True on the drain TARGET while its groups move off (and after
        # it has left the ring): writes shed 503 with the "draining"
        # qos reason, reads keep serving the tail.
        self.draining = False
        # join-absorption counters: heat-ordered warm fetches and the
        # byte-verify outcomes of the gated self-join path
        self.warm_heat_ordered = 0
        self.warm_verified = 0
        self.warm_verify_failed = 0

    @property
    def state(self) -> str:
        return self._state

    @state.setter
    def state(self, value: str) -> None:
        self._state = value
        if value == STATE_NORMAL:
            self._state_normal.set()
        else:
            self._state_normal.clear()

    def wait_until_normal(self, timeout: float) -> bool:
        """Block until the cluster leaves RESIZING (queries are deferred
        during a resize, reference cluster state machine — SURVEY.md §2
        #13). Returns False on timeout."""
        return self._state_normal.wait(timeout)

    def _command_state(self, value: str) -> None:
        """Apply an externally commanded cluster state (coordinator
        broadcast, or the local coordinator path itself). A NORMAL
        command is deferred while local fetch jobs are in flight — the
        last job to finish restores it (_end_local_fetch)."""
        with self._gate_lock:
            self._commanded_state = value
            if value == STATE_NORMAL and self._local_fetch_jobs > 0:
                return
            self.state = value

    def _begin_local_fetch(self) -> None:
        with self._gate_lock:
            self._local_fetch_jobs += 1
            self.state = STATE_RESIZING

    def _end_local_fetch(self) -> None:
        with self._gate_lock:
            self._local_fetch_jobs -= 1
            if self._local_fetch_jobs <= 0:
                self.state = self._commanded_state

    # --------------------------------------------------- epoch / quorum

    def _load_epoch(self) -> int:
        """Read the persisted epoch high-water mark. A corrupt or torn
        ``cluster.epoch`` (binary garbage, a half-written tmp swap) is
        an OPERATIONAL event, not a crash: log it, start from 0, and
        re-persist a clean file — the real epoch is re-adopted from
        gossip on the first peer contact (adopt_epoch takes the max any
        peer reports), so fencing recovers to cluster truth without
        operator surgery."""
        self._epoch_file_corrupt = False
        if self._epoch_path is None:
            return 0
        try:
            with open(self._epoch_path, "rb") as f:
                raw = f.read(64).decode("ascii", errors="replace").strip()
        except FileNotFoundError:
            return 0
        except OSError as e:
            self._log_exception("cluster epoch read", e)
            return 0
        if not raw:
            return 0
        try:
            return int(raw)
        except ValueError:
            self._epoch_file_corrupt = True
            self._log_exception(
                "cluster epoch file",
                ValueError(
                    f"corrupt {self._epoch_path!r} (contents "
                    f"{raw[:32]!r}): re-adopting epoch from gossip"
                ),
            )
            return 0

    def _persist_epoch_locked(self) -> None:
        if self._epoch_path is None:
            return
        tmp = self._epoch_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(str(self.epoch))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._epoch_path)
        except OSError as e:  # epoch still advances in memory; fencing
            # degrades to per-process until the disk recovers
            self._log_exception("cluster epoch persist", e)

    def adopt_epoch(self, epoch: int) -> None:
        """Record a higher epoch seen on the wire (messages, peers'
        /status). The persisted high-water mark is what stops a
        RESTARTED ex-coordinator from reusing pre-partition epochs."""
        with self._lock:
            if epoch > self.epoch:
                self.epoch = int(epoch)
                self._persist_epoch_locked()

    def adopt_placement(self, d) -> bool:
        """Apply a placement table seen on the wire (placement-update
        message, a peer's /status, the join seed). Strictly-newer
        stamps win; anything malformed is ignored — the table is
        always reconstructible from the coordinator's next gossip."""
        if not isinstance(d, dict):
            return False
        try:
            epoch = int(d.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return False
        if epoch <= self.placement.epoch:
            return False  # cheap pre-check; replace() re-checks locked
        overrides = PlacementTable.from_wire(d.get("overrides", []))
        ranges = PlacementTable.ranges_from_wire(d.get("ranges", []))
        applied = self.placement.replace(overrides, epoch, ranges=ranges)
        if applied and self.logger is not None:
            self.logger.info(
                "%s adopted placement table epoch %d (%d overrides, "
                "%d split shards)",
                self.local.id, epoch, len(overrides), len(ranges),
            )
        return applied

    def apply_placement(self, overrides: dict,
                        ranges: dict | None = None) -> int:
        """Coordinator-side install of a new override table, the
        autopilot's single actuator: quorum-gated, epoch-minted (so the
        broadcast fences above every stale copy), persisted, and pushed
        to every peer. The caller then drives coordinate_resize() — new
        owners pull their fragments through the existing epoch-fenced
        machinery and the post-resize cleanup drops the old copies.
        ``ranges`` carries sub-shard splits (each split's union owners
        MUST also appear as a whole-shard override — the planner and
        drain both enforce it — so range-unaware peers compute the same
        data placement). Returns the minted epoch, or 0 when refused
        (not coordinator / no quorum)."""
        if not self.is_acting_coordinator:
            return 0
        if len(self.nodes) > 1 and not self.check_quorum():
            return 0
        epoch = self._bump_epoch()
        self._note_acted(epoch, "placement-update")
        self.placement.replace(overrides, epoch, ranges=ranges)
        message = {
            "type": "placement-update", "epoch": epoch,
            "overrides": PlacementTable.wire_entries(
                self.placement.snapshot()),
        }
        range_snapshot = self.placement.ranges_snapshot()
        if range_snapshot:
            message["ranges"] = PlacementTable.wire_ranges(range_snapshot)
        self._broadcast(message)
        return epoch

    # ------------------------------------------------------ drain record

    @property
    def drain_active(self) -> bool:
        """A drain is in flight somewhere in the cluster: one
        coordinated actuator at a time (autopilot skips, a second
        drain is refused)."""
        return self.drain_record.get("state") in DRAIN_ACTIVE_STATES

    def set_drain(self, record: dict) -> None:
        """Install + broadcast a drain record (coordinator side, or the
        failover coordinator taking the state machine over). The record
        is epoch-stamped once at drain start and rev-bumped per state
        change, so adopt_drain orders copies without re-minting."""
        with self._lock:
            self.drain_record = dict(record)
        self._apply_drain_side_effects()
        # wire epoch is the CURRENT cluster epoch, not the record's
        # minted-at-start epoch: the drain's own moving step bumps the
        # cluster epoch (apply_placement + resize), and a later state
        # advance stamped with the start epoch would be fenced as stale
        # by every peer. Fencing guards against stale SENDERS; record
        # ordering is (epoch, rev) inside adopt_drain.
        self._broadcast({
            "type": "drain-update",
            "epoch": self.epoch,
            "drain": dict(record),
        })

    def adopt_drain(self, d) -> bool:
        """Apply a drain record seen on the wire (drain-update message,
        a peer's /status, the join seed). Ordered by (epoch, rev) —
        strictly newer wins; malformed copies are ignored."""
        if not isinstance(d, dict) or not d:
            return False
        try:
            key = (int(d.get("epoch", 0) or 0), int(d.get("rev", 0) or 0))
        except (TypeError, ValueError):
            return False
        if key[0] <= 0:
            return False
        with self._lock:
            cur = self.drain_record
            cur_key = (int(cur.get("epoch", 0) or 0),
                       int(cur.get("rev", 0) or 0))
            if key <= cur_key:
                return False
            self.drain_record = dict(d)
        self._apply_drain_side_effects()
        return True

    def _apply_drain_side_effects(self) -> None:
        """Recompute the local ``draining`` latch from the current
        record: the TARGET sheds writes through every active state and
        stays shedding after "done" if it actually departed (_left) —
        a drained node is read-only until decommissioned. A target that
        never left (drain resolved via declare-dead, then the node
        healed and rejoined) un-sheds on the terminal state, because it
        is a full member again."""
        with self._lock:
            record = dict(self.drain_record)
        if record.get("target") != self.local.id:
            return
        state = record.get("state")
        was = self.draining
        self.draining = (state in DRAIN_ACTIVE_STATES
                         or (state == "done" and self._left))
        if was != self.draining and self.logger is not None:
            self.logger.info(
                "%s drain latch -> %s (drain state %s)",
                self.local.id, self.draining, state,
            )

    # ---------------------------------------------- departed-member CDC

    def drop_departed_cursors(self, node_id: str) -> int:
        """Drop WAL CDC cursors a permanently departed member
        registered on this node's WAL (``tailer:<id>``,
        ``follower:<id>``): a dead node's cursor would otherwise pin
        WAL retention until force-reclaim. Called on node-leave (drain
        handoff, graceful exit) and declare-dead; counted in the
        ``wal_cdc_cursors_dropped_total`` metric."""
        wal = getattr(self.holder, "wal", None) if self.holder else None
        if wal is None:
            return 0
        drop = getattr(wal, "drop_cursors_for", None)
        if drop is None:
            return 0
        dropped = drop(node_id)
        if dropped and self.logger is not None:
            self.logger.info(
                "dropped %d CDC cursor(s) for departed member %s",
                dropped, node_id,
            )
        return dropped

    # Epochs advance in strides, with each node minting into its own
    # hash slot: two coordinators acting CONCURRENTLY (possible in the
    # documented 2-member/asymmetric corner where both sides pass their
    # quorum check) mint provably DIFFERENT epochs, so "one authority
    # per epoch" holds by construction and the conflict resolves by
    # fencing — the higher epoch's commands win, the lower side's are
    # rejected everywhere (the Raft-term shape, without the election).
    EPOCH_STRIDE = 1024

    def _bump_epoch(self) -> int:
        """Mint the next epoch for a coordinated action (caller holds
        quorum — check_quorum adopted the cluster-wide max first, so
        the minted epoch exceeds anything any reachable peer has
        seen)."""
        with self._lock:
            slot = _hash64(self.local.id) % self.EPOCH_STRIDE
            self.epoch = ((self.epoch // self.EPOCH_STRIDE + 1)
                          * self.EPOCH_STRIDE + slot)
            self._persist_epoch_locked()
            return self.epoch

    def quorum_size(self) -> int:
        """Majority of the CURRENT member list (the list quorum-gated
        actions froze their decisions against)."""
        with self._lock:
            return len(self.nodes) // 2 + 1

    def check_quorum(self) -> bool:
        """Live quorum probe: concurrently /status every member with the
        tight heartbeat timeout; this node has quorum when itself plus
        the reachable peers form a member-list majority. Adopts any
        higher epoch a peer reports (so an action minted next fences
        above everything the majority has seen) and updates
        ``degraded``.

        Two-node special case: a majority of 2 is 2, so a lone survivor
        could never fail over — the reference has the same blind spot
        (memberlist cannot distinguish peer death from a cut link with
        n=2). A 2-node survivor is allowed to act; the tradeoff is
        documented in docs/OPERATIONS.md: run 3+ nodes for partition
        safety."""
        with self._lock:
            peers = [n for n in self.nodes.values()
                     if n.id != self.local.id]
            n = len(peers) + 1
        if not peers:
            self.degraded = False
            return True

        def probe(node):
            try:
                st = self.client.status(node.uri,
                                        timeout=self.heartbeat_timeout)
            except Exception:  # noqa: BLE001 — any transport symptom
                # (wrapped or raw) reads as unreachable for the vote
                return None
            return int(st.get("epoch", 0) or 0)

        epochs = [e for e in concurrent_map(probe, peers) if e is not None]
        top = max(epochs, default=0)
        if top > self.epoch:
            self.adopt_epoch(top)
        ok = (1 + len(epochs)) >= (n // 2 + 1) or n <= 2
        self.degraded = not ok
        if not ok:
            self.quorum_denials += 1
        return ok

    def _note_acted(self, epoch: int, action: str) -> None:
        self.acted_epochs.append((epoch, action))

    # Bounded jittered retry for control-message sends: one dropped
    # node-leave/state broadcast must not strand a peer in RESIZING
    # until the straggler timeout. Class attributes so tests and the
    # chaos harness can shrink the backoff.
    SEND_ATTEMPTS = 3
    SEND_BACKOFF_S = 0.05

    def _send_retry(self, uri: str, message: dict) -> dict:
        """send_message with bounded jittered-backoff retry on NODE
        faults (transport, 5xx). Deterministic 4xx never retries —
        every replay would answer the same. Raises the last ClientError
        when every attempt fails."""
        last: ClientError | None = None
        for attempt in range(max(1, self.SEND_ATTEMPTS)):
            try:
                return self.client.send_message(uri, message)
            except ClientError as e:
                if not e.is_node_fault:
                    raise
                last = e
                if attempt + 1 < self.SEND_ATTEMPTS:
                    time.sleep(self.SEND_BACKOFF_S * (2 ** attempt)
                               * (0.5 + random.random()))
        raise last

    def metrics(self) -> dict:
        """Partition-tolerance series for /metrics and /debug/vars
        (docs/OBSERVABILITY.md) — every key present from scrape one."""
        with self._lock:
            members = len(self.nodes)
            suspects = sum(1 for f in self._heartbeat_failures.values()
                           if f > 0)
        return {
            "cluster_epoch": self.epoch,
            "cluster_quorum": 0 if self.degraded else 1,
            "cluster_degraded": 1 if self.degraded else 0,
            "cluster_members": members,
            "cluster_suspects": suspects,
            "cluster_heartbeat_probes_total": self.heartbeat_probes,
            "cluster_heartbeat_failures_total":
                self.heartbeat_probe_failures,
            "cluster_deaths_declared_total": self.deaths_declared,
            "cluster_deaths_vetoed_total": self.deaths_vetoed,
            "cluster_stale_epoch_rejects_total": self.stale_epoch_rejects,
            "cluster_quorum_denials_total": self.quorum_denials,
            "cluster_rejoins_total": self.rejoins,
            "cluster_cleanup_deferred_total": self.cleanups_deferred,
            "cluster_placement_overrides": len(self.placement),
            "cluster_placement_epoch": self.placement.epoch,
            "cluster_placement_ranges": self.placement.range_count,
            "elastic_drain_active": 1 if self.drain_active else 0,
            "elastic_drain_epoch":
                int(self.drain_record.get("epoch", 0) or 0),
            "elastic_draining": 1 if self.draining else 0,
            "elastic_warm_heat_ordered_total": self.warm_heat_ordered,
            "elastic_warm_verified_total": self.warm_verified,
            "elastic_warm_verify_failed_total": self.warm_verify_failed,
        }

    # How long the coordinator waits for every member to drain to NORMAL
    # before the post-resize cleanup. A member still RESIZING runs its
    # own gated self-join fetch, which may be SOURCING from fragments the
    # cleanup would delete; on timeout the cleanup is skipped entirely
    # (safe: stale copies only mislead after a LATER ownership change,
    # and the next resize retries the cleanup). Runs under _resize_lock,
    # so the timeout also bounds how long a follow-on resize can be
    # delayed behind an undrainable peer.
    CLEANUP_DRAIN_TIMEOUT = 15.0

    def _broadcast_cleanup(self, epoch: int | None = None) -> None:
        """End-of-resize holder cleanup, coordinator-initiated: every
        member drops fragments for shards it no longer owns. Runs ONLY
        after (a) every receiver reported resize-complete AND (b) every
        member's /status shows NORMAL — a joiner's self-join inventory
        fetch is a separate background job that outlives the
        instruction-resize, and deleting its source fragments mid-fetch
        loses sole copies (exactly what happened when cleanup ran at
        resize-complete time in the join test). The message carries the
        membership the coordinator resized against: a receiver whose
        member view disagrees (missed join/leave broadcast) skips, so a
        stale ring can never compute wrong ownership and delete a sole
        surviving copy."""
        with self._lock:
            members = sorted(self.nodes)
            # Poll EVERY peer, including DEGRADED ones: a transient
            # failure (missed instruction ack, heartbeat blip) marks a
            # LIVE node DEGRADED while its gated self-join fetch is
            # still in flight — skipping it here would let cleanup
            # delete the sole source copy that fetch is about to pull
            # (fatal at replica_n=1). An actually-dead peer never
            # reports NORMAL, so the deadline below converts it into a
            # conservative cleanup skip; the timeout bounds how long a
            # follow-on resize can stall behind it.
            peers = [n for n in self.nodes.values()
                     if n.id != self.local.id]
        deadline = time.monotonic() + self.CLEANUP_DRAIN_TIMEOUT
        pending = {p.id: p for p in peers}
        while pending:
            with self._lock:
                if sorted(self.nodes) != members:
                    return  # membership changed mid-drain: the new
                            # event's own resize will clean up instead
            for pid, node in list(pending.items()):
                try:
                    st = self.client.status(node.uri)
                except Exception:  # noqa: BLE001 — a freshly-killed
                    # peer can surface raw socket errors the client
                    # doesn't wrap; any failure means "not confirmably
                    # NORMAL", retried until the deadline
                    continue
                if st.get("state") == STATE_NORMAL:
                    del pending[pid]
            if not pending:
                break
            if time.monotonic() >= deadline:
                if self.logger is not None:
                    self.logger.info(
                        "skipping post-resize cleanup: %s still draining",
                        sorted(pending),
                    )
                return
            time.sleep(0.1)
        try:
            self.cleanup_unowned(members, epoch=epoch)
        except Exception as e:  # noqa: BLE001 — must not wedge the resize
            self._log_exception("post-resize holder cleanup", e)
        message = {"type": "resize-cleanup", "members": members}
        if epoch is not None:
            # epoch-fenced: a receiver that has seen a newer epoch (a
            # later coordinator acted) must not delete by this resize's
            # now-stale view of ownership
            message["epoch"] = epoch
        self._broadcast(message)

    def cleanup_unowned(self, members: list[str] | None = None,
                        epoch: int | None = None) -> int:
        """Reference post-resize holder cleanup: delete fragments for
        shards this node no longer owns. Without this, a node that loses
        a shard during churn keeps an era-frozen copy; when a later
        resize returns ownership, the missing-only fetch skips the held
        fragment and the node serves stale data (set-field union repair
        cannot remove the stale-extra bits, and Store/ClearRow computed
        from the stale replica poison healthy ones — found by the
        seed-swept membership-churn property test). ``members`` is the
        coordinator's post-resize membership; mismatch with the local
        view means this node's ring is stale and deleting by it could
        destroy a sole copy — skip. Returns #fragments removed.

        The node RING is snapshotted under _lock at the same moment the
        membership is verified, and every per-shard ownership decision
        below walks that frozen snapshot (ADVICE r5 TOCTOU): a
        node-join/leave message landing mid-loop would otherwise swing
        shard_nodes() to the NEW ring before the new ring's resize has
        copied anything — at replica_n=1 deleting by the new ring
        destroys the sole copy the coming resize needs as its source.

        QUORUM-GATED (docs/OPERATIONS.md failure model): fragment
        deletion is the one irreversible control-plane action, and a
        minority-side node's ring is by definition a minority view of
        ownership — under an asymmetric partition the pre-gate code
        deleted sole surviving copies by it. No member-majority contact
        → no deletion, logged and counted. Every decision (epoch,
        quorum, removed) lands in ``cleanup_log`` — the chaos harness's
        no-deletion-without-quorum oracle reads it."""
        if self.holder is None:
            return 0
        entry = {
            "epoch": self.epoch if epoch is None else int(epoch),
            "quorum": True, "removed": 0, "skipped": None,
        }
        self.cleanup_log.append(entry)
        with self._lock:
            n_members = len(self.nodes)
        if n_members > 1 and not self.check_quorum():
            entry["quorum"] = False
            entry["skipped"] = "no quorum"
            if self.logger is not None:
                self.logger.info(
                    "skipping holder cleanup on %s: no member quorum",
                    self.local.id,
                )
            return 0
        faults.crash_point("cluster.pre-cleanup")
        with self._lock:
            local_members = sorted(self.nodes)
            ring = self._frozen_ring()
            # overrides freeze WITH the ring: a placement-update landing
            # mid-walk must not swing ownership under the deletions
            placement = self.placement.snapshot()
        if self.local.id not in local_members:
            entry["skipped"] = "departed"
            return 0  # departed (leave()): never self-wipe on exit
        if members is not None and sorted(members) != local_members:
            entry["skipped"] = "membership mismatch"
            if self.logger is not None:
                self.logger.info(
                    "skipping post-resize cleanup: membership %s != "
                    "coordinator's %s", local_members, sorted(members),
                )
            return 0
        removed = 0
        deferred = 0
        for index_name, idx in list(self.holder.indexes.items()):
            owned: dict[int, bool] = {}
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    unowned = []
                    for shard in list(view.fragments):
                        mine = owned.get(shard)
                        if mine is None:
                            mine = any(
                                n.id == self.local.id
                                for n in self._shard_nodes_on(
                                    ring, placement, index_name, shard,
                                )
                            )
                            owned[shard] = mine
                        if mine:
                            continue
                        frag = view.fragment(shard)
                        if (frag is not None and frag.count()
                                and not self._owner_covers(
                                    ring, placement, index_name,
                                    field.name, view.name, shard, frag)):
                            # this copy holds bits NO owner does — a
                            # write acked under an older ring, or
                            # divergence a partition left behind.
                            # Deleting it would lose acked data;
                            # keep it until an anti-entropy pass
                            # absorbs it into the owners (stray-copy
                            # absorption in _sync_fragment), and let
                            # the resize after that delete it.
                            deferred += 1
                            continue
                        unowned.append(shard)
                    # bulk removal: one durable-tombstone barrier per
                    # view, not one group-commit fsync per shard
                    view_removed = view.remove_fragments(
                        unowned, invalidate_derived=False
                    )
                    if view_removed:
                        # one derived-entry purge per field, not per shard
                        view.invalidate_derived_entries()
                        removed += view_removed
        entry["removed"] = removed
        entry["deferred"] = deferred
        if deferred:
            self.cleanups_deferred += deferred
        if (removed or deferred) and self.logger is not None:
            self.logger.info(
                "post-resize cleanup: removed %d non-owned fragments"
                " (%d deferred: owners have not absorbed their bits)",
                removed, deferred,
            )
        return removed

    def _owner_covers(self, ring, placement, index_name: str,
                      field_name: str, view_name: str, shard: int,
                      frag) -> bool:
        """True when some live owner of ``shard`` demonstrably holds a
        SUPERSET of this fragment's bits, so deleting the local copy
        cannot lose data. Checksum-equal blocks are covered outright;
        differing blocks are fetched and compared as sets — a strict
        subset (the era-frozen-copy case) still deletes, only bits the
        owner genuinely lacks defer the deletion. Uses the per-block
        legacy wire so mixed-version owners answer too; an unreachable
        owner simply fails to cover (the next pass retries)."""
        local_blocks = dict(frag.blocks())
        if not local_blocks:
            return True
        for node in self._shard_nodes_on(
                ring, placement, index_name, shard):
            if node.id == self.local.id:
                continue
            try:
                peer_blocks = dict(self.client.fragment_blocks(
                    node.uri, index_name, field_name, view_name, shard,
                ))
            except ClientError:
                continue
            covered = True
            for block, checksum in local_blocks.items():
                if peer_blocks.get(block) == checksum:
                    continue  # identical content
                try:
                    bm = self.client.fragment_block_bitmap(
                        node.uri, index_name, field_name, view_name,
                        shard, block,
                    )
                except ClientError:
                    covered = False
                    break
                # subset test as one galloping set-difference kernel
                # over the two sorted id arrays, not Python sets
                peer_ids = kernels.fragment_ids(kernels.flatten(bm))
                if kernels.setdiff_sorted(
                        frag.block_ids(block), peer_ids).size:
                    covered = False  # we hold bits this owner lacks
                    break
            if covered:
                return True
        return False

    def _log_exception(self, what: str, exc: BaseException) -> None:
        logger = self.logger
        if logger is not None:
            logger.error("%s failed on %s: %r", what, self.local.id, exc)
        else:  # no server wired (bare Cluster in tests/tools)
            import traceback

            traceback.print_exception(exc)

    def _drop_resize_pending(self, node_id: str) -> None:
        """A departed/dead node can't report resize-complete; don't gate
        the cluster on it for the full straggler timeout."""
        with self._resize_cv:
            if node_id in self._resize_pending:
                self._resize_pending.discard(node_id)
                self._resize_cv.notify_all()

    # ----------------------------------------------------------- membership

    @property
    def coordinator(self) -> Node:
        return self.sorted_nodes()[0]

    @property
    def is_coordinator(self) -> bool:
        return self.coordinator.id == self.local.id

    def sorted_nodes(self) -> list[Node]:
        return [self.nodes[i] for i in sorted(self.nodes)]

    def nodes_json(self) -> list[dict]:
        out = []
        for n in self.sorted_nodes():
            d = n.to_json()
            d["isCoordinator"] = n.id == self.coordinator.id
            out.append(d)
        return out

    # ----------------------------------------------------------- assignment

    def partition(self, index: str, shard: int) -> int:
        return _hash64(f"{index}:{shard}") % PARTITION_N

    def partition_nodes(self, partition: int) -> list[Node]:
        """replica_n nodes for a partition: walk the ring of nodes ordered
        by hash(node id), starting at the partition's point."""
        return self._partition_nodes_on(
            self._frozen_ring(), partition
        )

    def _note_membership_changed_locked(self) -> None:
        """Caller holds _lock and just mutated ``self.nodes``: the
        memoized ring is stale."""
        self._ring_gen += 1

    def _frozen_ring(self) -> list[Node]:
        """Hash-ordered snapshot of the current membership. Callers that
        make several ownership decisions against ONE membership view
        (cleanup_unowned) take this once under _lock and walk it, so a
        join/leave landing mid-walk cannot shift ownership under them.

        Memoized per ring generation (bumped on every membership
        mutation): the blake2b per node per call showed up per shard
        per query fan-out. Callers treat the returned list as frozen —
        never mutate it."""
        with self._lock:
            cached = self._ring_cache
            if (cached is not None and cached[0] == self._ring_gen
                    and cached[1] == id(self.nodes)
                    and cached[2] == len(self.nodes)):
                return cached[3]
            memo = self._ring_hash_memo
            if len(memo) > 4096:  # bound, not invalidate: id→hash is
                memo.clear()      # immutable, churn just grows the map

            def ring_key(n: Node) -> tuple[int, str]:
                h = memo.get(n.id)
                if h is None:
                    h = _hash64(n.id)
                    memo[n.id] = h
                return (h, n.id)

            ring = sorted(self.nodes.values(), key=ring_key)
            self._ring_cache = (self._ring_gen, id(self.nodes),
                                len(self.nodes), ring)
            return ring

    def _partition_nodes_on(self, ring: list[Node],
                            partition: int) -> list[Node]:
        if not ring:
            return []
        start = partition % len(ring)
        n = min(self.replica_n, len(ring))
        return [ring[(start + i) % len(ring)] for i in range(n)]

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        """Owners of one shard: the placement override when one applies
        (every listed owner a live member), else the pure hash walk.
        With an empty override table this is byte-identical to the
        pre-autopilot placement — the mixed-version safety contract.
        A range-split shard resolves through its union override (the
        planner installs both together), so data placement needs no
        range awareness here; ranges refine routing PREFERENCE only —
        read targets (range_read_nodes) and plain-set write slices
        (range_write_spans) — never membership of the data."""
        override = self.placement.get(index, shard)
        if override is not None:
            with self._lock:
                nodes = [self.nodes[i] for i in override
                         if i in self.nodes]
            if len(nodes) == len(override):
                return nodes
            # a listed owner left the membership: hash placement
            # resumes for this shard until the planner re-plans
        return self.partition_nodes(self.partition(index, shard))

    def range_read_nodes(self, index: str, shard: int,
                         column_offset: int) -> list[Node] | None:
        """Preferred readers for one column offset of a range-split
        shard, or None when the shard has no (fully live) split. Every
        range owner holds the WHOLE fragment (data placement is the
        union override), so this is a routing refinement — a caller
        that ignores it still reads correct bytes from any owner."""
        spans = self.placement.get_ranges(index, shard)
        if not spans:
            return None
        for lo, hi, ids in spans:
            if lo <= column_offset < hi:
                with self._lock:
                    nodes = [self.nodes[i] for i in ids if i in self.nodes]
                if len(nodes) == len(ids):
                    return nodes
                return None  # a range owner departed: union routing
        return None

    def range_write_spans(self, index: str, shard: int
                          ) -> list[tuple[int, int, list[Node] | None]] | None:
        """Write-routing view of a shard's sub-shard column ranges:
        ``[(lo, hi, owners-or-None), ...]`` covering the adopted spans,
        or None when the shard has no split (the union/hash path). A
        span whose owner list has a departed member yields ``None``
        owners — the caller must fall back to union fan-out for columns
        in that span (anti-entropy converges the refill; a narrowed send
        to a half-live span could strand the slice). Only PLAIN SET
        writes may use this: union repair converges a non-span owner
        that missed a set, but cannot undo a clear, a mutex row move, or
        a BSI value it never saw (see cluster_exec._route_all_replicas)
        — those keep full union fan-out."""
        spans = self.placement.get_ranges(index, shard)
        if not spans:
            return None
        out: list[tuple[int, int, list[Node] | None]] = []
        with self._lock:
            for lo, hi, ids in spans:
                nodes = [self.nodes[i] for i in ids if i in self.nodes]
                out.append((lo, hi,
                            nodes if len(nodes) == len(ids) else None))
        return out

    def _shard_nodes_on(self, ring: list[Node], placement: dict,
                        index: str, shard: int) -> list[Node]:
        """shard_nodes against a FROZEN (ring, placement) snapshot —
        the cleanup walk's TOCTOU discipline extended to overrides."""
        ids = placement.get((index, int(shard)))
        if ids:
            by_id = {n.id: n for n in ring}
            nodes = [by_id[i] for i in ids if i in by_id]
            if len(nodes) == len(ids):
                return nodes
        return self._partition_nodes_on(ring, self.partition(index, shard))

    def owns_shard(self, index: str, shard: int) -> bool:
        return any(n.id == self.local.id for n in self.shard_nodes(index, shard))

    def primary_for_shard(self, index: str, shard: int) -> Node:
        return self.shard_nodes(index, shard)[0]

    def local_shards(self, index: str, shards: list[int]) -> list[int]:
        return [s for s in shards if self.owns_shard(index, s)]

    def shard_nodes_json(self, index: str, shard: int) -> list[dict]:
        return [n.to_json() for n in self.shard_nodes(index, shard)]

    # ------------------------------------------------------------ broadcast

    def _broadcast(self, message: dict, mark_degraded: bool = False) -> None:
        """Deliver a message to every peer, tolerating per-node failures
        (the one broadcast loop — send_sync/leave/state/shard announcements
        all route here so error handling can't drift between them). Each
        send retries on node faults with jittered backoff (_send_retry):
        a single dropped state broadcast would otherwise strand a peer
        in RESIZING until the straggler timeout."""
        for node in self.sorted_nodes():
            if node.id == self.local.id:
                continue
            try:
                self._send_retry(node.uri, message)
            except ClientError:
                if mark_degraded:
                    node.state = STATE_DEGRADED

    def send_sync(self, message: dict) -> None:
        """Deliver a schema delta to every peer (reference SendSync)."""
        self._broadcast(message, mark_degraded=True)

    def handle_message(self, message: dict) -> dict:
        """Apply a cluster message received from a peer (reference
        broadcastHandler).

        Epoch fencing: a FENCED message stamped with an epoch older
        than this node's is rejected unapplied — the partitioned
        ex-coordinator's un-gate/resize/cleanup commands die here. A
        newer epoch is adopted first (the wire doubles as epoch
        gossip). Messages without an epoch (older wire, bare test
        constructions) pass unfenced, same mixed-version posture as
        every other wire change."""
        kind = message.get("type")
        msg_epoch = message.get("epoch")
        if msg_epoch is not None:
            msg_epoch = int(msg_epoch)
            if msg_epoch > self.epoch:
                self.adopt_epoch(msg_epoch)
            elif msg_epoch < self.epoch and kind in FENCED_MESSAGES:
                self.stale_epoch_rejects += 1
                if self.logger is not None:
                    self.logger.info(
                        "rejecting stale-epoch %s (%d < %d) on %s",
                        kind, msg_epoch, self.epoch, self.local.id,
                    )
                return {"error": f"stale epoch {msg_epoch} "
                                 f"(current {self.epoch})",
                        "epoch": self.epoch}
        if kind == "create-index":
            if self.holder.index(message["index"]) is None:
                self.holder.create_index(
                    message["index"],
                    keys=message.get("keys", False),
                    track_existence=message.get("trackExistence", True),
                )
        elif kind == "delete-index":
            if self.holder.index(message["index"]) is not None:
                self.holder.delete_index(message["index"])
            self.forget_index(message["index"])
        elif kind == "create-field":
            from pilosa_tpu.storage import FieldOptions

            idx = self.holder.index(message["index"])
            if idx is not None and idx.field(message["field"]) is None:
                idx.create_field(
                    message["field"], FieldOptions.from_dict(message.get("options", {}))
                )
        elif kind == "delete-field":
            idx = self.holder.index(message["index"])
            if idx is not None and idx.field(message["field"]) is not None:
                idx.delete_field(message["field"])
        elif kind == "resize-cleanup":
            try:
                self.cleanup_unowned(message.get("members"),
                                     epoch=msg_epoch)
            except Exception as e:  # noqa: BLE001
                self._log_exception("post-resize holder cleanup", e)
        elif kind == "suspect-probe":
            # death corroboration: the asking coordinator suspects a
            # node; answer with THIS node's own live view of it (tight
            # timeout — the answer must arrive inside the asker's
            # heartbeat pass)
            uri = message.get("uri")
            if not uri:
                with self._lock:
                    node = self.nodes.get(message.get("id"))
                uri = node.uri if node is not None else None
            if uri is None:
                return {"reachable": False, "known": False}
            try:
                self.client.status(uri, timeout=self.heartbeat_timeout)
            except Exception:  # noqa: BLE001 — unreachable however it
                # failed; this vote corroborates the suspicion
                return {"reachable": False}
            return {"reachable": True}
        elif kind == "recalculate-caches":
            # reference RecalculateCachesMessage: each receiver recounts
            # its own fragments' TopN caches (local-only apply — the
            # originator already broadcast to every peer)
            if self.api is not None:
                self.api.recalculate_caches(remote=True)
        elif kind == "forward-query":
            # a write forwarded verbatim (attr calls); apply locally
            if self.api is not None:
                self.api.query(
                    message["index"], message["pql"], remote=True
                )
        elif kind == "node-join":
            node = Node(message["id"], message["uri"])
            with self._lock:
                known = node.id in self.nodes
                self.nodes[node.id] = node
                self._forgotten.pop(node.id, None)
                self._note_membership_changed_locked()
                relay_to = ([n for n in self.nodes.values()
                             if n.id != node.id
                             and n.id != self.local.id]
                            if not known else [])
            if relay_to:
                # Join gossip (reference: memberlist broadcasts joins).
                # A joiner announces only to the members the seed's
                # /status listed at ITS join time, so two nodes joining
                # the same seed CONCURRENTLY each adopt [seed, self] and
                # announce to the seed alone — neither ever learns the
                # other, and each serves its own asymmetric ring (reads
                # through one routes around data the other holds). On
                # first learning of a node, relay the join both ways:
                # the new member to every known member, every known
                # member to the new one. A relay of an already-known
                # node is a no-op here (known ⇒ no further relay), so
                # the wave terminates after one generation per edge.
                def _relay_join():
                    for peer in relay_to:
                        try:
                            self._send_retry(peer.uri, {
                                "type": "node-join",
                                "id": node.id, "uri": node.uri,
                            })
                        except ClientError:
                            pass
                        try:
                            self._send_retry(node.uri, {
                                "type": "node-join",
                                "id": peer.id, "uri": peer.uri,
                            })
                        except ClientError:
                            pass

                # async: this handler runs on the serving thread of the
                # announce POST — the relay fan-out must not hold it
                threading.Thread(target=_relay_join, daemon=True,
                                 name="join-relay").start()
            # membership changed ownership: the acting coordinator computes
            # per-node fetch instructions (reference ResizeInstruction)
            if self.is_acting_coordinator:
                self._spawn_resize()
        elif kind == "node-leave":
            with self._lock:
                removed = self.nodes.pop(message["id"], None)
                self._note_membership_changed_locked()
                if removed is not None:
                    # remember the uri: if this node later ends up solo
                    # (everyone amputated during a partition) it probes
                    # forgotten peers to reunite instead of serving as
                    # a split-brained 1-node cluster (dead peers just
                    # fail the probe — tracking them is harmless)
                    self._forgotten[removed.id] = removed.uri
                self._heartbeat_failures.pop(message["id"], None)
            self._drop_resize_pending(message["id"])
            if removed is not None:
                # departed-member CDC: its cursors must not pin our WAL
                self.drop_departed_cursors(message["id"])
            if self.is_acting_coordinator:
                self._spawn_resize()
        elif kind == "create-shard":
            with self._lock:
                self.known_shards.setdefault(message["index"], set()).update(
                    int(s) for s in message.get("shards", [])
                )
        elif kind == "cluster-state":
            self._command_state(message.get("state", STATE_NORMAL))
        elif kind == "resize-instruction":
            job, reply_to = message.get("job"), message.get("reply_to")
            if job is None:
                # direct form (tests/tools): fetch inline
                self.fetch_fragments(message.get("sources", []))
            else:
                # ack now, fetch in a worker: the coordinator's delivery
                # must not block on the fetch (a large move would trip
                # the client timeout, spuriously DEGRADE a healthy-but-
                # busy node, and un-gate queries mid-move). Gate BEFORE
                # spawning: if the worker took the gate itself, a node
                # whose other fetch paths just drained would be briefly
                # observable as NORMAL while the instruction fragments
                # are still missing — wait_until_normal callers then
                # query short (caught ~1-in-15 under CI load).
                self._begin_local_fetch()
                try:
                    threading.Thread(
                        target=self._run_resize_job,
                        args=(message.get("sources", []), job, reply_to,
                              True),
                        daemon=True,
                    ).start()
                except BaseException:
                    self._end_local_fetch()
                    raise
        elif kind == "resize-complete":
            with self._resize_cv:
                if message.get("job") == self._resize_job:
                    if int(message.get("fetched", 0)) < 0:
                        # the CURRENT job's peer fetch raised: it acked
                        # but is missing fragments — mark it DEGRADED
                        # BEFORE the notify wakes the coordinator, so
                        # queries can't route to it in the window between
                        # un-gating and the mark (stale reports from
                        # superseded jobs are ignored; anti-entropy
                        # repairs and the next heartbeat restores it)
                        node = self.nodes.get(message.get("node"))
                        if node is not None:
                            node.state = STATE_DEGRADED
                    self._resize_pending.discard(message.get("node"))
                    self._resize_cv.notify_all()
        elif kind == "placement-update":
            # fenced above: a healed ex-coordinator's stale table was
            # already rejected; what reaches here is current-or-newer
            self.adopt_placement(message)
        elif kind == "drain-update":
            # fenced above; (epoch, rev) ordering inside adopt_drain
            # handles same-epoch state advances
            self.adopt_drain(message.get("drain"))
        elif kind == "drain-leave":
            # the drain coordinator finished moving this node's groups:
            # leave the ring. Async — the coordinator's send must not
            # block on our departure broadcast fan-out.
            if message.get("node") == self.local.id:
                threading.Thread(target=self.leave, daemon=True,
                                 name="drain-leave").start()
        elif kind == "resize-progress":
            with self._resize_cv:
                if message.get("job") == self._resize_job:
                    # still alive and moving: push the straggler deadline
                    self._resize_deadline = (
                        time.monotonic() + self.RESIZE_COMPLETE_TIMEOUT
                    )
        else:
            return {"error": f"unknown message type {kind!r}"}
        return {}

    def note_local_shards(self, index: str, shards) -> None:
        """Announce newly-created local shards to every peer (reference
        CreateShardMessage on max-shard bump — SURVEY.md §2 #15), so remote
        queries see them immediately rather than after the catalog-poll
        TTL. Fire-and-forget: the catalog poll remains the backstop."""
        with self._lock:
            seen = self._announced_shards.setdefault(index, set())
            new = sorted(set(int(s) for s in shards) - seen)
            if not new:
                return
            seen.update(new)
            # Self-knowledge too: the shard universe is monotonic
            # cluster metadata (reference maxShard only grows), NOT a
            # reflection of local holdings. Without this, a node whose
            # post-resize cleanup deleted its formerly-local fragments
            # lost those shards from its own fan-out universe whenever
            # the peer-poll cache predated the resize — a cluster-wide
            # Count quietly skipped them (mesh join test, ~1-in-10
            # under load).
            self.known_shards.setdefault(index, set()).update(new)
        if len(self.nodes) <= 1:
            return
        message = {"type": "create-shard", "index": index, "shards": new}
        threading.Thread(
            target=self._broadcast, args=(message,), daemon=True
        ).start()

    def get_known_shards(self, index: str) -> list[int]:
        """Snapshot of peer-announced shards (copied under the lock: the
        message handler mutates the set from HTTP threads)."""
        with self._lock:
            return sorted(self.known_shards.get(index, ()))

    def forget_index(self, index: str) -> None:
        """Drop shard bookkeeping for a deleted index: stale entries would
        fan queries out to phantom shards and suppress announcements for a
        recreated index of the same name."""
        with self._lock:
            self.known_shards.pop(index, None)
            self._announced_shards.pop(index, None)

    # ------------------------------------------------------------ heartbeat

    @property
    def is_acting_coordinator(self) -> bool:
        """First NON-DEAD node in id order: coordination must fail over
        when the coordinator itself is the node that died."""
        for n in self.sorted_nodes():
            if n.state != STATE_DEGRADED:
                return n.id == self.local.id
        return True

    def heartbeat(self) -> None:
        """Liveness probe of peers (memberlist's role — SURVEY.md §2 #14).
        Probes run CONCURRENTLY with the tight dedicated
        ``heartbeat_timeout`` — a hung peer's socket must not stall the
        whole loop and delay detection of OTHER failures. After
        DEAD_HEARTBEATS consecutive failures the acting coordinator moves
        the node suspect→dead — but only with member-majority quorum AND
        ≥2 corroborating observers (all-but-self in 2-node clusters), so
        a single-observer flap (one cut link) can no longer amputate a
        live node (reference: memberlist's peer-corroborated suspect
        protocol — SURVEY.md §5.3).

        Each pass also (a) tracks quorum → the ``degraded`` read-only
        flag, (b) adopts any higher epoch a peer reports, and (c)
        detects EVICTION — a reachable peer whose member list no longer
        contains this node means the majority declared us dead while we
        were partitioned; we rejoin through it instead of split-braining
        forever."""
        with self._lock:
            peers = [n for n in self.sorted_nodes()
                     if n.id != self.local.id]
        if not peers:
            self.degraded = False
            if self._forgotten and not self._left:
                # solo after declaring everyone dead: if any forgotten
                # peer answers, the "deaths" were a partition — reunite
                self._solo_reunion()
            return

        def probe(node):
            try:
                return node, self.client.status(
                    node.uri, timeout=self.heartbeat_timeout
                )
            except ClientError:
                return node, None

        results = concurrent_map(probe, peers)
        dead: list[Node] = []
        live: list[Node] = []
        rejoin_via: dict | None = None
        for node, st in results:
            self.heartbeat_probes += 1
            if st is not None:
                live.append(node)
                node.state = STATE_NORMAL
                self._heartbeat_failures.pop(node.id, None)
                peer_epoch = int(st.get("epoch", 0) or 0)
                if peer_epoch > self.epoch:
                    self.adopt_epoch(peer_epoch)
                # placement + drain record gossip with the heartbeat: a
                # node that missed the broadcast (partitioned,
                # restarting) converges on the next probe round
                self.adopt_placement(st.get("placement"))
                self.adopt_drain(st.get("drain"))
                peer_ids = {n.get("id") for n in st.get("nodes", [])}
                if (peer_ids and self.local.id not in peer_ids
                        and (peer_epoch >= self.epoch
                             or len(peer_ids) >= len(self.nodes))
                        and rejoin_via is None):
                    # evicted while partitioned: the peer's view is at
                    # least as authoritative as ours (newer epoch, or no
                    # smaller a cluster) — surrender and rejoin through
                    # it rather than serving a split-brained ring
                    rejoin_via = st
            else:
                self.heartbeat_probe_failures += 1
                node.state = STATE_DEGRADED
                fails = self._heartbeat_failures.get(node.id, 0) + 1
                self._heartbeat_failures[node.id] = fails
                if fails >= DEAD_HEARTBEATS:
                    dead.append(node)
        n = len(peers) + 1
        self.degraded = not ((1 + len(live)) >= (n // 2 + 1) or n <= 2)
        if rejoin_via is not None and not self._left:
            self._rejoin(rejoin_via)
            return
        if self._forgotten and not self._left:
            # peers we (or a coordinator) amputated that turn out to be
            # alive were partitioned, not dead: INVITE the fully-split
            # ones back (they add us, see our view on their next probe,
            # and rejoin through it) — without this, a side that never
            # probes the forgotten node leaves it serving as a
            # split-brained cluster forever
            self._probe_forgotten()
        if dead and self.is_acting_coordinator:
            if self.degraded:
                # wanted to declare deaths but holds no quorum: the
                # minority side of a partition observing exactly the
                # blast radius the gate exists to stop
                self.quorum_denials += 1
                return
            for node in dead:
                if self._death_corroborated(node, live):
                    self.declare_dead(node.id)
                else:
                    # suspect stays DEGRADED (unrouted) but keeps its
                    # membership: a one-link flap must not amputate it
                    self.deaths_vetoed += 1

    def _death_corroborated(self, suspect: Node, live_peers: list[Node]
                            ) -> bool:
        """suspect→dead needs ≥2 observers: this node's failed probes
        plus at least one live peer that ALSO cannot reach the suspect
        right now (suspect-probe message → the peer runs its own
        tight-timeout probe). With no other live peer — a 2-node
        cluster — all-but-self is just this node and the single
        observation stands (check_quorum documents the 2-node
        tradeoff); in larger clusters a coordinator that can reach no
        corroborator has no business declaring deaths (the quorum gate
        already vetoes that, belt and braces)."""
        others = [p for p in live_peers if p.id != suspect.id]
        if not others:
            return len(self.nodes) <= 2

        def ask(peer):
            try:
                out = self.client.send_message(peer.uri, {
                    "type": "suspect-probe", "id": suspect.id,
                    "uri": suspect.uri,
                })
            except ClientError:
                return False
            return out.get("reachable") is False

        return any(concurrent_map(ask, others))

    def declare_dead(self, node_id: str) -> bool:
        """Remove a dead node and re-replicate its shards: broadcast the
        departure (epoch-stamped), then send per-node resize
        instructions. QUORUM-GATED: a minority-side node must not
        amputate members it merely cannot see — under an asymmetric
        partition both sides would otherwise each declare the other
        dead and resize against disjoint rings. Returns False when
        vetoed (no quorum / unknown node)."""
        with self._lock:
            known = node_id in self.nodes
            n_members = len(self.nodes)
        if not known:
            return False
        if n_members > 2 and not self.check_quorum():
            if self.logger is not None:
                self.logger.info(
                    "refusing to declare %s dead: no member quorum on %s",
                    node_id, self.local.id,
                )
            return False
        faults.crash_point("cluster.pre-declare-dead")
        epoch = self._bump_epoch()
        with self._lock:
            node = self.nodes.pop(node_id, None)
            if node is None:
                return False
            self._note_membership_changed_locked()
            self._forgotten[node_id] = node.uri
            self._heartbeat_failures.pop(node_id, None)
        self.deaths_declared += 1
        self._note_acted(epoch, f"declare-dead:{node_id}")
        self._drop_resize_pending(node_id)
        # a declared-dead member's CDC cursors must not pin retention
        self.drop_departed_cursors(node_id)
        for node in self.sorted_nodes():
            if node.id == self.local.id:
                continue
            try:
                self._send_retry(node.uri, {
                    "type": "node-leave", "id": node_id, "epoch": epoch,
                })
            except ClientError:
                pass
        self.coordinate_resize()
        return True

    def _probe_forgotten(self) -> None:
        """Tight-timeout probes of declared-dead peers. A reachable one
        whose member list no longer names US gets a node-join invite:
        it adds us, its next heartbeat sees our (no-smaller, no-older)
        view lacking it, and it rejoins through us. One message; safe —
        a genuinely removed node either stays unreachable (probe fails)
        or deliberately left (its _left latch refuses auto-rejoin)."""
        def one(item):
            node_id, node_uri = item
            try:
                st = self.client.status(node_uri,
                                        timeout=self.heartbeat_timeout)
            except Exception:  # noqa: BLE001 — still gone
                return
            peer_ids = {n.get("id") for n in st.get("nodes", [])}
            if self.local.id in peer_ids:
                return  # it still knows us: its own probes reconcile
            try:
                self._send_retry(node_uri, {
                    "type": "node-join", "id": self.local.id,
                    "uri": self.local.uri,
                })
            except ClientError:
                pass

        concurrent_map(one, list(self._forgotten.items()))

    def _solo_reunion(self) -> None:
        """A 1-node 'cluster' probing the peers it declared dead: a
        reachable one means the declarations were really a partition.
        Merge memberships (only ADDING — there is nobody left to evict)
        and announce ourselves so both sides' coordinators reconcile;
        data differences heal through anti-entropy's stray-copy
        absorption. Without this, a symmetric 2-way amputation leaves
        two 1-node clusters serving forever."""
        for node_id, node_uri in list(self._forgotten.items()):
            try:
                st = self.client.status(node_uri,
                                        timeout=self.heartbeat_timeout)
            except Exception:  # noqa: BLE001 — still unreachable
                continue
            if self.logger is not None:
                self.logger.info(
                    "%s rediscovered %s after a partition; reuniting",
                    self.local.id, node_id,
                )
            self.rejoins += 1
            with self._lock:
                self.nodes[node_id] = Node(node_id, node_uri)
                for n in st.get("nodes", []):
                    if n.get("id") and n["id"] not in self.nodes:
                        self.nodes[n["id"]] = Node(n["id"], n["uri"])
                self._forgotten.clear()
                self._note_membership_changed_locked()
            self.adopt_epoch(int(st.get("epoch", 0) or 0))
            self.adopt_placement(st.get("placement"))
            self.adopt_drain(st.get("drain"))
            for node in self.sorted_nodes():
                if node.id == self.local.id:
                    continue
                try:
                    self._send_retry(node.uri, {
                        "type": "node-join", "id": self.local.id,
                        "uri": self.local.uri,
                    })
                except ClientError:
                    pass
            if self.is_acting_coordinator:
                self._spawn_resize()
            return

    def _rejoin(self, via_status: dict) -> None:
        """This node was evicted while partitioned (a reachable peer's
        member list no longer contains it): adopt the majority's
        membership + epoch, announce ourselves (the coordinator's
        node-join resize re-replicates toward us), and run the gated
        self-join fetch so the stale window is repaired before the
        query gate releases. Without this, a healed partition leaves
        the evicted side split-brained forever — each side serving its
        own ring."""
        if not self._rejoin_lock.acquire(blocking=False):
            return  # one rejoin at a time
        try:
            if self.logger is not None:
                self.logger.info(
                    "%s was evicted while partitioned; rejoining the "
                    "majority", self.local.id,
                )
            self.rejoins += 1
            with self._lock:
                replacement = {self.local.id: self.local}
                for n in via_status.get("nodes", []):
                    if n.get("id") and n["id"] != self.local.id:
                        replacement[n["id"]] = Node(n["id"], n["uri"])
                # members the adoption DROPS go to the forgotten
                # registry: if the majority's view is itself missing a
                # live node (cascading partitions), someone must still
                # probe-and-invite it back — a silently dropped member
                # is how split-brained 1-node clusters wedge forever
                dropped = {
                    node_id: node.uri
                    for node_id, node in self.nodes.items()
                    if node_id not in replacement
                }
                self.nodes = replacement
                self._heartbeat_failures.clear()
                self._forgotten = dropped
                self._note_membership_changed_locked()
            self.adopt_epoch(int(via_status.get("epoch", 0) or 0))
            self.adopt_placement(via_status.get("placement"))
            self.adopt_drain(via_status.get("drain"))
            self.degraded = False
            for node in self.sorted_nodes():
                if node.id == self.local.id:
                    continue
                try:
                    self._send_retry(node.uri, {
                        "type": "node-join", "id": self.local.id,
                        "uri": self.local.uri,
                    })
                except ClientError:
                    pass
            self.resize_fetch_async()
        finally:
            self._rejoin_lock.release()

    # ----------------------------------------------------------- join/resize

    def join(self, seed_uri: str) -> None:
        """Join an existing cluster via any seed node: announce ourselves,
        adopt the member list + schema, then fetch owned fragments
        (reference: memberlist join + coordinator ResizeInstructions —
        SURVEY.md §3.5)."""
        status = self.client.status(seed_uri)
        with self._lock:
            for n in status.get("nodes", []):
                self.nodes[n["id"]] = Node(n["id"], n["uri"])
            self._note_membership_changed_locked()
        # adopt the cluster's epoch before announcing: a node that
        # rejoins after an eviction must not carry a pre-partition epoch
        # into its first broadcasts
        self.adopt_epoch(int(status.get("epoch", 0) or 0))
        # the placement table rides the same status payload: a joiner
        # must compute the SAME ownership as the members from its first
        # resize-instruction onward; the drain record rides along so a
        # joiner can immediately act as a failover drain coordinator
        self.adopt_placement(status.get("placement"))
        self.adopt_drain(status.get("drain"))
        # Gate BEFORE announcing: the announce triggers the coordinator's
        # resize, whose post-resize cleanup waits for every member to
        # drain to NORMAL — this node must never be observable as NORMAL
        # in the window between its instruction-job finishing and its
        # self-join inventory fetch starting, or the cleanup could delete
        # the very fragments that fetch is about to pull.
        self._begin_local_fetch()
        try:
            # announce to everyone (including seed); retried — a missed
            # join announcement leaves a peer routing around this node
            # until the next catalog poll
            for node in self.sorted_nodes():
                if node.id == self.local.id:
                    continue
                try:
                    self._send_retry(
                        node.uri,
                        {"type": "node-join", "id": self.local.id,
                         "uri": self.local.uri},
                    )
                except ClientError:
                    pass
            # adopt schema from the seed
            schema = self.client.schema(seed_uri)
            for idx_schema in schema.get("indexes", []):
                self.handle_message(
                    {
                        "type": "create-index",
                        "index": idx_schema["name"],
                        **idx_schema.get("options", {}),
                    }
                )
                for f in idx_schema.get("fields", []):
                    self.handle_message(
                        {
                            "type": "create-field",
                            "index": idx_schema["name"],
                            "field": f["name"],
                            "options": f.get("options", {}),
                        }
                    )
            self.resize_fetch_async(pre_gated=True)
        except BaseException:
            self._end_local_fetch()
            raise

    def resize_fetch_async(self, pre_gated: bool = False) -> threading.Thread:
        """Self-join fetch as a background job — the async pattern the
        instruction-driven resize path uses (_run_resize_job): the joiner
        flips to RESIZING immediately (queries gate on wait_until_normal)
        and returns, so Server.open completes and the node answers
        /status and cluster messages while fragments stream in
        concurrently. Unlike the instruction path, no keepalives are
        sent: this is the pull-based fallback — no coordinator is
        awaiting a completion report, and progress is observable as
        state=RESIZING in /status. ``pre_gated``: the caller already
        holds the local-fetch gate (join() gates before announcing) and
        hands it to the fetch thread — exactly one begin per end."""
        if not pre_gated:
            self._begin_local_fetch()  # gate queries before returning
        t = threading.Thread(target=self._resize_fetch_gated, daemon=True,
                             name="self-join-fetch")
        try:
            t.start()
        except BaseException:
            # the thread never ran, so the gate would never drain and
            # the node would sit RESIZING forever. pre_gated: the
            # CALLER's exception handler releases its own begin — ending
            # here too would double-decrement and un-gate a later fetch
            if not pre_gated:
                self._end_local_fetch()
            raise
        return t

    def _peer_fragment_entries(self, index_name: str, peers=None):
        """(field, view, shard, source node) for every fragment any peer
        holds of one index — shared by resize fetches and the anti-entropy
        inventory walk. Peers are polled CONCURRENTLY (reference: one
        goroutine per node in cross-node walks — SURVEY.md §2 #12), so
        the walk costs the slowest peer's RTT, not the sum; an
        unreachable peer contributes nothing. ``peers`` restricts the
        walk (the fast-path sync only catalogs old-wire peers this way —
        manifests carry the catalog for everyone else)."""
        if peers is None:
            peers = [n for n in self.sorted_nodes()
                     if n.id != self.local.id]

        def one(node):
            try:
                catalog = self.client.fragment_catalog(node.uri, index_name)
            except ClientError:
                return []
            return [(e["field"], e["view"], e["shard"], node)
                    for e in catalog]

        return [e for chunk in concurrent_map(one, peers) for e in chunk]

    def _peer_entries_by_index(self) -> dict[str, list]:
        """One concurrent catalog walk per index, shared by the self-join
        inventory and the gated freshness sync (one walk, two consumers)."""
        return {
            name: self._peer_fragment_entries(name)
            for name in list(self.holder.indexes)
        }

    def _owned_missing_sources(self, peer_entries: dict | None = None) -> list[dict]:
        """Fetch-instruction list for every fragment this node owns but
        does not hold locally (the self-join inventory). One FETCH per
        fragment: with replicaN>1 the peer walk reports the same
        (field, view, shard) once per replica holding it, and fetching a
        full payload per replica would multiply join transfer — so extra
        replicas become ``fallbacks`` that fetch_fragments tries only if
        the first source errors. Fragments already present locally WITH
        DATA are left to anti-entropy's block diff instead of a redundant
        full fetch; an empty local fragment is re-fetched (it may be the
        placeholder of an earlier failed fetch, which must not mask the
        repair)."""
        if peer_entries is None:
            peer_entries = self._peer_entries_by_index()
        sources = []
        # key -> source dict, or None for a key already evaluated and
        # skipped (so replicaN>1 doesn't re-resolve/count per replica)
        by_key: dict[tuple, dict | None] = {}
        for index_name, idx in list(self.holder.indexes.items()):
            for fname, vname, shard, node in peer_entries.get(index_name, []):
                key = (index_name, fname, vname, shard)
                if key in by_key:
                    prior = by_key[key]
                    if prior is not None:
                        prior["fallbacks"].append(node.uri)
                    continue
                if not self.owns_shard(index_name, shard):
                    by_key[key] = None
                    continue
                field = idx.field(fname)
                view = field.view(vname) if field is not None else None
                frag = view.fragment(shard) if view is not None else None
                if frag is not None and frag.count() > 0:
                    by_key[key] = None  # already held locally with data
                    continue
                src = {
                    "index": index_name, "field": fname, "view": vname,
                    "shard": shard, "from": node.uri, "fallbacks": [],
                }
                by_key[key] = src
                sources.append(src)
        return sources

    def resize_fetch(self) -> None:
        """Synchronous form of the self-join fetch (tests/tools): run the
        background job and wait for it. Same error behavior as the async
        path — failures are logged and left to anti-entropy, not raised."""
        self.resize_fetch_async().join()

    def _resize_fetch_gated(self) -> None:
        """The fetch body, with the local-fetch gate already held;
        always releases it. A failure is logged loudly (the async join
        path has no caller to raise to) and leaves the gap to
        anti-entropy repair.

        Join absorption (elastic plane): the inventory fetch is ordered
        HOTTEST SHARD FIRST from the cluster heatmap — a joiner starts
        holding the shards that matter to the serving tail instead of a
        hash-random order — and every fetched fragment is byte-verified
        (block checksums vs its source) before it may skip the
        follow-on freshness diff. An unverified copy stays in the
        diff's work list, so the query gate never releases a fragment
        whose bytes were not either verified or block-diff repaired —
        reads for a shard serve only once its copy is byte-verified
        (the gate holds the whole node in RESIZING throughout)."""
        try:
            peer_entries = self._peer_entries_by_index()
            sources = self._owned_missing_sources(peer_entries)
            if len(sources) > 1:
                heat = self._cluster_shard_heat()
                if heat:
                    sources.sort(
                        key=lambda s: heat.get(
                            (s["index"], int(s["shard"])), 0.0),
                        reverse=True,
                    )
                    self.warm_heat_ordered += len(sources)
            self.fetch_fragments(sources)
            verified = self._verify_fetched(sources)
            # Freshness: fragments we ALREADY held may be stale from an
            # outage window (writes landed on replicas while this node
            # was away). Block-diff them against replicas before the
            # gate releases, so a rejoining node never serves the stale
            # window — the full fetch above covers only missing
            # fragments (the byte-verified ones skip here), a
            # checksum-block diff is far cheaper than re-downloading
            # every held payload, and the peer catalog walk is shared
            # with the inventory above.
            self.sync_holder(peer_entries=peer_entries, skip=verified)
        except Exception as e:  # noqa: BLE001 — must not die silently
            self._log_exception("self-join fragment fetch", e)
        finally:
            self._end_local_fetch()

    def _cluster_shard_heat(self) -> dict:
        """(index, shard) → heat merged from every reachable peer's
        heatmap — the join-absorption warm order. Best-effort: an
        unreachable peer (or a peer whose wire predates the heatmap
        route) contributes nothing, and an empty result leaves the
        fetch in catalog order."""
        peers = [n for n in self.sorted_nodes() if n.id != self.local.id]
        if not peers:
            return {}
        try:
            from pilosa_tpu.storage.heat import merge_shard_heat
        except Exception:  # noqa: BLE001 — heat plane absent
            return {}

        def one(node):
            try:
                return self.client.heatmap(
                    node.uri, timeout=self.heartbeat_timeout,
                ).get("shards", [])
            except Exception:  # noqa: BLE001 — old wire / unreachable
                return []

        try:
            return merge_shard_heat(concurrent_map(one, peers))
        except Exception:  # noqa: BLE001 — malformed rows must not
            return {}      # fail the join fetch

    def _verify_fetched(self, sources: list[dict]) -> set:
        """Byte-verify freshly fetched fragments against their primary
        source: a fragment whose 100-row block checksums match is
        warm-verified and may skip the follow-on freshness diff; a
        mismatch (the source advanced mid-fetch, a torn transfer, a
        fallback source supplied the bytes) or an unreachable source
        keeps the fragment IN the diff, which repairs it block-by-block
        before the gate releases."""
        verified: set = set()
        for src in sources:
            key = (src["index"], src["field"], src["view"], src["shard"])
            idx = self.holder.index(src["index"])
            field = idx.field(src["field"]) if idx else None
            view = field.view(src["view"]) if field is not None else None
            frag = (view.fragment(int(src["shard"]))
                    if view is not None else None)
            local_blocks = dict(frag.blocks()) if frag is not None else {}
            try:
                peer_blocks = dict(self.client.fragment_blocks(
                    src["from"], src["index"], src["field"], src["view"],
                    int(src["shard"]),
                ))
            except ClientError:
                self.warm_verify_failed += 1
                continue  # unverifiable: leave it to the freshness diff
            if local_blocks == peer_blocks:
                verified.add(key)
                self.warm_verified += 1
            else:
                self.warm_verify_failed += 1
        return verified

    def fetch_fragments(self, sources: list[dict]) -> int:
        """Execute the receiving half of resize instructions: fetch and
        union each listed fragment from its source node, with the HTTP
        fetches running concurrently. Fragment objects are resolved (and
        created) serially first — view.fragment(create=True) must not be
        raced for one (view, shard) — and the per-fragment union runs
        under each fragment's own lock.

        A joiner runs TWO overlapping fetch paths (its own inventory
        fetch and the coordinator's resize instruction), which can both
        transfer a fragment when their timing overlaps. That redundancy
        is DELIBERATE: the union is idempotent, and each path covers the
        other's failure modes (the instruction job can arrive before
        schema adoption and fetch nothing; the inventory can race a
        source's cleanup). An earlier claims registry that deduplicated
        them converted a failed instruction fetch into a permanent gap —
        the skipped inventory pass was the safety net.

        A fragment created here solely to receive the move is REMOVED
        again when every source failed to supply data and nothing else
        has written to it: an empty placeholder would otherwise (a)
        serve silently-empty reads for a shard whose data exists
        elsewhere and (b) mask the gap from the self-join inventory's
        "already held locally" check — the other half of the
        resize-source race (the receiver was left holding an empty
        fragment when its last usable source disappeared mid-move)."""
        work = []
        created: list[tuple] = []
        for src in sources:
            idx = self.holder.index(src["index"])
            field = idx.field(src["field"]) if idx else None
            if field is None:
                continue
            view = field.view(src["view"], create=True)
            existed = view.fragment(int(src["shard"])) is not None
            frag = view.fragment(int(src["shard"]), create=True)
            if not existed:
                created.append((view, int(src["shard"]), frag))
            work.append((src, frag))

        from pilosa_tpu.roaring.format import load_any
        from pilosa_tpu.utils.stats import global_stats

        probe_blocks = getattr(self.client, "fragment_blocks", None)

        def one(item):
            src, frag = item
            for source_uri in [src["from"], *src.get("fallbacks", [])]:
                # Block-checksum probe first (ADVICE r4 #4): a
                # legitimately-empty fragment — advertised by the peer
                # catalog but holding no bits — would otherwise be
                # re-fetched as a full payload from EVERY replica on
                # every self-join/resize pass (the empty-payload check
                # below only fires after the download). The blocks list
                # is O(checksum rows), so an empty source costs one tiny
                # control response instead of a data-plane transfer.
                if probe_blocks is not None:
                    try:
                        if not probe_blocks(
                            source_uri, src["index"], src["field"],
                            src["view"], int(src["shard"]),
                        ):
                            global_stats().count(
                                "sync_empty_fetches_skipped", 1
                            )
                            continue  # source holds no data: next replica
                    except ClientError:
                        continue  # unreachable for the probe: data fetch
                                  # would fail the same way
                try:
                    data = self.client.fragment_data(
                        source_uri, src["index"], src["field"], src["view"],
                        int(src["shard"]),
                    )
                except ClientError:
                    continue  # replica fallback: try the next holder
                if not data:
                    continue  # source lacks the fragment; try a replica
                try:
                    bitmap, _ = load_any(data)
                except Exception:
                    # torn/corrupt payload (e.g. a snapshot mid-write on
                    # the source) must not abort the batch — a healthy
                    # replica may hold good data for this fragment
                    continue
                if bitmap.count() == 0:
                    # an EMPTY payload may be the placeholder of the
                    # source's own failed fetch — keep trying replicas
                    # rather than declaring the move done with no data
                    continue
                frag.import_roaring_bitmap(bitmap)
                return 1
            return 0  # no replica holds data (or all are unreachable)

        fetched = sum(concurrent_map(one, work))
        for view, shard, frag in created:
            # drop placeholders that never received data; a write that
            # landed concurrently bumped count() and keeps the fragment
            # (the identity check guards against a racing re-create)
            if frag.count() == 0 and view.fragment(shard) is frag:
                view.remove_fragments([shard])
        return fetched

    # Seconds between resize-progress keepalives while a fetch runs.
    RESIZE_PROGRESS_INTERVAL = 10.0

    def _run_resize_job(self, sources: list[dict], job: str,
                        reply_to: str | None,
                        pre_gated: bool = False) -> None:
        """Receiver worker for an async resize instruction: fetch, with a
        timer thread sending progress keepalives for as long as the fetch
        runs — wall-clock-based, not per-fragment, so one huge fragment
        cannot outlast the coordinator's quiet deadline silently — then
        report completion (reference resize-job pattern — nodes fetch
        asynchronously and report, SURVEY.md §3.5). ``pre_gated``: the
        message handler already holds the local-fetch gate (taken before
        spawning this worker) and hands it over — exactly one begin per
        the finally's end."""
        done = threading.Event()

        def keepalive() -> None:
            while not done.wait(self.RESIZE_PROGRESS_INTERVAL):
                try:
                    self.client.send_message(reply_to, {
                        "type": "resize-progress", "job": job,
                        "node": self.local.id,
                    })
                except ClientError:
                    pass

        if not pre_gated:
            self._begin_local_fetch()
        ka = None
        try:
            # keepalive start is INSIDE the gate's try: a thread-spawn
            # failure here must still release the handed-over gate, or
            # the node wedges RESIZING forever
            if reply_to:
                ka = threading.Thread(target=keepalive, daemon=True)
                ka.start()
            fetched = self.fetch_fragments(sources)
        except Exception as e:
            self._log_exception("resize-instruction fetch", e)
            fetched = -1  # report anyway: the coordinator must not wait
        finally:
            self._end_local_fetch()
            done.set()
        if ka is not None:
            ka.join(timeout=5)
        if reply_to:
            try:
                # retried: a single dropped completion report would hold
                # the cluster RESIZING for the full straggler timeout
                self._send_retry(reply_to, {
                    "type": "resize-complete", "job": job,
                    "node": self.local.id, "fetched": fetched,
                })
            except ClientError:
                pass  # coordinator's straggler timeout covers lost acks

    def _spawn_resize(self) -> None:
        threading.Thread(target=self.coordinate_resize, daemon=True).start()

    def coordinate_resize(self) -> dict:
        """Coordinator-computed resize (reference ResizeInstruction —
        SURVEY.md §2 #13, §3.5): gather the cluster-wide fragment catalog,
        compute which fragments each owner is missing and a live source
        for each, gate queries cluster-wide (RESIZING), send every node
        its instruction list, then return the cluster to NORMAL.

        Runs are serialized: an overlapping run's NORMAL broadcast must
        not un-gate queries while another run is still moving fragments.
        """
        with self._resize_lock:
            return self._coordinate_resize_locked()

    def _coordinate_resize_locked(self) -> dict:
        if not self.is_acting_coordinator:
            return {}
        with self._lock:
            n_members = len(self.nodes)
        if n_members == 1:
            # a 1-node "cluster" has nothing to move, nobody to fence,
            # and — crucially — no business MINTING epochs: a node that
            # amputated its peers during a partition must not out-mint
            # the real majority, or the rejoin direction (lower epoch
            # surrenders) inverts and the majority would shatter itself
            self._command_state(STATE_NORMAL)
            return {}
        if not self.check_quorum():
            # minority side of a partition: degrade to serving locally-
            # owned reads instead of resizing against a minority view of
            # ownership — the pre-gate code's cleanup then deleted sole
            # surviving copies by that view (the data-loss scenario the
            # failure model in docs/OPERATIONS.md walks through)
            if self.logger is not None:
                self.logger.info(
                    "refusing to coordinate resize on %s: no member "
                    "quorum (cluster degraded)", self.local.id,
                )
            return {}
        # check_quorum adopted the reachable maximum, so this epoch
        # fences above every command the previous coordinator minted
        epoch = self._bump_epoch()
        self._note_acted(epoch, "resize")
        # fragment → holders (node ids), from local + peer catalogs
        holders: dict[tuple, list[Node]] = {}
        for index_name, idx in list(self.holder.indexes.items()):
            for field_name, field in list(idx.fields.items()):
                for view_name, view in list(field.views.items()):
                    for shard in list(view.fragments):
                        holders.setdefault(
                            (index_name, field_name, view_name, shard), []
                        ).append(self.local)
            for f, v, s, node in self._peer_fragment_entries(index_name):
                holders.setdefault((index_name, f, v, s), []).append(node)
        instructions: dict[str, list[dict]] = {}
        for (index_name, f, v, s), have in holders.items():
            have_ids = {n.id for n in have}
            live_sources = [n for n in have if n.state != STATE_DEGRADED]
            if not live_sources:
                continue
            owners = self.shard_nodes(index_name, s)
            owner_ids = {n.id for n in owners}
            for owner in owners:
                if owner.state == STATE_DEGRADED or owner.id in have_ids:
                    continue
                usable = [n for n in live_sources if n.id != owner.id]
                if not usable:
                    continue
                # extra live holders ride along as fallbacks, tried by
                # the receiver when the primary source errors mid-move
                # (fetch_fragments) — same contract as the self-join
                # inventory. OWNERS FIRST: a holder that remains an
                # owner keeps its copy, while a non-owner's copy is
                # deleted by this very resize's cleanup — a receiver
                # whose fetch races that cleanup loses its source (the
                # ~1-in-12 resize-source flake)
                usable.sort(key=lambda n: (n.id not in owner_ids, n.id))
                instructions.setdefault(owner.id, []).append({
                    "index": index_name, "field": f, "view": v, "shard": s,
                    "from": usable[0].uri,
                    "fallbacks": [n.uri for n in usable[1:]],
                })
        if not instructions:
            # A coordinator can die between broadcasting RESIZING and
            # NORMAL; if the failover coordinator then finds nothing to
            # move (e.g. replica_n == 1 left no live source) it must still
            # un-gate peers or every query fails with "cluster is
            # resizing" forever. Unconditional (not gated on local state):
            # the dying coordinator's RESIZING broadcast may have missed
            # THIS node while reaching others — idempotent and serialized
            # under _resize_lock, so always safe.
            self._broadcast_state(STATE_NORMAL, epoch)
            # a leave can complete with nothing to move (survivors
            # already hold everything) yet still change ownership —
            # non-owned leftovers must go now, not at the next resize
            self._broadcast_cleanup(epoch)
            return {}
        job = uuid.uuid4().hex
        with self._resize_cv:
            self._resize_job = job
            self._resize_pending = set()
            self._resize_deadline = (
                time.monotonic() + self.RESIZE_COMPLETE_TIMEOUT
            )
        self._broadcast_state(STATE_RESIZING, epoch)
        faults.crash_point("cluster.post-resizing-broadcast")
        try:
            local_sources = None
            for node_id, sources in instructions.items():
                if node_id == self.local.id:
                    local_sources = sources  # after the sends: peers
                    continue                 # fetch concurrently with us
                node = self.nodes.get(node_id)
                if node is None:
                    continue
                with self._resize_cv:
                    self._resize_pending.add(node_id)
                try:
                    self._send_retry(
                        node.uri,
                        {"type": "resize-instruction", "sources": sources,
                         "job": job, "reply_to": self.local.uri,
                         "epoch": epoch},
                    )
                except ClientError:
                    # failing the quick ack IS a health signal (unlike a
                    # long fetch, which no longer holds this request open)
                    node.state = STATE_DEGRADED
                    with self._resize_cv:
                        self._resize_pending.discard(node_id)
            if local_sources is not None:
                self.fetch_fragments(local_sources)
            # hold RESIZING (queries stay gated) until every peer reports
            # its fetch done. The deadline distinguishes dead from slow:
            # peers send resize-progress keepalives per fetched fragment,
            # each pushing the deadline out — a large move stays gated to
            # completion, while a silent straggler (died mid-fetch) is
            # released to anti-entropy repair after one quiet timeout.
            with self._resize_cv:
                while self._resize_pending:
                    remaining = self._resize_deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._resize_cv.wait(remaining)
        finally:
            with self._resize_cv:
                self._resize_job = None
                self._resize_pending = set()
            self._broadcast_state(STATE_NORMAL, epoch)
            self._broadcast_cleanup(epoch)
        return instructions

    def _broadcast_state(self, state: str, epoch: int | None = None) -> None:
        # sent to EVERY node, including ones marked DEGRADED mid-resize: a
        # node that received RESIZING but is skipped for NORMAL would stay
        # gated forever (queries time out with "cluster is resizing");
        # epoch-stamped so a healed ex-coordinator's stale un-gate (or
        # re-gate) commands are rejected by everyone current
        self._command_state(state)
        message = {"type": "cluster-state", "state": state}
        if epoch is not None:
            message["epoch"] = epoch
        self._broadcast(message)

    def leave(self) -> None:
        """Graceful departure: announce node-leave so peers re-own our
        shards (they repair from replicas; with replica_n == 1 data must be
        drained beforehand — same caveat as the reference)."""
        self._left = True  # never auto-rejoin after a deliberate exit
        for node in self.sorted_nodes():
            if node.id == self.local.id:
                continue
            try:
                self._send_retry(
                    node.uri, {"type": "node-leave", "id": self.local.id}
                )
            except ClientError:
                pass

    # ----------------------------------------------------- translate tailing

    def sync_translate(self) -> int:
        """Replica side of key-translation replication: tail the
        coordinator's append log from our current offset (reference
        translate.go Reader — SURVEY.md §2 #9)."""
        if self.is_coordinator or self.holder.translate is None:
            return 0
        coord = self.coordinator
        try:
            data = self.client.translate_log(coord.uri, self._translate_offset)
        except ClientError:
            return 0
        if not data:
            return 0
        applied = self.holder.translate.apply_log(data)
        self._translate_offset += len(data)
        return applied

    # --------------------------------------------------------- anti-entropy

    def sync_holder(self, peer_entries: dict | None = None,
                    skip: set | None = None) -> dict:
        """One anti-entropy pass over every fragment this node replicates
        (reference HolderSyncer.SyncHolder — SURVEY.md §3.5). Returns
        repair counts for observability. ``peer_entries`` reuses an
        already-gathered catalog walk; ``skip`` excludes fragments just
        fetched in full (the gated self-join path uses both).

        Fast path (docs/OPERATIONS.md): per index, ONE batched manifest
        per peer replaces the per-fragment blocks GET storm (and the
        catalog walk — the manifest carries the peer's inventory), and
        the owned fragments then diff/fetch/apply as a bounded pipeline
        (``sync_workers`` wide), so the pass costs the slowest peer, not
        the sum over fragments. Differing blocks move as one multi-block
        delta POST per (fragment, peer). Peers whose wire predates the
        sync routes (404 once) fall back per-peer to the r5 per-fragment
        path; post-repair state is byte-identical either way, and the
        mutex/bool/BSI conflict-aware merge rules are unchanged.

        A sampled pass (trace-sample-rate) roots a ``sync.pass`` trace:
        per-peer manifest and delta spans nest under it and each peer's
        serving-side span lands in that peer's local /debug/traces under
        the propagated trace id (docs/OBSERVABILITY.md)."""
        from pilosa_tpu.utils.tracing import global_tracer

        with global_tracer().root_span("sync.pass"):
            return self._sync_holder_pass(peer_entries, skip)

    def _sync_holder_pass(self, peer_entries: dict | None = None,
                          skip: set | None = None) -> dict:
        from pilosa_tpu.utils.stats import global_stats

        t0 = time.perf_counter()
        repaired = {"fragments": 0, "bits": 0, "attr_blocks": 0}
        repaired["translate_ops"] = self.sync_translate()
        repaired["attr_blocks"] = self._sync_attrs()
        for index_name, idx in list(self.holder.indexes.items()):
            peers = [n for n in self.sorted_nodes()
                     if n.id != self.local.id]
            manifests = (self._peer_sync_manifests(index_name, peers)
                         if peers else {})
            # Inventory = local fragments ∪ peers' holdings: a replica
            # that never materialized an owned fragment must still
            # repair it (the reference syncer walks the schema ×
            # max-shard space, not just local files — SURVEY.md §3.5).
            # Manifests double as the peer catalog; only old-wire peers
            # still cost a catalog GET.
            inventory = set()
            for field_name, field in list(idx.fields.items()):
                for view_name, view in list(field.views.items()):
                    for shard in list(view.fragments):
                        inventory.add((field_name, view_name, shard))
            for m in manifests.values():
                if isinstance(m, dict):
                    inventory.update(m.keys())
            legacy_peers = [n for n in peers
                            if manifests.get(n.id) == "legacy"]
            if peer_entries is not None:
                inventory.update(
                    (f, v, s)
                    for f, v, s, _ in peer_entries.get(index_name, [])
                )
            elif legacy_peers:
                inventory.update(
                    (f, v, s) for f, v, s, _ in
                    self._peer_fragment_entries(index_name, legacy_peers)
                )
            work = []
            for key in sorted(inventory):
                field_name, view_name, shard = key
                if skip and (index_name, *key) in skip:
                    continue
                if not self.owns_shard(index_name, shard):
                    continue
                if idx.field(field_name) is None:
                    continue
                work.append(key)
            results = concurrent_map(
                lambda key: self._sync_fragment(index_name, idx, key,
                                                manifests),
                work, max_workers=max(1, self.sync_workers),
                return_exceptions=True,
            )
            for key, result in zip(work, results):
                if isinstance(result, Exception):
                    self._log_exception(
                        f"anti-entropy sync of {index_name}/{key}", result
                    )
                    continue
                repaired["fragments"] += result[0]
                repaired["bits"] += result[1]
        global_stats().timing("sync_pass", time.perf_counter() - t0)
        return repaired

    def _peer_sync_manifests(self, index_name: str, peers) -> dict:
        """Concurrently fetch one batched sync manifest per peer. Values:
        a ``{(field, view, shard): {block: checksum}}`` dict for peers
        that answered, the string ``"legacy"`` for peers without the
        route (repair falls back to per-fragment GETs against them), or
        None for peers unreachable this pass (skipped — their fragment
        GETs would fail identically, so nothing is lost but the RTTs)."""
        def one(node):
            if not self.client.supports_sync_manifest(node.uri):
                return node.id, "legacy"
            from pilosa_tpu.utils.tracing import global_tracer

            try:
                # sync.manifest span + X-Pilosa-Trace on the hop when a
                # sampled sync pass is active (sync_holder roots it);
                # the kwarg rides only when sampled so client doubles
                # predating it keep working on the untraced path
                with global_tracer().span("sync.manifest",
                                          node=node.id) as span:
                    kw = ({"trace": span.header_value()}
                          if span is not None else {})
                    entries = self.client.sync_manifest(
                        node.uri, index_name, **kw,
                    )
            except ClientError:
                if not self.client.supports_sync_manifest(node.uri):
                    return node.id, "legacy"  # 404/405: old wire
                return node.id, None  # transport fault: skip this pass
            except Exception as e:  # noqa: BLE001 — a malformed 200
                # (truncated body, undecodable protobuf) from ONE peer
                # must not abort the whole pass against every peer; the
                # per-fragment blast radius the old loop had is the bar
                self._log_exception(
                    f"sync manifest from {node.id}", e
                )
                return node.id, None
            return node.id, {
                (f, v, s): dict(blocks) for f, v, s, blocks in entries
            }

        return dict(concurrent_map(one, peers))

    def _sync_fragment(self, index_name: str, idx, key, manifests
                       ) -> tuple[int, int]:
        """Diff/fetch/apply one owned fragment against its replicas (one
        pipeline work item). Returns (blocks-with-adds, bits-added) —
        the same counting the serial pass reported."""
        field_name, view_name, shard = key
        field = idx.field(field_name)
        if field is None:
            return 0, 0
        replicas = [
            n for n in self.shard_nodes(index_name, shard)
            if n.id != self.local.id
        ]
        # Stray-copy absorption: a NON-owner whose manifest lists this
        # fragment still contributes — a write acked under an older
        # ring (or during a partition) may live only on a node that no
        # longer owns the shard, and cleanup_unowned refuses to delete
        # such a copy until an owner has demonstrably absorbed it.
        # Owners first (authoritative), strays after; the conflict-
        # aware merge rules below apply to both.
        replica_ids = {n.id for n in replicas} | {self.local.id}
        for node in self.sorted_nodes():
            if node.id in replica_ids:
                continue
            stray = manifests.get(node.id)
            if isinstance(stray, dict) and stray.get(key):
                replicas.append(node)
        view = field.view(view_name, create=True)
        # fragment created lazily at first merge so a sync pass that
        # repairs nothing leaves no empty fragment files
        frag = view.fragment(shard)
        local_blocks = dict(frag.blocks()) if frag is not None else {}
        blocks_repaired = 0
        bits = 0
        for node in replicas:
            manifest = manifests.get(node.id)
            if manifest is None:
                continue  # unreachable this pass
            if isinstance(manifest, dict):
                peer_blocks = manifest.get(key)
                if not peer_blocks:
                    continue  # peer holds no data for this fragment
            else:  # "legacy": old-wire peer, per-fragment blocks GET
                try:
                    peer_blocks = dict(self.client.fragment_blocks(
                        node.uri, index_name, field_name, view_name,
                        shard,
                    ))
                except ClientError:
                    continue
            # the ONE manifest-diff implementation (roaring/kernels.py),
            # shared with the CDC bulk sync and the scrub replica fetch
            wanted = kernels.diff_digests(local_blocks, peer_blocks)
            if not wanted:
                continue
            merged_any = False
            for block, bm in self._fetch_delta_blocks(
                    node, index_name, key, wanted):
                if bm is None or not bm.count():
                    continue
                if frag is None:
                    frag = view.fragment(shard, create=True)
                if field.options.type in ("mutex", "bool"):
                    # single-value fields: union repair would resurrect
                    # rows a newer import cleared; conflicting columns
                    # keep the local row
                    added = frag.add_ids_mutex(
                        kernels.fragment_ids(kernels.flatten(bm)))
                elif view_name == field.bsi_view_name():
                    # BSI planes: per-column all-or-nothing — unioning
                    # stale planes into a newer value would fabricate
                    # values
                    added = frag.add_ids_value(
                        kernels.fragment_ids(kernels.flatten(bm)))
                else:
                    added = frag.import_roaring_bitmap(bm)
                if added:
                    bits += added
                    blocks_repaired += 1
                    merged_any = True
            # Recompute the local checksum set ONLY when this peer
            # actually merged something: the serial pass re-hashed the
            # whole fragment after EVERY peer, so an N-replica cluster
            # with zero divergence still paid N full to_ids+hash walks
            # per fragment per pass.
            if merged_any:
                local_blocks = dict(frag.blocks())
        return blocks_repaired, bits

    def _fetch_delta_blocks(self, node, index_name: str, key, wanted):
        """[(block, RoaringBitmap)] for the wanted blocks of one fragment
        from one peer: ONE multi-block POST when the peer speaks
        /internal/sync/blocks, per-block GETs otherwise (old wire). A
        transport fault skips the peer for this fragment — the next pass
        retries."""
        from pilosa_tpu.utils.tracing import global_tracer

        field_name, view_name, shard = key
        if self.client.supports_sync_manifest(node.uri):
            try:
                with global_tracer().span(
                    "sync.blocks", node=node.id, blocks=len(wanted),
                ) as span:
                    kw = ({"trace": span.header_value()}
                          if span is not None else {})
                    bitmaps = self.client.sync_blocks(
                        node.uri, index_name,
                        [(field_name, view_name, shard, wanted)],
                        **kw,
                    )
                return list(zip(wanted, bitmaps))
            except ClientError:
                if self.client.supports_sync_manifest(node.uri):
                    return []  # transport fault: skip peer this pass
                # 404/405 was just recorded: old wire — fall through to
                # the per-block path below
            except Exception as e:  # noqa: BLE001 — torn frames or an
                # undecodable payload from this peer: skip it this pass
                # (the next pass retries) instead of failing the fragment
                self._log_exception(
                    f"sync delta blocks from {node.id}", e
                )
                return []
        out = []
        for block in wanted:
            try:
                out.append((block, self.client.fragment_block_bitmap(
                    node.uri, index_name, field_name, view_name, shard,
                    block,
                )))
            except ClientError:
                continue
        return out

    def _sync_attrs(self) -> int:
        """Diff + union attr-store blocks against every peer (reference
        attr-block sync — SURVEY.md §3.5). Attrs are replicated everywhere
        (they are tiny), matching the reference's attr stores living beside
        every fragment owner. Peers are walked CONCURRENTLY per store —
        this runs inside the gated self-join path, where serial per-peer
        RTTs would extend the query-blocking window; merge_block
        serializes on the store's own lock."""
        merged = 0
        peers = [n for n in self.sorted_nodes() if n.id != self.local.id]
        for index_name, idx in list(self.holder.indexes.items()):
            stores = [("", idx.column_attrs)]
            stores += [
                (fname, f.row_attrs)
                for fname, f in list(idx.fields.items())
                if f.row_attrs is not None
            ]
            for field_name, store in stores:
                if store is None:
                    continue
                local = dict(store.blocks())
                # one fetch per DISTINCT peer version of a block: attrs
                # replicate everywhere, so N-1 peers usually advertise
                # the same checksum for a stale local block — without
                # the claim set every peer would redundantly fetch and
                # merge it. Divergent versions (different checksums)
                # still all merge.
                claimed: set[tuple] = set()
                claim_lock = threading.Lock()

                def sync_peer(node, field_name=field_name, store=store,
                              local=local, claimed=claimed,
                              claim_lock=claim_lock):
                    n = 0
                    try:
                        peer = self.client._call(
                            "GET",
                            f"{node.uri}/internal/attrs/blocks"
                            f"?index={index_name}&field={field_name}",
                        )
                    except ClientError:
                        return 0
                    for entry in peer.get("blocks", []):
                        block, checksum = entry["block"], entry["checksum"]
                        if local.get(block) == checksum:
                            continue
                        with claim_lock:
                            if (block, checksum) in claimed:
                                continue
                            claimed.add((block, checksum))
                        try:
                            data = self.client._call(
                                "GET",
                                f"{node.uri}/internal/attrs/block/data"
                                f"?index={index_name}&field={field_name}"
                                f"&block={block}",
                            )
                        except ClientError:
                            continue
                        store.merge_block(data.get("attrs", {}))
                        n += 1
                    return n

                merged += sum(concurrent_map(sync_peer, peers))
        return merged
