"""Cluster-wide wave batching: per-node group-commit of remote sub-queries.

The local serving pipeline (server/pipeline.py) converts concurrent
requests into shared device dispatches; this module does the same for the
HTTP hop between nodes. When a wave's remote sub-queries target the same
node, they ship as ONE ``/internal/query-batch`` request, so the remote
hop amortizes the per-request host cost (request line, headers, handler
dispatch, response envelope) exactly as the local micro-batcher amortizes
device dispatches.

Mechanism — group commit, not a timer window: one flusher thread per peer
node drains a queue. While a batch's round trip is in flight, newly
arriving sub-queries for that node accumulate; the next flush ships them
all. Idle traffic therefore pays ZERO added latency (a lone sub-query
flushes immediately), and batching grows automatically with exactly the
concurrency that exists.

Scope guards (the caller — ClusterExecutor — enforces most of these):

- only deadline-free, depth-0 primary reads batch; deadline-capped hops,
  hedge legs, and replica-fallback retries keep their direct per-request
  path (a hedge racing its primary must not queue behind it, and checkout
  exclusivity in the connection pool already guarantees they never share
  a socket);
- a peer answering 404/405 (older wire, no batch route) is remembered and
  served per-query thereafter;
- a batch-level transport fault fails every member with the SAME
  ClientError shape a direct query would have raised, so the caller's
  replica-fallback and breaker logic are unchanged;
- per-item errors inside a 200 batch envelope surface as per-item
  ClientErrors carrying the item's status.
"""

from __future__ import annotations

import threading

from pilosa_tpu.parallel.client import ClientError
from pilosa_tpu.utils.pool import concurrent_map


class _NodeQueue:
    __slots__ = ("lock", "pending", "flushing")

    def __init__(self):
        self.lock = threading.Lock()
        self.pending: list = []
        self.flushing = False


class _Slot:
    """One sub-query's seat in a batch: an event + outcome box."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None

    def resolve(self, value=None, error=None):
        if self.event.is_set():  # idempotent: sweep-up after a partial
            return               # distribution must not clobber a result
        self.value = value
        self.error = error
        self.event.set()

    def wait(self):
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.value


class RemoteWaveBatcher:
    """Group-commit batcher over ``InternalClient.query_batch``."""

    def __init__(self, client):
        self.client = client
        self._nodes: dict[str, _NodeQueue] = {}
        self._lock = threading.Lock()
        # observability (exported as serving_* on /metrics)
        self.batches = 0          # multi-query batch requests sent
        self.batched_queries = 0  # sub-queries that rode those batches
        self.solo = 0             # flushes that carried a single query
        self.fallbacks = 0        # per-query fallbacks (no-batch peer)

    def metrics(self) -> dict:
        with self._lock:
            return {
                "remote_batches_total": self.batches,
                "remote_batched_queries_total": self.batched_queries,
                "remote_batch_solo_total": self.solo,
                "remote_batch_fallbacks_total": self.fallbacks,
            }

    def _count(self, **deltas) -> None:
        with self._lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)

    # -------------------------------------------------------------- public

    def query(self, node, index: str, pql: str, shards,
              trace: str | None = None) -> dict:
        """One remote sub-query through the per-node group-commit lane.
        Returns the same ``{"results": [...]}`` dict ``query_node``
        would; raises ClientError on failure. ``trace`` (an
        X-Pilosa-Trace value) rides the batch per item, so a sampled
        sub-query keeps its trace context even when it shares a POST
        with unsampled wavemates — the peer's span subtree comes back
        inside this item's response dict."""
        client = self.client
        if (not getattr(client, "supports_batch", lambda uri: False)(node.uri)
                or not hasattr(client, "query_batch")):
            # older peer wire, or a test double without the batch verb
            self._count(fallbacks=1)
            return self._query_direct(node, index, pql, shards, trace)
        nq = self._node_queue(node.id)
        slot = _Slot()
        with nq.lock:
            nq.pending.append((index, pql, shards, trace, slot))
            leader = not nq.flushing
            if leader:
                nq.flushing = True
        if leader:
            self._flush_loop(node, nq)
        return slot.wait()

    def _query_direct(self, node, index, pql, shards, trace):
        """Per-query path: the trace keyword rides only when set, so
        client doubles that predate it keep working untraced."""
        kw = {"trace": trace} if trace is not None else {}
        return self.client.query_node(node.uri, index, pql, shards,
                                      remote=True, **kw)

    # ------------------------------------------------------------ internals

    def _node_queue(self, node_id: str) -> _NodeQueue:
        with self._lock:
            nq = self._nodes.get(node_id)
            if nq is None:
                nq = self._nodes[node_id] = _NodeQueue()
            return nq

    def _flush_loop(self, node, nq: _NodeQueue, leader: bool = True) -> None:
        """Drain-and-send until the queue is empty; sub-queries arriving
        during a round trip are picked up by the next flush (group
        commit). The LEADER (the request thread that found no flush in
        flight) sends exactly one batch — its own slot resolves in it —
        then hands any accumulated tail to a worker thread, so the
        leader's caller gets its response without paying later batches'
        round trips."""
        while True:
            with nq.lock:
                batch = nq.pending
                nq.pending = []
                if not batch:
                    nq.flushing = False
                    return
            try:
                self._send(node, batch)
            except BaseException as e:
                # _send guards its own distribution, so this is a bug's
                # last line of defense: every unresolved slot — this
                # batch's AND any stragglers queued behind it — gets the
                # error as a ClientError (callers run replica fallback;
                # nobody hangs), and the flushing flag is released so
                # the node's lane cannot wedge permanently. Not
                # re-raised: the error IS the slots' outcome, and the
                # leader must fall through to its own slot.wait().
                with nq.lock:
                    stranded = nq.pending
                    nq.pending = []
                    nq.flushing = False
                for *_, slot in [*batch, *stranded]:
                    slot.resolve(error=_clone_error(e))
                return
            if leader:
                with nq.lock:
                    if not nq.pending:
                        nq.flushing = False
                        return
                threading.Thread(
                    target=self._flush_loop, args=(node, nq, False),
                    daemon=True, name=f"wavebatch-{node.id}",
                ).start()
                return

    def _send(self, node, batch: list) -> None:
        client = self.client
        if len(batch) == 1:
            index, pql, shards, trace, slot = batch[0]
            self._count(solo=1)
            try:
                slot.resolve(self._query_direct(node, index, pql, shards,
                                                trace))
            except BaseException as e:
                slot.resolve(error=e)
            return
        # untraced batches (the overwhelmingly common case) keep the
        # plain 3-tuple item shape; the 4th trace element appears only
        # when some wavemate is sampled
        if any(t is not None for _, _, _, t, _ in batch):
            items = [(index, pql, shards, trace)
                     for index, pql, shards, trace, _ in batch]
        else:
            items = [(index, pql, shards)
                     for index, pql, shards, _, _ in batch]
        try:
            responses = client.query_batch(node.uri, items)
            if len(responses) != len(batch):
                raise ClientError(
                    f"query-batch to {node.id}: {len(responses)} responses "
                    f"for {len(batch)} queries"
                )
        except BaseException as e:
            if isinstance(e, ClientError) and e.status in (404, 405):
                # peer predates the route: replay this batch per-query
                # (the client already recorded the peer as no-batch, so
                # future waves skip straight to query_node)
                self._count(fallbacks=len(batch))
                self._replay_individually(node, batch)
                return
            for *_, slot in batch:
                slot.resolve(error=_clone_error(e))
            return
        self._count(batches=1, batched_queries=len(batch))
        try:
            for (index, pql, shards, _, slot), resp in zip(batch,
                                                           responses):
                if not isinstance(resp, dict):
                    # malformed peer item (e.g. null): this slot fails,
                    # well-formed batchmates still resolve normally
                    slot.resolve(error=ClientError(
                        f"POST {node.uri}/internal/query-batch "
                        f"[{index}: {pql}]: malformed batch item "
                        f"{type(resp).__name__}"))
                elif "error" in resp:
                    slot.resolve(error=ClientError(
                        f"POST {node.uri}/internal/query-batch "
                        f"[{index}: {pql}]: {resp['error']}",
                        status=resp.get("status"),
                    ))
                else:
                    slot.resolve(resp)
        except BaseException as e:
            # distribution must never strand a slot: whatever broke,
            # every unresolved waiter gets a node-fault error
            for *_, slot in batch:
                slot.resolve(error=_clone_error(e))
            raise

    def _replay_individually(self, node, batch: list) -> None:
        def one(entry):
            index, pql, shards, trace, slot = entry
            try:
                slot.resolve(self._query_direct(node, index, pql, shards,
                                                trace))
            except BaseException as e:
                slot.resolve(error=e)

        concurrent_map(one, batch)


def _clone_error(exc: BaseException) -> BaseException:
    """Per-slot copies of a batch-level failure: every waiter raises its
    own exception object, so one caller's traceback/handling can never
    mutate a sibling's."""
    if isinstance(exc, ClientError):
        return ClientError(str(exc), status=exc.status)
    return ClientError(str(exc) or type(exc).__name__)
