"""Keep-alive HTTP connection pool for the serving fast lane.

Round-5 benchmarks showed serving QPS plateauing in the Python host path
with the device near-idle (docs/OPERATIONS.md): every internal hop and
edge request paid a fresh TCP connect (plus a server-side handler-thread
spawn) because `urllib.request.urlopen` opens and closes a socket per
call. This pool keeps bounded per-host sets of persistent
``http.client`` connections:

- **Exclusive checkout**: a connection serves exactly one request at a
  time, so concurrent callers (including a hedged read racing its
  primary — qos/hedge.py) can never share a socket.
- **Health-checked reuse**: a checked-out idle connection whose socket
  is already readable is half-closed (server sent FIN) or poisoned
  (stray bytes) — it is discarded, not reused. A reuse that still hits
  the keep-alive race (server closed between our check and the request
  landing) is retried once on a fresh connection; fresh-connection
  failures propagate.
- **Bounded**: at most ``max_per_host`` idle connections are retained
  per (scheme, host, port); extras close on check-in. Node death leaves
  nothing pooled — failed connections are always discarded.
- **TLS-capable**: an ``ssl.SSLContext`` (e.g. the internal client's
  skip-verify context) applies to https hosts.

Transport faults raise the stdlib exceptions callers already classify
(`URLError`-free zone: `OSError`/`TimeoutError`/`http.client` errors);
HTTP status is returned, never raised — the caller owns error mapping.
"""

from __future__ import annotations

import http.client
import select
import socket
import threading
from collections import deque
from urllib.parse import urlsplit

from pilosa_tpu.testing import faults
from pilosa_tpu.utils.tracing import global_tracer

# Retryable symptoms of the keep-alive race: the server closed a pooled
# connection between our health check and the request hitting its socket.
# Only ever retried when the connection was REUSED and nothing of the
# response was read — a fresh connection failing the same way is a real
# transport fault and propagates.
_STALE_ERRORS = (
    http.client.RemoteDisconnected,
    http.client.BadStatusLine,
    BrokenPipeError,
    ConnectionResetError,
    ConnectionAbortedError,
)


class PoolResponse:
    """Fully-read response: status + headers + body (the pool must drain
    the body before the connection can be reused, so streaming is not
    offered)."""

    __slots__ = ("status", "headers", "data")

    def __init__(self, status: int, headers, data: bytes):
        self.status = status
        self.headers = headers
        self.data = data


class ConnectionPool:
    """Bounded keep-alive pool over ``http.client`` connections."""

    def __init__(self, max_per_host: int = 8, timeout: float = 30.0,
                 ssl_context=None):
        self.max_per_host = max(1, int(max_per_host))
        self.timeout = timeout
        self.ssl_context = ssl_context
        # fault-injection source label (testing/faults.py): the node
        # name this pool sends AS, so partition rules can match one
        # direction of traffic. Set by the owning server; "" for bare
        # pools (CLI importer, tests), which rules match via src="*".
        self.fault_source = ""
        self._idle: dict[tuple, deque] = {}
        self._lock = threading.Lock()
        # lifecycle counters (read by /metrics via the owning server)
        self.created = 0
        self.reused = 0
        self.discarded = 0
        self.requests = 0

    # ------------------------------------------------------------ lifecycle

    def _checkout(self, key):
        """Pop a healthy idle connection for ``key``, or None."""
        while True:
            with self._lock:
                dq = self._idle.get(key)
                conn = dq.popleft() if dq else None
            if conn is None:
                return None
            sock = getattr(conn, "sock", None)
            if sock is None:
                self._note_discard(conn)
                continue
            try:
                # A readable idle socket means EOF (half-close) or stray
                # bytes — either way the connection cannot carry a fresh
                # request/response exchange.
                readable, _, _ = select.select([sock], [], [], 0)
            except (OSError, ValueError):
                readable = [sock]
            if readable:
                self._note_discard(conn)
                continue
            with self._lock:
                self.reused += 1
            return conn

    def _connect(self, key) -> http.client.HTTPConnection:
        scheme, host, port = key
        if scheme == "https":
            conn = http.client.HTTPSConnection(
                host, port, timeout=self.timeout, context=self.ssl_context
            )
        else:
            conn = http.client.HTTPConnection(host, port,
                                              timeout=self.timeout)
        with self._lock:
            self.created += 1
        return conn

    def _checkin(self, key, conn) -> None:
        with self._lock:
            dq = self._idle.setdefault(key, deque())
            if len(dq) < self.max_per_host:
                dq.append(conn)
                return
        self._note_discard(conn)

    def _note_discard(self, conn) -> None:
        with self._lock:
            self.discarded += 1
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        """Drop every idle connection (server shutdown, tests)."""
        with self._lock:
            idle, self._idle = self._idle, {}
        for dq in idle.values():
            for conn in dq:
                try:
                    conn.close()
                except OSError:
                    pass

    def metrics(self) -> dict:
        with self._lock:
            idle = sum(len(dq) for dq in self._idle.values())
            return {
                "pool_connections_created_total": self.created,
                "pool_connections_reused_total": self.reused,
                "pool_connections_discarded_total": self.discarded,
                "pool_requests_total": self.requests,
                "pool_idle_connections": idle,
            }

    # -------------------------------------------------------------- request

    def request(self, method: str, url: str, body: bytes | None = None,
                headers: dict | None = None,
                timeout: float | None = None,
                _redelivery: bool = False) -> PoolResponse:
        """One request/response exchange on a pooled connection. Returns
        the status whatever it is (no exception on 4xx/5xx); raises the
        underlying socket/http.client error on transport faults."""
        parts = urlsplit(url)
        scheme = parts.scheme or "http"
        key = (scheme, parts.hostname,
               parts.port or (443 if scheme == "https" else 80))
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        # Fault injection (testing/faults.py): one global load + None
        # test when no plane is installed — the shipping path pays
        # nothing. ``_redelivery`` marks a duplicate-rule redelivery so
        # the second copy isn't itself re-intercepted (infinite
        # duplication otherwise).
        duplicate = False
        plane = faults._PLANE
        if plane is not None and not _redelivery:
            directive = plane.intercept(
                self.fault_source, f"{key[1]}:{key[2]}", parts.path or "/"
            )
            if directive is not None:
                if directive.delay_s > 0:
                    plane.sleep(directive.delay_s)
                if directive.drop:
                    # a partitioned link looks like a transport fault to
                    # the sender: same exception family a dead peer's
                    # kernel would produce, mapped to ClientError by the
                    # internal client
                    raise OSError(
                        f"fault injected: drop {self.fault_source or '?'}"
                        f" -> {key[1]}:{key[2]} {parts.path}"
                    )
                if directive.error is not None:
                    status, body_bytes = directive.error
                    return PoolResponse(
                        status, {"Content-Type": "application/json"},
                        body_bytes,
                    )
                duplicate = directive.duplicate
        with self._lock:
            self.requests += 1
        effective = self.timeout if timeout is None else timeout
        last_exc: Exception | None = None
        for fresh in (False, True):
            # conn.checkout span: pool acquisition cost per request —
            # whether this hop rode a pooled keep-alive socket or paid a
            # fresh TCP connect is exactly the fast-lane property the
            # pool exists for (no-op when the request is unsampled)
            with global_tracer().span("conn.checkout",
                                      host=f"{key[1]}:{key[2]}") as cspan:
                conn = None if fresh else self._checkout(key)
                reused = conn is not None
                if conn is None:
                    conn = self._connect(key)
                if cspan is not None:
                    cspan.tags["reused"] = reused
            # per-request timeout: conn.timeout only applies at connect,
            # so a reused connection's live socket is re-armed explicitly
            # (and RESET when no per-request cap rides this call — the
            # previous request may have left a tighter deadline cap)
            conn.timeout = effective
            if conn.sock is None:
                try:
                    with global_tracer().span("conn.connect",
                                              host=f"{key[1]}:{key[2]}"):
                        conn.connect()
                    # request/response hops are latency-bound small
                    # writes: never let Nagle hold the tail packet
                    conn.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                except OSError:
                    self._note_discard(conn)
                    raise
            if conn.sock is not None:
                try:
                    conn.sock.settimeout(effective)
                except OSError as e:
                    self._note_discard(conn)
                    if not reused:
                        raise
                    last_exc = e
                    continue
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
            except _STALE_ERRORS as e:
                self._note_discard(conn)
                if not reused:
                    raise
                last_exc = e
                continue  # keep-alive race: one retry on a fresh socket
            except BaseException:
                # timeout mid-exchange, SSL fault, DNS, refused connect —
                # the request may have been processed, so never retried
                self._note_discard(conn)
                raise
            try:
                data = resp.read()
            except BaseException:
                # the status line ARRIVED: the server executed this
                # request, so a fault while reading the body must never
                # replay it (the retry invariant above is "nothing of
                # the response was read") — discard and propagate
                self._note_discard(conn)
                raise
            if resp.will_close:
                self._note_discard(conn)
            else:
                self._checkin(key, conn)
            if duplicate:
                # at-least-once delivery: the peer just processed a
                # copy; deliver another and return the LAST response —
                # what a duplicating network shows the sender
                return self.request(method, url, body=body,
                                    headers=headers, timeout=timeout,
                                    _redelivery=True)
            return PoolResponse(resp.status, resp.headers, data)
        raise last_exc  # pragma: no cover — loop always returns or raises
