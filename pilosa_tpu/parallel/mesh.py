"""Device mesh + shard→device assignment.

The mesh axis ``"shards"`` is the TPU analog of the reference's hash
partitioning (cluster.go: partition = hash(index, shard) % 256 → nodes —
SURVEY.md §2 #13): a query's shard list is laid out as the leading axis of
a global array sharded over the mesh, so each chip's HBM holds its slice
of shards and XLA collectives do the reduce that the reference did over
HTTP.

Multi-host: ``initialize_distributed`` wires jax.distributed so the same
mesh spans hosts over DCN; the shard axis simply gets longer. Nothing in
the executor changes — that is the point of expressing the cluster as a
mesh instead of porting the reference's gossip/RPC.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.executor.batch import ShardBlock
from pilosa_tpu.shardwidth import next_pow2

SHARDS_AXIS = "shards"
GROUPS_AXIS = "groups"


def make_mesh(n_devices: int | None = None, devices=None,
              groups: int | None = None) -> Mesh:
    """Mesh over the shard axis. Default is 1-D: bitmap ops have no
    second model axis to map, so a flat topology is just the flattened
    device list.

    ``groups`` > 1 factorizes the same devices as a 2-D ``groups x
    shards`` mesh — device g*S+s is slot (g, s) — turning every
    reduction into the hierarchical two-stage form (parallel/dist.py):
    dense psum/pmax inside each group, then a narrow encoded inter-group
    lane (parallel/reduction.py). Groups model the expensive boundary
    (chips across DCN, or ICI superblocks); results stay bit-identical
    to the 1-D path, only the wire traffic shape changes."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    devices = np.asarray(devices)
    if groups is not None and groups > 1:
        if devices.size % groups:
            raise ValueError(
                f"groups={groups} does not divide {devices.size} devices"
            )
        return Mesh(devices.reshape(groups, -1),
                    (GROUPS_AXIS, SHARDS_AXIS))
    return Mesh(devices, (SHARDS_AXIS,))


def mesh_groups(mesh: Mesh) -> tuple[int, int] | None:
    """(groups, shards_per_group) for a 2-D hierarchical mesh, None for
    the flat 1-D form."""
    if GROUPS_AXIS in mesh.axis_names:
        return (mesh.shape[GROUPS_AXIS], mesh.shape[SHARDS_AXIS])
    return None


def shards_spec(mesh: Mesh) -> P:
    """PartitionSpec splitting a leading shard-slot axis over every mesh
    device (both axes of the 2-D form — slot order matches the flattened
    device list either way)."""
    if GROUPS_AXIS in mesh.axis_names:
        return P((GROUPS_AXIS, SHARDS_AXIS))
    return P(SHARDS_AXIS)


def shards_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [n_shards_padded, ...] arrays: leading axis split over
    the mesh."""
    return NamedSharding(mesh, shards_spec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host bring-up over DCN (replaces the reference's
    memberlist/gossip data-plane role; schema gossip stays HTTP —
    parallel.cluster)."""
    if coordinator is None:
        return  # single-host
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class ShardAssignment(ShardBlock):
    """Maps a query's shard list onto mesh slots.

    Extends the local ShardBlock layout (executor/batch.py): rows ordered
    by the sorted shard list, padded to a multiple of the mesh size with
    empty slots; slot s lives on device s // (S_padded / n_devices).
    Replication (the reference's replicaN) is a host-side property of
    fragment *files* (parallel.cluster); device residency is single-copy
    since HBM is a cache, not the durable store.
    """

    def __init__(self, shards: list[int], mesh: Mesh):
        super().__init__(shards)
        self.n_devices = mesh.size
        n = max(len(self.shards), 1)
        # bucketed per-device slot count (see ShardBlock): compile count
        # stays O(log shards) as the index grows
        self.padded = self.n_devices * next_pow2(-(-n // self.n_devices))
        self.mesh = mesh
        self.local_slots = (0, self.padded)
        # Multi-host: this process feeds only the slot rows that live on
        # its addressable devices (jax.make_array_from_process_local_data
        # in DistExecutor._leaf_put assembles the global array). Writes
        # patch resident leaves per-PIECE: the addressable single-device
        # buffer holding the shard's slot is rewritten locally and the
        # global handle reassembled, no collective involved
        # (batch._patch_sharded; batch._make_probe states the
        # owner-applies-the-write correctness contract).
        if jax.process_count() > 1:
            per_dev = self.padded // self.n_devices
            flat = mesh.devices.ravel()
            mine = [i for i, d in enumerate(flat)
                    if d.process_index == jax.process_index()]
            if not mine:
                raise ValueError(
                    f"mesh contains no devices of process "
                    f"{jax.process_index()}; every process driving a "
                    f"multi-host DistExecutor must own mesh devices "
                    f"(don't slice jax.devices() down to one host)"
                )
            lo, hi = mine[0], mine[-1] + 1
            if mine != list(range(lo, hi)):
                raise ValueError(
                    "mesh devices of one process must be contiguous for "
                    "per-host shard feeding"
                )
            self.local_slots = (lo * per_dev, hi * per_dev)
            self.patchable = False

    @property
    def slot_of(self) -> dict[int, int]:
        return {s: i for i, s in enumerate(self.shards)}
