"""pilosa_tpu — a TPU-native distributed bitmap index.

A ground-up re-design of the capabilities of ngaut/pilosa (a fork of
pilosa/pilosa, the distributed roaring-bitmap index) for TPU hardware:

- fragments are dense bit-packed ``uint32[rows, 32768]`` matrices pinned in
  per-chip HBM instead of roaring container trees (roaring remains the
  host/disk interchange format — see ``pilosa_tpu.roaring``),
- container set-ops + popcounts become fused XLA bitwise/popcount kernels
  (``pilosa_tpu.ops``),
- the per-shard mapReduce of the reference executor becomes ``shard_map``
  over a device mesh with ICI collectives (``pilosa_tpu.parallel``),
- PQL, the storage tree (holder→index→field→view→fragment), HTTP API and
  clustering semantics are preserved (``pilosa_tpu.pql``,
  ``pilosa_tpu.storage``, ``pilosa_tpu.server``).

Reference layout this mirrors (see SURVEY.md §1–2): roaring/, row.go,
fragment.go, field.go, index.go, holder.go, pql/, executor.go, http/,
cluster.go, server.go.
"""

__version__ = "0.1.0"

from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP, WORDS_PER_SHARD
