"""CLI: server / import / export / config / inspect / check / version.

Reference: cmd/pilosa + ctl/ (SURVEY.md §2 #28–30) — cobra subcommands with
TOML-config < env < flag precedence. Here: argparse with the same
precedence (PILOSA_TPU_* env vars), talking either to a running server
over HTTP (--host) or directly to a data dir in-process (--data-dir),
which is the TPU-friendly path for bulk imports (no HTTP hop).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import urllib.request

from pilosa_tpu import __version__

DEFAULT_HOST = "http://localhost:10101"

_DEFAULT_TOML = """\
# pilosa-tpu server configuration. Precedence: flags > PILOSA_TPU_* env
# vars > this file > defaults (env var names: key uppercased, dashes ->
# underscores, e.g. PILOSA_TPU_ANTI_ENTROPY_INTERVAL).
data-dir = "~/.pilosa_tpu"
bind = "localhost"
port = 10101
# name = "node-<port>"        # stable node id in the cluster
# advertise = ""              # URI peers should use (default: bind:port)
# seeds = ["http://host:10101"]  # join an existing cluster via any member
replica-n = 1                 # replicas per shard
anti-entropy-interval = 600.0 # seconds; 0 disables the repair ticker
heartbeat-interval = 5.0      # seconds; 0 disables death detection
heartbeat-timeout = 2.0       # tight per-probe timeout for liveness
                              # checks (heartbeat, quorum, death
                              # corroboration) — a hung peer must not
                              # stall detection of other failures
# use-mesh = true             # force the device-mesh executor (default:
                              # auto - mesh when >1 JAX device)
# mesh-groups = 0             # reduction groups for multi-chip meshes;
                              # 0 = auto (flat 1-D mesh)
# topn-quantized-ranking = false # EQuARX 8-bit TopN/GroupBy candidate
                              # ranking on the inter-group wire; final
                              # results stay byte-identical (exact
                              # recount on the error-bound-widened
                              # window)
# device-budget-bytes = 0     # HBM residency budget; 0 = auto
long-query-time = 0.0         # log queries slower than this; 0 = off
max-writes-per-request = 5000 # reject larger write batches; 0 = unlimited
ingest-workers = 1            # local shard-group apply pool per import
                              # batch; raise where fragment writes pay real
                              # disk latency (docs/INGEST.md)

# Serving fast lane (docs/OPERATIONS.md): keep-alive pooling + batching
client-pool-size = 8          # keep-alive connections retained per peer
remote-batch = true           # coalesce same-node remote sub-queries onto
                              # /internal/query-batch (false = per-query)

# Multi-process serving tier (docs/OPERATIONS.md deployment shapes):
# shatters the single-interpreter serving ceiling with N SO_REUSEPORT
# worker processes fronting this (device-owner) process over
# shared-memory rings; requires SO_REUSEPORT (Linux), falls back to
# single-process otherwise
serving-workers = 0           # worker processes; 0 = single-process
ring-slots = 1024             # slots per ring direction per worker
ring-slot-bytes = 65536       # bytes per slot (large responses span
                              # consecutive slots)

# Skewed traffic (docs/OPERATIONS.md): write-invalidated result cache +
# heat-driven HBM residency tiering — the actuators on the heat plane
result-cache-bytes = 0        # pre-serialized hot-query response bytes
                              # kept across waves, invalidated at every
                              # (index,field,shard) write; 0 = off
residency-promote-interval = 0.0  # seconds between tiering passes
                              # (demote cold fragments to the compressed
                              # host tier, promote hot ones back); 0 = off
residency-promote-heat = 4.0  # heat above which host-tier fragments
                              # promote to device residency
residency-demote-heat = 1.0   # heat below which device-resident
                              # fragments demote host-side; the gap to
                              # promote-heat is the hysteresis dead band
residency-host-tier-bytes = 1073741824  # compressed host-tier budget

# Autopilot placement plane (docs/OPERATIONS.md autopilot): the
# coordinator periodically rebalances the hottest (index,shard) groups
# off overloaded nodes via epoch-fenced placement overrides + resize.
# The kill switch gates only the planner — overrides minted elsewhere
# are still honored by every node, keeping placement consistent.
autopilot-enabled = false     # master kill switch for the planner ticker
autopilot-interval = 30.0     # seconds between planner passes
autopilot-heat-budget = 1.5   # per-node heat ceiling as a multiple of
                              # mean node heat; the margin over 1.0 is
                              # the hysteresis dead band
autopilot-max-moves = 4       # shard-group moves per pass (further
                              # shaped by repair-max-bytes-per-sec)
autopilot-min-dwell = 0.0     # seconds a moved shard is frozen before
                              # it may move again; 0 = two intervals
autopilot-split-threshold = 0.0  # shard heat above this multiple of
                              # mean node load splits the shard into
                              # sub-shard column ranges; 0 = off
autopilot-split-ways = 2      # ranges a hot shard is split into

# Write-path durability (docs/OPERATIONS.md): what an HTTP 200 on a
# write means
durability-mode = "group"     # group = one fsync per commit group of
                              # concurrent writers (acked = durable);
                              # per-op = fsync every write; flush-only =
                              # legacy r5 behavior (OS buffer only)
group-commit-max-ms = 2.0     # max time a record waits for its group's
                              # fsync to start (bounds write ACK latency)
group-commit-max-ops = 256    # max op records fsynced per group

# Storage integrity (docs/OPERATIONS.md integrity runbook)
verify-on-load = true         # check fragment snapshots against their
                              # .checksums sidecars at open; corrupt
                              # files quarantine (never served) and
                              # read-repair from replicas
scrub-interval = 0.0          # seconds between background scrub passes
                              # over owned fragments' DISK bytes; 0 = off
scrub-max-bytes-per-sec = 0   # token-bucket budget for scrub reads;
                              # 0 = unpaced

# Anti-entropy / resize data plane (docs/OPERATIONS.md)
sync-workers = 8              # fragment diff/fetch/apply pipeline width
                              # per repair pass
repair-max-bytes-per-sec = 0  # token-bucket pacing of repair/resize
                              # transfers; 0 = unpaced
repair-max-inflight = 0       # concurrent repair transfers; 0 = unbounded
repair-compression = true     # zlib Content-Encoding on fragment and
                              # delta payloads (negotiated per peer)

# Replication & CDC (docs/OPERATIONS.md): WAL tail change feed ->
# cluster-safe result caching, stale-bounded read replicas, and
# `restore --as-of <seq>` point-in-time restore
cdc-enabled = false           # tail peers' WAL feeds to invalidate the
                              # result cache cluster-wide (lifts the
                              # single-node-only cache refusal)
cdc-max-retention-bytes = 67108864  # WAL bytes pinned for lagging tail
                              # cursors before they are forced off
                              # (410 Gone -> consumer resyncs)
cdc-poll-interval = "50ms"    # tailer poll cadence (Go duration)
cdc-max-batch-bytes = 1048576 # max event bytes per tail poll
# cdc-follow = ""             # upstream URI: run as a read replica
                              # (non-quorum follower; writes 403)
cdc-staleness-budget = "1s"   # declared follower staleness bound; reads
                              # past it shed 503 (X-Pilosa-Max-Staleness
                              # can tighten per request); 0 = unbounded

# Serving QoS (docs/QOS.md): admission -> deadline -> hedged reads
qos-max-inflight = 0          # concurrent-query cap; excess sheds 429 (0 = off)
qos-tenant-inflight = 0       # per-tenant cap (X-Pilosa-Tenant); 0 = global
qos-default-deadline = 0.0    # server-default request deadline; 0 = none
qos-hedge-delay = 0.25        # hedge trigger before the p95 tracker warms up
qos-hedge-budget = 0.05       # max hedges as a fraction of reads; 0 disables
qos-breaker-threshold = 5     # consecutive faults before a breaker opens
qos-breaker-cooldown = 5.0    # open -> half-open probe interval (seconds)
tracing = false               # legacy always-on switch (= sample rate 1.0)
trace-sample-rate = 0.0       # probabilistic trace sampling: 0 = off
                              # (zero overhead), 0.01 = 1% of requests
                              # root a cross-node span tree on
                              # /debug/traces (docs/OBSERVABILITY.md)
# trace-log-dir = ""          # where POST /debug/trace-device writes JAX
                              # profiler captures (default:
                              # <data-dir>/jax-traces)

# Query cost plane (docs/OBSERVABILITY.md): PROFILE is per-request
# (?profile=true), the ledger/heat surfaces are always on
slow-query-ring = 100         # offenders kept by /debug/queries/slow
                              # (threshold = long-query-time above)
heat-half-life = 300.0        # decay half-life (seconds) of the
                              # per-shard heat counters (/debug/heatmap)
# slo-objectives = ["reads:latency:100ms:0.99", "avail:errors:0.999"]
                              # declarative SLOs; burn rates exported as
                              # slo_* gauges and GET /debug/slo
# slo-windows = ["300s", "3600s"]  # burn-rate evaluation windows
                              # (default: the classic 5m/1h pair)
# statsd = "127.0.0.1:8125"   # statsd UDP sink (Prometheus /metrics is
                              # always on)
# diagnostics-endpoint = ""   # phone-home URL; empty = off
verbose = false

# [tls]
# certificate = "/path/node.crt"
# key = "/path/node.key"
# skip-verify = false         # accept self-signed peer certs
"""


def _load_config(path: str | None) -> dict:
    cfg: dict = {}
    if path:
        try:  # stdlib on 3.11+
            import tomllib
        except ImportError:  # 3.10 runtimes ship the identical tomli
            import tomli as tomllib

        with open(path, "rb") as f:
            cfg = tomllib.load(f)
    # env overrides file: PILOSA_TPU_DATA_DIR → data-dir
    for key, val in os.environ.items():
        if key.startswith("PILOSA_TPU_"):
            cfg[key[len("PILOSA_TPU_"):].lower().replace("_", "-")] = val
    return cfg


_pool = None


def _client_pool():
    """Process-wide keep-alive pool for CLI HTTP calls: every import
    batch (and the --concurrency workers' parallel POSTs) reuses
    persistent connections instead of paying TCP connect per batch —
    the same fast lane the internal node-to-node client rides."""
    global _pool
    if _pool is None:
        from pilosa_tpu.parallel.connpool import ConnectionPool

        _pool = ConnectionPool(max_per_host=16, timeout=300.0)
    return _pool


class _HTTPStatusError(Exception):
    """Non-2xx response through the pooled client (code + body text)."""

    def __init__(self, code: int, detail: str):
        super().__init__(f"HTTP {code}: {detail}")
        self.code = code
        self.detail = detail


def _http(method: str, url: str, data: bytes | None = None,
          content_type: str = "application/json"):
    headers = {"Content-Type": content_type} if data is not None else {}
    resp = _client_pool().request(method, url, body=data, headers=headers)
    if 300 <= resp.status < 400:
        # the pool does not follow redirects (urllib did): surface a
        # clear error instead of feeding an HTML body to json.loads
        location = resp.headers.get("Location", "")
        raise _HTTPStatusError(
            resp.status,
            "redirect" + (f" to {location}" if location else "")
            + " — point --host at the final URL",
        )
    if resp.status >= 400:
        raise _HTTPStatusError(resp.status,
                               resp.data.decode(errors="replace"))
    return json.loads(resp.data or b"{}")


def _iter_csv_bits(files, batch: float):
    """Stream ``row,col[,ts]`` CSVs as (rows, cols, timestamps|None)
    batches of at most ``batch`` lines — whole-file parse lists never
    materialize, so import memory is O(batch), not O(file)."""
    rows, cols, timestamps = [], [], []
    any_ts = False
    for path in files:
        fh = sys.stdin if path == "-" else open(path)
        try:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                rows.append(int(parts[0]))
                cols.append(int(parts[1]))
                ts = parts[2] if len(parts) > 2 else None
                timestamps.append(ts)
                any_ts = any_ts or ts is not None
                if len(rows) >= batch:
                    yield rows, cols, (timestamps if any_ts else None)
                    rows, cols, timestamps = [], [], []
                    any_ts = False
        finally:
            if fh is not sys.stdin:
                fh.close()
    if rows:
        yield rows, cols, (timestamps if any_ts else None)


def _iter_csv_values(files, batch: float):
    """Stream ``col,value`` CSVs as (cols, vals) batches (see
    _iter_csv_bits)."""
    cols, vals = [], []
    for path in files:
        fh = sys.stdin if path == "-" else open(path)
        try:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                cols.append(int(parts[0]))
                vals.append(int(parts[1]))
                if len(cols) >= batch:
                    yield cols, vals
                    cols, vals = [], []
        finally:
            if fh is not sys.stdin:
                fh.close()
    if cols:
        yield cols, vals


def _parse_csv_bits(files):
    """Whole-file form of _iter_csv_bits (small inputs, tests)."""
    return next(_iter_csv_bits(files, float("inf")), ([], [], None))


def _parse_csv_values(files):
    return next(_iter_csv_values(files, float("inf")), ([], []))


def cmd_server(args) -> int:
    from pilosa_tpu.server import Server, ServerConfig

    cfg_dict = _load_config(args.config)
    config = ServerConfig.from_dict(cfg_dict)
    if args.data_dir:
        config.data_dir = args.data_dir
    if args.bind:
        config.bind = args.bind
    if args.port is not None:
        config.port = args.port
    if args.verbose:
        config.verbose = True
    server = Server(config).open()
    try:
        import signal
        import threading

        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
    finally:
        server.close()
    return 0


def cmd_serve_worker(args) -> int:
    """Hidden entry for one SO_REUSEPORT serving worker process —
    spawned by the device owner's OwnerRuntime with an inherited
    listening socket, never run by hand (serving/mpserve.py)."""
    from pilosa_tpu.serving.worker import worker_main

    return worker_main(args.handshake_sock, args.listen_fd,
                       args.worker_id)


def _in_process_api(data_dir: str):
    from pilosa_tpu.server.api import API
    from pilosa_tpu.storage import Holder

    return API(Holder(data_dir).open())


DEFAULT_IMPORT_BATCH = 100_000


def _probe_batch_limit(host: str) -> int:
    """Server write-batch limit from /status (0 = none advertised). A
    probe failure is fine — the 413 split fallback in _post_import still
    converges on an acceptable size."""
    try:
        st = _http("GET", f"{host}/status")
        return int(st.get("maxWritesPerRequest") or 0)
    except (_HTTPStatusError, OSError, http.client.HTTPException,
            ValueError):
        return 0


def _post_import(host: str, path: str, payload: dict) -> int:
    """POST one import body; on a 413 (server max-writes-per-request
    tighter than the client's batch — e.g. the /status probe failed or
    raced a config change) split the batch in half and retry both
    halves. Returns bits changed."""
    body = json.dumps(payload).encode()
    try:
        return _http("POST", f"{host}{path}", body).get("changed", 0)
    except _HTTPStatusError as e:
        n = len(payload["columns"])
        if e.code == 413 and n > 1:
            lo = {k: (v[: n // 2] if isinstance(v, list) else v)
                  for k, v in payload.items()}
            hi = {k: (v[n // 2:] if isinstance(v, list) else v)
                  for k, v in payload.items()}
            return (_post_import(host, path, lo)
                    + _post_import(host, path, hi))
        raise


def cmd_import(args) -> int:
    if args.data_dir:
        api = _in_process_api(args.data_dir)
        if args.create:
            if api.holder.index(args.index) is None:
                api.create_index(args.index)
            if api.holder.index(args.index).field(args.field) is None:
                opts = {"type": "int", "min": args.min, "max": args.max} if args.values else {}
                api.create_field(args.index, args.field, opts)
        # streamed batches: O(batch) memory even for huge CSVs (the
        # in-process path has no HTTP limit to clamp against)
        batch = args.batch_size if args.batch_size > 0 else 1_000_000
        n = 0
        if args.values:
            for cols, vals in _iter_csv_values(args.files, batch):
                n += api.import_values(args.index, args.field, cols, vals,
                                       clear=args.clear)
        else:
            for rows, cols, ts in _iter_csv_bits(args.files, batch):
                n += api.import_bits(args.index, args.field, rows, cols,
                                     timestamps=ts, clear=args.clear)
        api.holder.close()
        print(f"imported: {n} bits changed")
        return 0
    # HTTP mode: stream-parse the CSV and pipeline encode→POST — batch
    # N+1 parses on this thread while batch N's POST is in flight
    # (double-buffer); --concurrency > 1 keeps that many POSTs in
    # flight, which the server routes per shard server-side.
    import collections
    from concurrent.futures import ThreadPoolExecutor

    host = args.host.rstrip("/")
    # <= 0 means "auto" (bare `or` would let a negative through, turning
    # every CSV line into its own single-row POST)
    batch = args.batch_size if args.batch_size > 0 else DEFAULT_IMPORT_BATCH
    limit = _probe_batch_limit(host)
    if limit > 0:
        batch = min(batch, limit)
    workers = max(1, args.concurrency)
    if args.values:
        path = f"/index/{args.index}/field/{args.field}/import-value"
        payloads = (
            {"columns": cols, "values": vals, "clear": args.clear}
            for cols, vals in _iter_csv_values(args.files, batch)
        )
    else:
        path = f"/index/{args.index}/field/{args.field}/import"

        def _bit_payloads():
            for rows, cols, ts in _iter_csv_bits(args.files, batch):
                p = {"rows": rows, "columns": cols, "clear": args.clear}
                if ts:
                    p["timestamps"] = ts
                yield p

        payloads = _bit_payloads()
    total = 0
    try:
        if args.create:
            _http_create(host, args)
        inflight: collections.deque = collections.deque()
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for payload in payloads:
                inflight.append(
                    pool.submit(_post_import, host, path, payload)
                )
                while len(inflight) > workers:
                    total += inflight.popleft().result()
            while inflight:
                total += inflight.popleft().result()
    except _HTTPStatusError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except (OSError, http.client.HTTPException) as e:
        # transport-stage failure through the pooled client: connect
        # refused/unreachable, or a server dying mid-stream (reset,
        # RemoteDisconnected on a fresh connection) — same user-facing
        # failure, same exit
        print(f"error: connection to {host} failed: {e}", file=sys.stderr)
        return 1
    print(f"imported: {total} bits changed")
    return 0


def _http_create(host: str, args) -> None:
    """Best-effort schema creation for --create in HTTP mode (409 = exists)."""
    for url, body in (
        (f"{host}/index/{args.index}", {}),
        (
            f"{host}/index/{args.index}/field/{args.field}",
            {"options": {"type": "int", "min": args.min, "max": args.max}}
            if args.values
            else {},
        ),
    ):
        try:
            _http("POST", url, json.dumps(body).encode())
        except _HTTPStatusError as e:
            if e.code != 409:
                raise


def cmd_export(args) -> int:
    if args.data_dir:
        api = _in_process_api(args.data_dir)
        sys.stdout.write(api.export_csv(args.index, args.field))
        api.holder.close()
        return 0
    host = args.host.rstrip("/")
    url = f"{host}/export?index={args.index}&field={args.field}"
    with urllib.request.urlopen(url) as resp:
        sys.stdout.write(resp.read().decode())
    return 0


def cmd_config(args) -> int:
    cfg = _load_config(args.config)
    from pilosa_tpu.server import ServerConfig

    print(json.dumps(ServerConfig.from_dict(cfg).to_dict(), indent=2))
    return 0


def cmd_generate_config(args) -> int:
    print(_DEFAULT_TOML, end="")
    return 0


def cmd_inspect(args) -> int:
    """Dump fragment/container statistics from a data dir (reference
    ctl/inspect.go)."""
    from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN
    from pilosa_tpu.storage import Holder

    holder = Holder(args.data_dir).open()
    kind_names = {ARRAY: "array", BITMAP: "bitmap", RUN: "run"}
    for iname, idx in sorted(holder.indexes.items()):
        for fname, field in sorted(idx.fields.items()):
            for vname, view in sorted(field.views.items()):
                for shard, frag in sorted(view.fragments.items()):
                    kinds = {"array": 0, "bitmap": 0, "run": 0}
                    for key in frag.bitmap.keys:
                        kinds[kind_names[frag.bitmap.container(key).kind]] += 1
                    print(
                        f"{iname}/{fname}/{vname}/{shard}: "
                        f"bits={frag.count()} rows={len(frag.row_ids())} "
                        f"containers={len(frag.bitmap.keys)} {kinds} "
                        f"ops={frag.op_n}"
                    )
    holder.close()
    return 0


def cmd_backup(args) -> int:
    """Back up to an incremental manifest directory (the default — only
    blocks changed since any previous generation are written; see
    docs/OPERATIONS.md runbook), or to a legacy whole-tree tar.gz when
    the output path ends in .tar.gz/.tgz. ``--host`` backs up a LIVE
    cluster over the anti-entropy wire (compressed, pacer-shaped);
    ``-d`` walks a data dir in-process and must only run against a
    STOPPED node."""
    if args.output.endswith((".tar.gz", ".tgz")):
        import tarfile

        if not args.data_dir:
            print("error: tar.gz backup requires -d/--data-dir",
                  file=sys.stderr)
            return 1
        data_dir = os.path.expanduser(args.data_dir)
        if not os.path.isdir(data_dir):
            print(f"error: no data dir {data_dir}", file=sys.stderr)
            return 1
        with tarfile.open(args.output, "w:gz") as tar:
            tar.add(data_dir, arcname=".")
        print(f"backed up {data_dir} -> {args.output}")
        return 0
    from pilosa_tpu.storage.backup import backup_from_host, backup_holder

    if args.data_dir:
        from pilosa_tpu.storage import Holder

        if not os.path.isdir(os.path.expanduser(args.data_dir)):
            # same validation the tar path always had: a typo'd path
            # must not produce a confidently empty "backup"
            print(f"error: no data dir {args.data_dir}", file=sys.stderr)
            return 1
        holder = Holder(args.data_dir).open()
        try:
            manifest = backup_holder(holder, args.output)
        finally:
            holder.close()
    else:
        from pilosa_tpu.parallel.client import InternalClient

        client = InternalClient(timeout=300.0)
        if args.max_bytes_per_sec > 0:
            # ride the PR-4 repair pacer so a backup storm can't starve
            # the serving traffic of the node it reads from
            from pilosa_tpu.parallel.pacer import RepairPacer

            client.pacer = RepairPacer(
                max_bytes_per_sec=args.max_bytes_per_sec
            )
        try:
            manifest = backup_from_host(args.host, args.output,
                                        client=client)
        except Exception as e:
            print(f"error: backup from {args.host} failed: {e}",
                  file=sys.stderr)
            return 1
    print(
        f"backup generation {manifest['generation']} -> {args.output}: "
        f"{len(manifest['fragments'])} fragments, "
        f"{manifest['newBlobs']} new blobs, "
        f"{manifest['reusedBlobs']} reused"
    )
    return 0


def cmd_restore(args) -> int:
    data_dir = os.path.expanduser(args.data_dir)
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        print(f"error: {data_dir} exists and is not empty", file=sys.stderr)
        return 1
    if os.path.isfile(args.input):  # legacy whole-tree archive
        import tarfile

        os.makedirs(data_dir, exist_ok=True)
        with tarfile.open(args.input, "r:gz") as tar:
            tar.extractall(data_dir, filter="data")
        print(f"restored {args.input} -> {data_dir}")
        return 0
    from pilosa_tpu.storage.backup import restore_holder

    try:
        manifest = restore_holder(args.input, data_dir,
                                  generation=args.generation,
                                  as_of=args.as_of)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    msg = (
        f"restored generation {manifest['generation']} -> {data_dir}: "
        f"{manifest['restoredFragments']} fragments (digest-verified)"
    )
    if args.as_of is not None:
        msg += (f"; replayed {manifest['replayedOps']} ops to seq "
                f"{manifest['asOfSeq']}")
        if manifest.get("skippedReplayOps"):
            msg += f" ({manifest['skippedReplayOps']} skipped)"
    print(msg)
    return 0


def cmd_check(args) -> int:
    """Integrity check (reference ctl/check.go, grown into the scrub
    front door — docs/OPERATIONS.md integrity runbook): with ``-d``,
    an OFFLINE scrub of a data dir — every fragment file decoded AND
    its block digests verified against the ``.checksums`` sidecar
    (exactly what verify-on-load does at open); with ``--host``, a
    LIVE scrub pass triggered on a running node (``POST
    /internal/scrub`` — the node verifies its own disk bytes,
    quarantines rot, and read-repairs from replicas). Exit 1 when
    anything is corrupt or already quarantined."""
    if getattr(args, "host", None):
        url = f"{args.host.rstrip('/')}/internal/scrub"
        try:
            out = _http("POST", url, b"")
        except Exception as e:
            print(f"error: live scrub via {url} failed: {e}",
                  file=sys.stderr)
            return 1
        print(
            f"live scrub: scanned={out.get('scanned', 0)} "
            f"bytes={out.get('bytes', 0)} corrupt={out.get('corrupt', 0)} "
            f"repaired={out.get('repaired', 0)} "
            f"self_healed={out.get('self_healed', 0)} "
            f"unrepaired={out.get('unrepaired', 0)}"
        )
        return 1 if out.get("unrepaired", 0) else 0
    if not args.data_dir:
        print("error: check needs -d/--data-dir or --host",
              file=sys.stderr)
        return 1
    import glob

    from pilosa_tpu.roaring.format import replay_ops
    from pilosa_tpu.storage import integrity

    bad = 0
    data_dir = os.path.expanduser(args.data_dir)
    pattern = os.path.join(data_dir, "**", "fragments", "*")
    for path in sorted(glob.glob(pattern, recursive=True)):
        if (not os.path.isfile(path)
                or path.endswith((".cache", integrity.CHECKSUM_SUFFIX))
                or integrity.is_quarantined(os.path.basename(path))):
            continue
        try:
            bitmap, data, ops_at = integrity.verify_fragment_file(path)
            n_ops = replay_ops(bitmap, data, ops_at)
            print(f"ok: {path} bits={bitmap.count()} ops={n_ops}")
        except Exception as e:
            bad += 1
            print(f"CORRUPT: {path}: {e}", file=sys.stderr)
    quarantined = integrity.list_quarantined(data_dir)
    for q in quarantined:
        print(f"QUARANTINED: {q}", file=sys.stderr)
    return 1 if bad or quarantined else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pilosa-tpu", description="TPU-native distributed bitmap index"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("server", help="run a server node")
    p.add_argument("-c", "--config", help="TOML config file")
    p.add_argument("-d", "--data-dir")
    p.add_argument("-b", "--bind")
    p.add_argument("--port", type=int)
    p.add_argument("--verbose", action="store_true")
    p.set_defaults(fn=cmd_server)

    # internal: one SO_REUSEPORT serving worker (spawned by the owner)
    p = sub.add_parser("serve-worker")
    p.add_argument("--handshake-sock", required=True)
    p.add_argument("--listen-fd", type=int, required=True)
    p.add_argument("--worker-id", type=int, required=True)
    p.set_defaults(fn=cmd_serve_worker)

    p = sub.add_parser("import", help="bulk-import CSV (row,col[,ts] or col,value)")
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--field", required=True)
    p.add_argument("--host", default=DEFAULT_HOST)
    p.add_argument("-d", "--data-dir", help="import in-process against a data dir")
    p.add_argument("--values", action="store_true", help="CSV is col,value (int field)")
    p.add_argument("--clear", action="store_true")
    p.add_argument("--create", action="store_true", help="create index/field if missing")
    p.add_argument("--min", type=int, default=0)
    p.add_argument("--max", type=int, default=1 << 32)
    p.add_argument("--batch-size", type=int, default=0,
                   help="rows per HTTP batch (default 100000, clamped to "
                        "the server's max-writes-per-request)")
    p.add_argument("--concurrency", type=int, default=1,
                   help="parallel in-flight POSTs (server routes per "
                        "shard); >1 reorders batches, so duplicate "
                        "columns across batches lose write order")
    p.add_argument("files", nargs="+", help="CSV files ('-' for stdin)")
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("export", help="export field as CSV")
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--field", required=True)
    p.add_argument("--host", default=DEFAULT_HOST)
    p.add_argument("-d", "--data-dir")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("config", help="echo resolved config")
    p.add_argument("-c", "--config")
    p.set_defaults(fn=cmd_config)

    p = sub.add_parser("generate-config", help="print default TOML config")
    p.set_defaults(fn=cmd_generate_config)

    p = sub.add_parser("inspect", help="dump fragment statistics")
    p.add_argument("-d", "--data-dir", required=True)
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser(
        "check",
        help="verify fragment files against their checksum sidecars "
             "(offline -d scrub, or --host live scrub trigger)",
    )
    p.add_argument("-d", "--data-dir",
                   help="offline scrub of a data dir (node stopped)")
    p.add_argument("--host",
                   help="trigger a live scrub pass on a running node")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "backup",
        help="incremental manifest backup of a data dir or live cluster "
             "(legacy tar.gz when -o ends in .tar.gz)",
    )
    p.add_argument("-d", "--data-dir",
                   help="back up a data dir in-process (node must be "
                        "stopped)")
    p.add_argument("--host", default=DEFAULT_HOST,
                   help="back up a LIVE cluster over the sync wire "
                        "(fragment data; keyed/attr stores need -d)")
    p.add_argument("-o", "--output", required=True,
                   help="backup directory (or .tar.gz path for legacy)")
    p.add_argument("--max-bytes-per-sec", type=int, default=0,
                   help="pace live-backup transfers (0 = unpaced)")
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser(
        "restore",
        help="restore a backup directory (or legacy tar.gz) into an "
             "empty data dir",
    )
    p.add_argument("-d", "--data-dir", required=True)
    p.add_argument("-i", "--input", required=True)
    p.add_argument("--generation", type=int, default=None,
                   help="generation to restore (default: latest)")
    p.add_argument("--as-of", type=int, default=None, dest="as_of",
                   help="restore to an exact WAL seq: nearest anchored "
                        "generation + change-feed replay (needs backups "
                        "taken from a group-durability WAL)")
    p.set_defaults(fn=cmd_restore)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=lambda a: (print(__version__), 0)[1])

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
