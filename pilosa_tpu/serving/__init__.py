"""Multi-process serving tier (docs/OPERATIONS.md deployment shapes).

The single-process serving ceiling is the Python interpreter, not the
device (BENCH_SUITE ``ceiling_note``): ~1.7 ms of single-interpreter
HTTP + API work per request plateaus one node near ~830 QPS while the
accelerator idles. This package shatters that ceiling with the standard
deployment shape for Python services, adapted to a device-owning
backend:

- N ``SO_REUSEPORT`` **worker processes** accept HTTP on the public
  port and run the per-request host work (socket handling, header/QoS
  envelope, PQL parse, admission, degraded-mode shedding, response
  writes) — the GIL-bound ~70% of a request;
- ONE **device-owner process** (the plain Server) keeps the holder,
  WAL, and device caches, and executes queries submitted by the
  workers;
- submissions cross a **pickle-free shared-memory ring** per worker
  (``shmring.py``): fixed-slot rings of length-prefixed bytes with
  torn-record-safe framing and backpressure instead of unbounded
  queueing — worker waves group-commit into the owner's micro-batched
  dispatches, the third instance of the group-commit shape after the
  WAL fsync groups and the remote wave batcher.

``mpserve.py`` holds both halves (OwnerRuntime + the worker entry);
platforms without ``SO_REUSEPORT`` fall back to single-process mode.
"""

from pilosa_tpu.serving.shmring import (
    RingFull,
    ShmRing,
    decode_frame,
    encode_frame,
)

__all__ = ["RingFull", "ShmRing", "decode_frame", "encode_frame"]
