"""Write-invalidated result cache for the serving fast lane.

PR 3's wave dedupe proved that under skewed traffic identical plain edge
reads dominate: within one wave they submit once and share pre-serialized
response bytes. This module persists that memo ACROSS waves — the
actuation half of ROADMAP open item 3's result-cache leg:

- **Keyed** by ``(scope, index, normalized PQL)`` with the SAME
  eligibility test as the pipeline's ``_SharedDeferred`` dedupe: a plain
  edge read — PQL string, no explicit shards, no deadline, no result
  options, read-only, pipeline-coalescable. Normalization is the
  whitespace trim the dedupe key already implies (identical strings are
  identical requests; PQL inside quoted keys is never rewritten).
- **Valued** by the pre-serialized ``{"results": [...]}`` response bytes
  (executor/result.py) — a hit costs no parse, no plan, no dispatch, no
  json.dumps.
- **Invalidated** per ``(scope, index, field, shard)`` write event at the
  same WAL-visible write points the heat counters hook — every fragment
  mutation (PQL writes, bulk imports, roaring bodies, WAL replay,
  read-repair swaps) routes through ``Fragment._after_row_write`` /
  ``_after_rows_added``, which call :func:`invalidate_write`
  unconditionally (the cost kill switch gates accounting, never
  correctness). Attr writes, TopN cache recounts, and schema deletes
  invalidate index-wide via :func:`invalidate_index_wide`.
- **Race-safe fills** use the same cutoff discipline as the PR 11 mp
  dedupe ``on_submitted`` hook: the filler snapshots the global write
  version BEFORE execution starts; ``insert`` refuses when any of the
  entry's dependencies advanced past the snapshot, so a write
  group-committing concurrently with a fill can never be masked by the
  fill's stale bytes (an acked write is visible in memory — and
  invalidated here — before its WAL barrier releases the 200).

Dependency granularity: the field set is extracted from the parsed AST
for the provably field-local call shapes (Count/Row/Union/Intersect/
Xor/Difference/Shift/Range/Sum/Min/Max with explicit field references);
anything touching index-wide state (Not/All ride the existence field,
TopN rides the rank cache, GroupBy enumerates rows) depends on the WHOLE
index — conservative beats subtly stale. The write events themselves
always carry (index, field, shard); per-shard refinement buys nothing
here because cache-eligible queries never pin shards (a write can create
a brand-new shard the fill never saw).

Scope rules: entries are scope-qualified (the holder-unique tag, as in
frag_id/heat keys) so in-process multi-holder setups never serve each
other's bytes. A multi-node cluster edge result folds in REMOTE data
whose writes land on other nodes' fragments, so cluster edges are only
cacheable when the WAL-tailing CDC plane (pilosa_tpu/cdc/) is live:
every node tails its peers' committed-seq feeds and routes remote write
events through :meth:`ResultCache.invalidate` with the same dependency
keys and version fences as local writes. ``API`` refuses lookup/fill on
a cluster edge whenever the tailer is absent or unhealthy, and counts
WHY in :meth:`ResultCache.record_refusal` so operators can watch the
cache turn on after an upgrade (`/debug/rescache` refusals block).

Eviction is bounded by bytes and heat-weighted: each entry keeps a
decayed hit score (same lazy half-life decay as storage/heat.py), and
overflow evicts the coldest entries first — a burst of one-off queries
cannot flush the Zipf hot set.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.pql.ast import Condition

DEFAULT_HALF_LIFE_S = 300.0

# Eviction hysteresis: one overflow scan frees down to this fraction of
# the budget so a thrashing insert rate pays one O(entries) scan per
# batch of evictions, not one per insert.
EVICT_TO_FRACTION = 0.9

# Per-entry bookkeeping overhead charged against the byte budget beside
# the payload itself (key strings, dict slots, score fields).
ENTRY_OVERHEAD_BYTES = 256

# Bound on the fill-race fence table: every first write to a distinct
# (scope, index, field) adds a version record whether or not any entry
# depends on it, so index/field churn would otherwise grow it forever
# (the same cardinality concern the cost ledger bounds with _MAX_PAIRS).
MAX_DEP_VERSIONS = 4096


class _Entry:
    __slots__ = ("payload", "fields", "score", "touched", "created",
                 "hits", "nbytes")

    def __init__(self, payload: bytes, fields: frozenset | None,
                 key_len: int, now: float):
        self.payload = payload
        self.fields = fields  # None = depends on the whole index
        self.score = 1.0  # decayed hit heat (the fill counts as one)
        self.touched = now
        self.created = now
        self.hits = 0
        self.nbytes = len(payload) + key_len + ENTRY_OVERHEAD_BYTES


class ResultCache:
    """Byte-bounded, write-invalidated map of pre-serialized responses.

    Thread-safe; every mutation happens under one lock (lookups are a
    dict get + float decay, writers a dict pop per registered entry).
    """

    def __init__(self, budget_bytes: int = 0,
                 half_life_s: float = DEFAULT_HALF_LIFE_S):
        self.budget_bytes = int(budget_bytes)
        self.half_life_s = float(half_life_s) or DEFAULT_HALF_LIFE_S
        self._lock = threading.Lock()
        self._entries: dict[tuple, _Entry] = {}
        # dependency registry: (scope, index, field) -> entry keys, with
        # field None for index-wide (wildcard) dependents; plus a
        # per-index key set for whole-index invalidation
        self._by_dep: dict[tuple, set] = {}
        self._by_index: dict[tuple, set] = {}
        # write-version fence (the fill-race cutoff): a global counter,
        # with the value at each dependency's last invalidation
        self._version = 0
        self._floor = 0  # fills snapshotted before a clear() refuse
        self._dep_version: dict[tuple, int] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.invalidations = 0
        self.invalidated_entries = 0
        self.evictions = 0
        self.fill_races = 0
        # cluster-edge refusal reasons (API gate): why a cacheable
        # query was NOT served from / filled into the cache on a
        # multi-node edge — "cluster-no-cdc" before the CDC tailer is
        # wired (the pre-upgrade steady state), "cdc-stale" when the
        # tailer exists but a peer's feed is lagging its bound
        self.refusals: dict[str, int] = {}

    # ------------------------------------------------------------ config

    def configure(self, budget_bytes: int, half_life_s: float | None = None
                  ) -> "ResultCache":
        """Re-point the budget (Server.open). Shrinking evicts down to
        the new bound; a zero budget disables lookups and clears."""
        with self._lock:
            self.budget_bytes = int(budget_bytes)
            if half_life_s:
                self.half_life_s = float(half_life_s)
            if self.budget_bytes <= 0:
                self._clear_locked()
            else:
                self._evict_locked()
        return self

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    # ------------------------------------------------------------- reads

    def version(self) -> int:
        """The fill-race snapshot: take BEFORE execution starts; pass to
        ``insert`` so a dependency written after the snapshot refuses
        the stale fill."""
        with self._lock:
            return self._version

    def peek(self, scope: str, index: str, pql: str) -> bytes | None:
        """Payload bytes without counting a hit (the API peeks before
        the admission gate so a 429 shed doesn't inflate the hit
        counters); a served hit is recorded via ``record_hit``."""
        if self.budget_bytes <= 0:
            return None
        with self._lock:
            e = self._entries.get((scope, index, pql.strip()))
            return e.payload if e is not None else None

    def record_hit(self, scope: str, index: str, pql: str) -> None:
        now = time.monotonic()
        with self._lock:
            self.hits += 1
            e = self._entries.get((scope, index, pql.strip()))
            if e is not None:
                self._decay(e, now)
                e.score += 1.0
                e.hits += 1

    def record_miss(self) -> None:
        with self._lock:
            self.misses += 1

    def record_refusal(self, reason: str) -> None:
        """A cluster-edge query skipped the cache: count the reason so
        the /debug/rescache runbook can tell 'CDC not wired' apart from
        'CDC wired but lagging' at a glance."""
        with self._lock:
            self.refusals[reason] = self.refusals.get(reason, 0) + 1

    def lookup(self, scope: str, index: str, pql: str) -> bytes | None:
        """peek + hit/miss accounting in one call (tests, simple
        callers; the API façade uses the split form)."""
        payload = self.peek(scope, index, pql)
        if payload is None:
            if self.budget_bytes > 0:
                self.record_miss()
            return None
        self.record_hit(scope, index, pql)
        return payload

    # ------------------------------------------------------------- fills

    def insert(self, scope: str, index: str, pql: str, payload: bytes,
               fields: frozenset | set | None, snapshot: int) -> bool:
        """Install a fill captured at write-version ``snapshot``.
        Returns False (and counts a fill race) when any dependency was
        invalidated after the snapshot — the executed result may or may
        not contain that write, so the bytes must not outlive it."""
        if self.budget_bytes <= 0:
            return False
        key = (scope, index, pql.strip())
        deps = ([("f", scope, index, f) for f in sorted(fields)]
                if fields else [("w", scope, index)])
        now = time.monotonic()
        with self._lock:
            if snapshot < self._floor:
                # clear() fenced everything: the deps' invalidation
                # history is gone, so a pre-clear fill cannot prove
                # its freshness
                self.fill_races += 1
                return False
            # the index-wide epoch fences EVERY entry of the index
            # (schema deletes, attr writes, cache recounts)
            if self._dep_version.get(("e", scope, index), 0) > snapshot:
                self.fill_races += 1
                return False
            for dep in deps:
                if self._dep_version.get(dep, 0) > snapshot:
                    self.fill_races += 1
                    return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._deregister_locked(key, old)
            entry = _Entry(
                payload, frozenset(fields) if fields else None,
                len(scope) + len(index) + len(key[2]), now,
            )
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self.fills += 1
            regs = ([(scope, index, f) for f in entry.fields]
                    if entry.fields else [(scope, index, None)])
            for reg in regs:
                self._by_dep.setdefault(reg, set()).add(key)
            self._by_index.setdefault((scope, index), set()).add(key)
            self._evict_locked()
            return key in self._entries  # the fill itself may be coldest

    # ------------------------------------------------------- invalidation

    def invalidate(self, scope: str, index: str, field: str,
                   shard: int | None = None) -> int:
        """One (index, field, shard) write event (the WAL-visible write
        points — fragment mutation hooks). Drops every entry depending
        on the field plus every index-wide dependent, and advances the
        version fence so in-flight fills refuse to land."""
        with self._lock:
            self._version += 1
            v = self._version
            self._note_dep_locked(("f", scope, index, field), v)
            self._note_dep_locked(("w", scope, index), v)
            dropped = 0
            for reg in ((scope, index, field), (scope, index, None)):
                for key in list(self._by_dep.get(reg, ())):
                    dropped += self._drop_locked(key)
            # every write event counts, dropped entries or not, so
            # operators see the invalidation stream beside the fills
            self.invalidations += 1
            self.invalidated_entries += dropped
            return dropped

    def invalidate_index_wide(self, scope: str, index: str) -> int:
        """Index-scope invalidation: attr writes, TopN cache recounts,
        field/index deletes, restores — anything that can change results
        without a fragment write event."""
        with self._lock:
            self._version += 1
            self._note_dep_locked(("e", scope, index), self._version)
            dropped = 0
            for key in list(self._by_index.get((scope, index), ())):
                dropped += self._drop_locked(key)
            self.invalidations += 1
            self.invalidated_entries += dropped
            return dropped

    def _note_dep_locked(self, dep: tuple, v: int) -> None:
        """Record a dependency's invalidation version, keeping the table
        bounded: past MAX_DEP_VERSIONS the oldest half is dropped and the
        fill floor raised to the newest dropped version — a fill
        snapshotted before it can no longer prove its dependencies'
        history, so it refuses (counted as a fill race). A fill
        snapshotted at or after the floor is unaffected: every dropped
        record's version is <= the floor <= its snapshot, so the missing
        check could only have passed."""
        self._dep_version[dep] = v
        if len(self._dep_version) <= MAX_DEP_VERSIONS:
            return
        items = sorted(self._dep_version.items(), key=lambda kv: kv[1])
        cut = len(items) // 2
        for dep_key, _ in items[:cut]:
            del self._dep_version[dep_key]
        self._floor = max(self._floor, items[cut - 1][1])

    def clear(self) -> None:
        with self._lock:
            self._clear_locked()

    def _clear_locked(self) -> None:
        self._entries.clear()
        self._by_dep.clear()
        self._by_index.clear()
        self._bytes = 0
        self._version += 1
        # the version fence survives a clear: in-flight fills snapshotted
        # before it must not land after (their deps' history is gone)
        self._dep_version.clear()
        self._floor = self._version

    def _drop_locked(self, key: tuple) -> int:
        entry = self._entries.pop(key, None)
        if entry is None:
            return 0
        self._bytes -= entry.nbytes
        self._deregister_locked(key, entry)
        return 1

    def _deregister_locked(self, key: tuple, entry: _Entry) -> None:
        scope, index, _ = key
        regs = ([(scope, index, f) for f in entry.fields]
                if entry.fields else [(scope, index, None)])
        for reg in regs:
            keys = self._by_dep.get(reg)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_dep[reg]
        keys = self._by_index.get((scope, index))
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_index[(scope, index)]

    # ---------------------------------------------------------- eviction

    def _decay(self, entry: _Entry, now: float) -> None:
        dt = now - entry.touched
        if dt > 0:
            entry.score *= 0.5 ** (dt / max(self.half_life_s, 1e-9))
            entry.touched = now

    def _evict_locked(self) -> None:
        """Heat-weighted eviction: decay every entry's hit score and
        drop the coldest until under ``EVICT_TO_FRACTION`` of budget —
        one scan per overflow batch, so a hot Zipf head survives any
        burst of one-off fills."""
        if self._bytes <= self.budget_bytes:
            return
        now = time.monotonic()
        scored = []
        for key, entry in self._entries.items():
            self._decay(entry, now)
            scored.append((entry.score, key))
        scored.sort()
        target = int(self.budget_bytes * EVICT_TO_FRACTION)
        for _, key in scored:
            if self._bytes <= target:
                break
            self._drop_locked(key)
            self.evictions += 1

    # ------------------------------------------------------------- views

    def metrics(self) -> dict:
        with self._lock:
            return {
                "result_cache_entries": len(self._entries),
                "result_cache_bytes": self._bytes,
                "result_cache_budget_bytes": self.budget_bytes,
                "result_cache_hits_total": self.hits,
                "result_cache_misses_total": self.misses,
                "result_cache_fills_total": self.fills,
                "result_cache_invalidations_total": self.invalidations,
                "result_cache_invalidated_entries_total":
                    self.invalidated_entries,
                "result_cache_evictions_total": self.evictions,
                "result_cache_fill_races_total": self.fill_races,
                "result_cache_refusals_total": sum(self.refusals.values()),
            }

    def refusal_reasons(self) -> dict:
        with self._lock:
            return dict(self.refusals)

    def inspect(self, k: int = 100) -> dict:
        """GET /debug/rescache: the entry table hottest-first (decayed
        score, hits, bytes, age, dependency fields) plus totals —
        the runbook's first stop for a hot-tenant p99 regression."""
        now = time.monotonic()
        with self._lock:
            rows = []
            for (scope, index, pql), e in self._entries.items():
                self._decay(e, now)
                row = {
                    "index": index,
                    "pql": pql[:256],
                    "bytes": e.nbytes,
                    "hits": e.hits,
                    "score": round(e.score, 3),
                    "ageSeconds": round(now - e.created, 3),
                    "fields": (sorted(e.fields)
                               if e.fields is not None else None),
                }
                if scope:
                    row["scope"] = scope
                rows.append(row)
        rows.sort(key=lambda r: r["score"], reverse=True)
        if k:
            rows = rows[:k]
        out = self.metrics()
        out["halfLifeS"] = self.half_life_s
        out["refusals"] = self.refusal_reasons()
        out["entries"] = rows
        return out


# ------------------------------------------------------- field extraction
#
# AST → dependency field set, for the call shapes where every bit the
# result can depend on lives in an explicitly named field. Anything else
# returns None = depend on the whole index (correct by construction).

# Calls whose results are a pure function of their named fields' bits.
# Excluded on purpose: Not/All (read the hidden existence field),
# TopN (reads the fragment rank cache, rebuilt by recalculate-caches),
# Rows/GroupBy (enumerate row ids host-side), and every write call.
_FIELD_PRECISE = {"Count", "Row", "Union", "Intersect", "Difference",
                  "Xor", "Shift", "Range", "Sum", "Min", "Max"}

# Per-call parameters with a known field-independent meaning: skipping
# them is safe AND keeps the dependency set precise (Shift's count,
# Row/Range's time bounds).
_CALL_PARAM_ARGS = {
    "Shift": frozenset({"n"}),
    "Row": frozenset({"from", "to"}),
    "Range": frozenset({"from", "to"}),
}

# BSI aggregates name their field in the ``field=``/``_field=`` VALUE.
_FIELD_VALUE_CALLS = frozenset({"Sum", "Min", "Max"})

# Mirror of executor._RESERVED_ARGS: every key some call shape treats as
# a parameter rather than a field name. (Copied, not imported: the write
# hooks make storage/fragment.py import this module, and the executor
# imports storage — an import here would cycle.) A key from this set on
# a call where it is NOT a known parameter is ambiguous — "n", "from",
# "limit", ... are all legal field names, and whether the executor reads
# the key as a field is a contract that lives in another module. Bail to
# the whole-index dependency instead of guessing: a missed dependency
# serves stale bytes after an acked write, the one thing this cache must
# never do.
_AMBIGUOUS_ARGS = {"_field", "_col", "from", "to", "n", "limit", "offset",
                   "previous", "column", "filter", "field", "ids",
                   "timestamp", "excludeColumns", "shards", "aggregate",
                   "columnAttrs", "attrName", "attrValue", "like",
                   "threshold", "having"}


def _walk_fields(call, fields: set) -> bool:
    name = getattr(call, "name", None)
    if name == "Options":
        kids = getattr(call, "children", None) or ()
        return bool(kids) and all(_walk_fields(c, fields) for c in kids)
    if name not in _FIELD_PRECISE:
        return False
    args = getattr(call, "args", None) or {}
    params = _CALL_PARAM_ARGS.get(name, frozenset())
    for k, v in args.items():
        if isinstance(v, Condition):
            # Row(fare > 10): the key IS the field — condition_field()
            # applies no reserved-name filter, so neither do we
            fields.add(k)
        elif (k == "field" or k == "_field") and name in _FIELD_VALUE_CALLS:
            fields.add(str(v))  # Sum(field=sal)
        elif k in params:
            continue
        elif k in _AMBIGUOUS_ARGS or k.startswith("_"):
            return False  # conservative: depend on the whole index
        else:
            fields.add(k)  # Row(f=1): the key IS the field
    return all(_walk_fields(c, fields)
               for c in getattr(call, "children", ()) or ())


def query_field_deps(query) -> frozenset | None:
    """The field set a parsed READ query's result can depend on, or
    None when it must be treated as depending on the whole index."""
    fields: set = set()
    calls = getattr(query, "calls", None)
    if not calls:
        return None
    if not all(_walk_fields(c, fields) for c in calls):
        return None
    return frozenset(fields) if fields else None


# ------------------------------------------------------------- singleton
#
# One process-wide cache, scope-qualified keys (the heat/residency
# pattern): in-process multi-holder setups share the instance without
# sharing entries. Disabled (budget 0) until Server.open configures it.

_global_cache: ResultCache | None = None


def global_result_cache() -> ResultCache:
    global _global_cache
    if _global_cache is None:
        _global_cache = ResultCache(0)
    return _global_cache


def set_global_result_cache(cache: ResultCache) -> None:
    global _global_cache
    _global_cache = cache


def invalidate_write(scope: str, index: str, field: str,
                     shard: int | None = None) -> None:
    """The fragment-mutation hook (storage/fragment.py): one global
    read + a predicate when the cache is off — the write hot path's
    whole cost, same bar as the fault plane's off state."""
    cache = _global_cache
    if cache is not None and cache.budget_bytes > 0:
        cache.invalidate(scope, index, field, shard)


def invalidate_index_wide(scope: str, index: str) -> None:
    cache = _global_cache
    if cache is not None and cache.budget_bytes > 0:
        cache.invalidate_index_wide(scope, index)
