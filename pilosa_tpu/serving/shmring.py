"""Pickle-free shared-memory rings for the multi-process serving tier.

One ``ShmRing`` is a fixed-slot single-producer/single-consumer ring of
length-prefixed byte records over ``multiprocessing.shared_memory`` —
the IPC primitive between ``SO_REUSEPORT`` worker processes and the
device-owner process (serving/mpserve.py). Design constraints, in
order:

- **Pickle-free**: records are raw bytes (compact-JSON frame headers +
  pre-serialized payloads — queries and results have been compact bytes
  since the PR-3 fast lane). Nothing is ever unpickled from shared
  memory, so a corrupt or malicious peer can at worst produce a frame
  that fails validation, never arbitrary object construction.
- **Torn-record-safe framing**: each slot carries ``(seq, len, crc32)``
  ahead of its payload. A record becomes visible only when the
  producer's head cursor advances (written last), and the consumer
  re-validates seq + bounds + crc before trusting a byte — a producer
  dying mid-write leaves an invisible record; memory tearing or
  corruption is detected, counted (``torn``), and skipped, never
  decoded into garbage or an exception loop.
- **Backpressure instead of unbounded queueing**: ``push`` returns
  ``False`` when the ring lacks space (``full_rejects`` counts), and
  the caller sheds (429 at the worker edge) — the same
  nothing-queues-unboundedly rule the admission gate enforces in front
  of the wave pipeline (qos/admission.py).
- **SPSC across processes, thread-safe within one**: exactly one
  producer process and one consumer process per ring (the MPSC submit
  path is N per-worker rings drained by one owner — fan-in without
  cross-process producer arbitration); each side guards its own cursor
  with an in-process lock so many worker handler threads (or owner pool
  threads) can share a ring end.

Records larger than one slot span consecutive slots (a continuation bit
rides the length word) — a big Row response does not need a bigger ring,
just more slots of it.
"""

from __future__ import annotations

import json
import struct
import threading
import zlib
from multiprocessing import shared_memory

# Header: magic u32 | slots u32 | slot_bytes u32 | waiting u32 |
#         head u64 | tail u64 | (pad to 64)
_MAGIC = 0x50524E47  # "PRNG" — pilosa ring
_HDR_FMT = "<IIII"
_HDR_SIZE = 64
_WAIT_OFF = 12
_HEAD_OFF = 16
_TAIL_OFF = 24
# Per-slot header: seq u64 | len u32 (bit 31 = continuation follows,
# bit 30 = first chunk of a record — lets the consumer skip a torn
# record's WHOLE chunk chain instead of reassembling a headless tail) |
# crc32 u32
_SLOT_HDR = struct.Struct("<QII")
_MORE = 0x80000000
_FIRST = 0x40000000
_LEN_MASK = 0x3FFFFFFF


class RingFull(Exception):
    """The ring lacks space for this record — shed, don't queue."""


def encode_frame(header: dict, body: bytes = b"") -> bytes:
    """One wire record: ``u32 header_len | compact-JSON header | body``.
    The header carries routing metadata (request id, index, tenant,
    deadline budget, trace context); the body is the already-serialized
    payload bytes — no pickling anywhere."""
    h = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack("<I", len(h)) + h + body


def decode_frame(record: bytes) -> tuple[dict, bytes]:
    """Inverse of :func:`encode_frame`. Raises ``ValueError`` on a
    malformed record (bad length prefix, non-JSON header) — the caller
    drops the frame, it never reaches execution."""
    if len(record) < 4:
        raise ValueError(f"frame too short ({len(record)} bytes)")
    (hlen,) = struct.unpack_from("<I", record)
    if hlen > len(record) - 4:
        raise ValueError(
            f"frame header length {hlen} exceeds record ({len(record)})"
        )
    header = json.loads(record[4:4 + hlen])
    if not isinstance(header, dict):
        raise ValueError("frame header is not an object")
    return header, record[4 + hlen:]


class ShmRing:
    """Fixed-slot SPSC byte ring in a named shared-memory segment."""

    def __init__(self, shm: shared_memory.SharedMemory, created: bool):
        self._shm = shm
        self._created = created
        buf = shm.buf
        magic, slots, slot_bytes, _ = struct.unpack_from(_HDR_FMT, buf, 0)
        if magic != _MAGIC:
            raise ValueError(f"not a pilosa ring: {shm.name}")
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._slot_size = _SLOT_HDR.size + slot_bytes
        self._buf = buf
        # in-process thread safety only; cross-process safety comes from
        # the SPSC protocol (each cursor has exactly one writing process)
        self._plock = threading.Lock()
        self._clock = threading.Lock()
        # local-side counters (each end keeps its own; exported via the
        # serving metrics block)
        self.pushed = 0
        self.popped = 0
        self.full_rejects = 0
        self.torn = 0

    # ------------------------------------------------------------ lifecycle

    @classmethod
    def create(cls, name: str, slots: int, slot_bytes: int) -> "ShmRing":
        if slots < 2:
            raise ValueError(f"ring needs >= 2 slots, got {slots}")
        if slot_bytes < 256:
            raise ValueError(f"slot_bytes must be >= 256, got {slot_bytes}")
        size = _HDR_SIZE + slots * (_SLOT_HDR.size + slot_bytes)
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        struct.pack_into(_HDR_FMT, shm.buf, 0, _MAGIC, slots, slot_bytes, 0)
        struct.pack_into("<QQ", shm.buf, _HEAD_OFF, 0, 0)
        return cls(shm, created=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name)
        try:
            # the attaching process must not let its resource tracker
            # unlink (or warn about) a segment the creator owns
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker internals are CPython
            pass           # detail; double-unlink is handled either way
        return cls(shm, created=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        """Remove the backing segment (creator side, after both ends
        closed or the peer died)."""
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    # -------------------------------------------------------------- cursors

    def _head(self) -> int:
        return struct.unpack_from("<Q", self._buf, _HEAD_OFF)[0]

    def _tail(self) -> int:
        return struct.unpack_from("<Q", self._buf, _TAIL_OFF)[0]

    def depth(self) -> int:
        """Published-but-unconsumed slots (a gauge, racy by nature)."""
        return max(0, self._head() - self._tail())

    # --------------------------------------------------- doorbell coalescing

    # Producers notify a sleeping consumer out of band (the mpserve
    # doorbell byte on the handshake socket). A doorbell per record is a
    # syscall per record under lock contention — measurably the top cost
    # of the whole IPC path — so the consumer DECLARES when it is about
    # to block (``set_waiting`` then a final ``depth`` check, closing the
    # lost-wakeup race), and producers ring only when ``take_waiting``
    # observes a declared sleeper. Races are benign: at worst an extra
    # doorbell, never a lost one.

    def set_waiting(self) -> None:
        struct.pack_into("<I", self._buf, _WAIT_OFF, 1)

    def take_waiting(self) -> bool:
        if struct.unpack_from("<I", self._buf, _WAIT_OFF)[0]:
            struct.pack_into("<I", self._buf, _WAIT_OFF, 0)
            return True
        return False

    # ------------------------------------------------------------- producer

    def push(self, data: bytes) -> bool:
        """Publish one record; ``False`` = insufficient free slots (the
        backpressure signal — callers shed, nothing queues)."""
        nchunks = max(1, -(-len(data) // self.slot_bytes))
        if nchunks > self.slots:
            raise RingFull(
                f"record of {len(data)} bytes exceeds ring capacity "
                f"({self.slots} slots x {self.slot_bytes} bytes)"
            )
        buf = self._buf
        with self._plock:
            head = self._head()
            if head + nchunks - self._tail() > self.slots:
                self.full_rejects += 1
                return False
            for i in range(nchunks):
                chunk = data[i * self.slot_bytes:(i + 1) * self.slot_bytes]
                off = _HDR_SIZE + ((head + i) % self.slots) * self._slot_size
                buf[off + _SLOT_HDR.size:
                    off + _SLOT_HDR.size + len(chunk)] = chunk
                length = (len(chunk)
                          | (_MORE if i < nchunks - 1 else 0)
                          | (_FIRST if i == 0 else 0))
                _SLOT_HDR.pack_into(buf, off, head + i + 1, length,
                                    zlib.crc32(chunk))
            # publish LAST: the record set is invisible until head moves,
            # so a producer crash mid-write leaves nothing half-readable
            struct.pack_into("<Q", buf, _HEAD_OFF, head + nchunks)
            self.pushed += 1
        return True

    # ------------------------------------------------------------- consumer

    def pop(self) -> bytes | None:
        """Consume one record, or ``None`` when the ring is empty or the
        next record failed validation (counted in ``torn`` and skipped —
        the caller just polls again)."""
        with self._clock:
            rec, _ = self._pop_locked()
            return rec

    def _pop_locked(self) -> tuple[bytes | None, bool]:
        """One record with ``_clock`` already held. Returns ``(record,
        progressed)``: ``(None, True)`` = a torn record was consumed
        and skipped, ``(None, False)`` = ring empty."""
        buf = self._buf
        if buf is None:  # closed concurrently (shutdown/reap race)
            return None, False
        tail = struct.unpack_from("<Q", buf, _TAIL_OFF)[0]
        head = struct.unpack_from("<Q", buf, _HEAD_OFF)[0]
        if tail >= head:
            return None, False
        parts: list[bytes] = []
        first = True
        while True:
            off = _HDR_SIZE + (tail % self.slots) * self._slot_size
            seq, length, crc = _SLOT_HDR.unpack_from(buf, off)
            more = bool(length & _MORE)
            is_first = bool(length & _FIRST)
            length &= _LEN_MASK
            payload = bytes(
                buf[off + _SLOT_HDR.size:off + _SLOT_HDR.size + length]
            ) if length <= self.slot_bytes else b""
            if (seq != tail + 1 or length > self.slot_bytes
                    or zlib.crc32(payload) != crc
                    or is_first != first):
                # torn/corrupt record: consume this slot AND any
                # published continuation chunks of the same record
                # (a valid-looking continuation must never be
                # reassembled into a headless record), surface
                # nothing
                self.torn += 1
                tail += 1
                while tail < head:
                    off = (_HDR_SIZE
                           + (tail % self.slots) * self._slot_size)
                    seq2, length2, _ = _SLOT_HDR.unpack_from(buf, off)
                    if seq2 != tail + 1 or (length2 & _FIRST):
                        break  # next record (or unreadable slot)
                    tail += 1
                struct.pack_into("<Q", buf, _TAIL_OFF, tail)
                return None, True
            parts.append(payload)
            tail += 1
            first = False
            if not more:
                struct.pack_into("<Q", buf, _TAIL_OFF, tail)
                self.popped += 1
                return b"".join(parts), True
            if tail >= head:
                # continuation promised but not published — cannot
                # happen with a live correct producer (head moves
                # after the whole record); treat as torn
                self.torn += 1
                struct.pack_into("<Q", buf, _TAIL_OFF, tail)
                return None, True

    def pop_many(self, limit: int | None = None) -> list[bytes]:
        """Consume up to ``limit`` records (all published records when
        ``None``) under ONE consumer-lock acquisition. Torn records are
        counted and skipped without ending the batch. This is the
        drain-side half of the doorbell-coalescing design: the owner's
        per-record cost at plateau was dominated by lock/cursor
        round-trips in ``pop`` (PROFILE'd at ~9.7us/record vs ~4.4us
        batched), not by the payload copies."""
        out: list[bytes] = []
        with self._clock:
            while limit is None or len(out) < limit:
                rec, progressed = self._pop_locked()
                if rec is not None:
                    out.append(rec)
                elif not progressed:
                    break  # empty — torn skips keep draining
        return out

    def drain(self, limit: int | None = None) -> list[bytes]:
        """Pop until empty (or ``limit`` records) — one drain per
        doorbell is how worker waves reach the owner as a batch."""
        return self.pop_many(limit)

    # ------------------------------------------------------ dead-peer reap

    def reclaim(self) -> int:
        """Drop every unconsumed record and return how many were lost.
        Only valid once the PEER process is known dead (worker reaped by
        the owner, or an owner restart detected by a worker): the
        surviving side resets the consumer cursor so the ring is
        immediately reusable and nothing is left half-in-flight."""
        with self._plock, self._clock:
            buf = self._buf
            if buf is None:  # already closed (shutdown beat the reap)
                return 0
            head = struct.unpack_from("<Q", buf, _HEAD_OFF)[0]
            tail = struct.unpack_from("<Q", buf, _TAIL_OFF)[0]
            dropped = 0
            # count RECORDS (one _FIRST chunk each; continuation chunks
            # collapse), best-effort: the headers may themselves be
            # torn, in which case each unreadable slot counts as one
            while tail < head:
                off = _HDR_SIZE + (tail % self.slots) * self._slot_size
                seq, length, _ = _SLOT_HDR.unpack_from(buf, off)
                tail += 1
                if seq != tail or (length & _FIRST):
                    dropped += 1
            struct.pack_into("<Q", buf, _TAIL_OFF, head)
            return dropped

    def metrics(self) -> dict:
        return {
            "depth": self.depth(),
            "pushed": self.pushed,
            "popped": self.popped,
            "full_rejects": self.full_rejects,
            "torn": self.torn,
        }
