"""Multi-process serving tier: SO_REUSEPORT workers + one device owner.

Topology (docs/OPERATIONS.md "Deployment shapes"):

- The **device-owner process** is the ordinary Server: it keeps the
  holder, WAL, device caches, cluster membership, and every /debug
  surface, but binds its full HTTP server on loopback only.
- ``OwnerRuntime`` (in the owner) spawns N **worker processes**, each
  inheriting its own ``SO_REUSEPORT`` listening socket on the PUBLIC
  bind:port — the kernel load-balances client connections across them,
  so the GIL-bound per-request host work (HTTP parse, QoS envelope, PQL
  parse, admission, response writes) runs on N interpreters.
- Workers submit edge JSON queries over a per-worker pair of
  **pickle-free shared-memory rings** (serving/shmring.py): submit ring
  worker→owner, response ring owner→worker. Everything else (imports,
  protobuf, ``?profile=true``, remote hops, /debug, /internal) proxies
  to the owner's loopback listener over a keep-alive pool — rare or
  internal traffic where byte-exact behavior matters more than the hop.
- A line-delimited **unix-socket handshake channel** per worker carries
  ring names, config, doorbell bytes (``!``), and finished worker-side
  trace trees. Worker death = socket EOF → the owner reaps the dead
  worker's in-flight ring slots (``ShmRing.reclaim``) and respawns;
  owner death/restart = socket EOF on the worker → re-handshake loop,
  then exit if the owner stays gone.

Contracts carried across the IPC boundary:

- **WAL ACK barrier**: the owner's ``api.query_raw`` runs ``_ack_durable``
  before the response frame is pushed, so a worker's 200 still means
  fsynced.
- **Tenant/cost/SLO**: the tenant rides the frame header; the owner
  runs the request under a CostContext and bills egress by the payload
  it produced — ``/debug/tenants`` stays the single source of truth.
- **Tracing**: the worker roots the edge span (sampling decision
  worker-side), ships ``trace_id:span_id`` in the frame; the owner
  roots an ``rpc.query`` remote span and returns the finished subtree
  in the response frame, which the worker grafts under its root — the
  same remote-leg shape as cross-node hops — and ships the finished
  tree back so the owner's ``/debug/traces`` renders it.
- **Degraded shedding**: the owner publishes cluster/storage degraded
  flags into a shared control block; workers shed writes 503
  worker-side without a ring round-trip (the owner re-checks
  authoritatively).
- **Backpressure**: a full submit ring sheds 429 at the worker; the
  owner drains rings only as fast as its bounded executor pool frees
  capacity — nothing queues unboundedly on either side.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

from pilosa_tpu.qos import Deadline
from pilosa_tpu.serving.shmring import (
    RingFull,
    ShmRing,
    decode_frame,
    encode_frame,
)
from pilosa_tpu.utils.cost import cost_enabled
from pilosa_tpu.utils.tracing import global_tracer, use_span

# Messages on the handshake channel are newline-delimited: a bare `!` is
# a doorbell (ring has records), a `{...}` line is a JSON control
# message (hello/cfg/ready/trace).
_DOORBELL = b"!\n"

MAX_WORKERS = 64

# 503 texts workers answer WITHOUT a ring round trip, kept byte-exact
# with server/api.py's degraded errors (the owner re-checks
# authoritatively for anything that reaches it).
CLUSTER_DEGRADED_MSG = (
    "cluster degraded (no member quorum): writes are shed on "
    "this node until the partition heals; locally-owned reads "
    "still serve"
)


def storage_degraded_msg(reason: str) -> str:
    return (
        f"storage degraded ({reason}): writes are shed on "
        "this node until a probe write succeeds; reads still serve"
    )


def mp_unsupported_reason(config) -> str | None:
    """Why multi-process serving cannot run here (None = it can).
    Platforms without ``SO_REUSEPORT`` (and TLS-terminating nodes —
    workers would each need the key material) fall back to
    single-process mode instead of failing startup."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return "socket.SO_REUSEPORT is unavailable on this platform"
    if getattr(config, "tls_enabled", False):
        return "TLS termination is single-process only"
    return None


# --------------------------------------------------------------- control


class ControlBlock:
    """Tiny shared-memory block beside the rings: degraded flags +
    reason (owner-written, worker-read on each write request) and one
    fixed stats slot per worker (worker-written, owner-read for
    /metrics and /debug/workers). Single writer per field — no
    cross-process locking needed."""

    FLAG_CLUSTER_DEGRADED = 1
    FLAG_STORAGE_DEGRADED = 2

    _HDR = 256
    _SLOT = 128
    # per-worker slot: gen u32 | pid u32 | requests u64 | ring u64 |
    # proxied u64 | shed u64 | ring_full u64 | rtt_p50_us u32 |
    # rtt_p99_us u32
    _SLOT_FMT = struct.Struct("<IIQQQQQII")

    def __init__(self, shm, created: bool):
        self._shm = shm
        self._created = created
        self._buf = shm.buf

    @classmethod
    def create(cls, name: str) -> "ControlBlock":
        from multiprocessing import shared_memory

        size = cls._HDR + MAX_WORKERS * cls._SLOT
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        shm.buf[:size] = b"\0" * size
        return cls(shm, created=True)

    @classmethod
    def attach(cls, name: str) -> "ControlBlock":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — CPython tracker internals
            pass
        return cls(shm, created=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    # owner side -----------------------------------------------------------

    def set_flags(self, flags: int, reason: str = "") -> None:
        raw = reason.encode()[:200]
        struct.pack_into("<IH", self._buf, 0, flags, len(raw))
        self._buf[8:8 + len(raw)] = raw

    # worker side ----------------------------------------------------------

    def flags(self) -> int:
        return struct.unpack_from("<I", self._buf, 0)[0]

    def reason(self) -> str:
        (n,) = struct.unpack_from("<H", self._buf, 4)
        return bytes(self._buf[8:8 + min(n, 200)]).decode(errors="replace")

    def write_worker(self, wid: int, gen: int, pid: int, requests: int,
                     ring: int, proxied: int, shed: int, ring_full: int,
                     rtt_p50_us: int, rtt_p99_us: int) -> None:
        self._SLOT_FMT.pack_into(
            self._buf, self._HDR + wid * self._SLOT, gen, pid, requests,
            ring, proxied, shed, ring_full,
            min(rtt_p50_us, 0xFFFFFFFF), min(rtt_p99_us, 0xFFFFFFFF),
        )

    def read_worker(self, wid: int) -> dict:
        (gen, pid, requests, ring, proxied, shed, ring_full, p50,
         p99) = self._SLOT_FMT.unpack_from(
            self._buf, self._HDR + wid * self._SLOT)
        return {
            "gen": gen, "pid": pid, "requests": requests,
            "ringRequests": ring, "proxied": proxied, "shed": shed,
            "ringFull": ring_full, "ringRttP50Us": p50,
            "ringRttP99Us": p99,
        }


# ------------------------------------------------------------- owner side


class _SharedExec:
    """One in-flight dedupe-eligible ring query's share point: followers
    that arrive while the leader's wave has NOT yet been submitted ride
    the leader's execution — the exact join-cutoff the pipeline's own
    wave dedupe uses, so read-your-writes is identical across
    deployment shapes. Followers cost the owner follower-grade
    accounting (ledger/SLO/egress) instead of a full API pass."""

    __slots__ = ("submitted", "followers")

    def __init__(self):
        self.submitted = threading.Event()
        self.followers: list = []  # (_WorkerState, gen, header)


class _WorkerState:
    """Owner-side record of one worker process."""

    def __init__(self, wid: int):
        self.id = wid
        self.gen = 0
        self.proc: subprocess.Popen | None = None
        self.conn: socket.socket | None = None
        self.conn_lock = threading.Lock()
        self.sub: ShmRing | None = None   # worker -> owner (owner consumes)
        self.rsp: ShmRing | None = None   # owner -> worker (owner produces)
        self.alive = False
        self.started_at = 0.0
        self.dropped_inflight = 0

    def to_json(self, ctl: ControlBlock | None) -> dict:
        out = {
            "id": self.id,
            "gen": self.gen,
            "pid": self.proc.pid if self.proc is not None else None,
            "alive": self.alive,
            "uptimeSeconds": (round(time.time() - self.started_at, 1)
                              if self.alive else 0.0),
            "ringDepth": self.sub.depth() if self.sub is not None else 0,
            "droppedInflight": self.dropped_inflight,
        }
        if ctl is not None:
            out.update(ctl.read_worker(self.id))
        return out


class OwnerRuntime:
    """The device-owner half: spawns/supervises workers, drains their
    submit rings into a bounded executor pool, and answers over the
    response rings. Created by ``Server.open`` when ``serving-workers``
    > 0 (and the platform supports it)."""

    READY_TIMEOUT_S = 60.0
    RESPAWN_DELAY_S = 0.2
    FLAGS_INTERVAL_S = 0.5

    def __init__(self, server):
        self.server = server
        self.api = server.api
        self.config = server.config
        self.logger = server.logger
        self.n_workers = min(MAX_WORKERS, int(self.config.serving_workers))
        self.ring_slots = int(self.config.ring_slots)
        self.ring_slot_bytes = int(self.config.ring_slot_bytes)
        self.port: int = 0           # public SO_REUSEPORT port
        self.owner_port: int = 0     # loopback full-server port
        self._token = f"psrv{os.getpid():x}-{id(self) & 0xFFFF:x}"
        self._sock_path = ""
        self._listener: socket.socket | None = None
        self._workers: dict[int, _WorkerState] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._ready = {}  # wid -> threading.Event
        self.ctl: ControlBlock | None = None
        self._threads: list[threading.Thread] = []
        # owner executor pool: one thread per in-flight ring query, like
        # the single-process handler had one thread per connection — the
        # threads are cheap (they block in the wave pipeline's resolve,
        # not on CPU) and a SMALL pool would both queue requests outside
        # the pipeline (latency the client sees as ring overhead) and
        # starve the wave gather of submitters (shallow waves = more
        # device dispatch floors). Hand-rolled threads over a
        # SimpleQueue rather than ThreadPoolExecutor: submit() there
        # builds a Future + work item under a lock per record, which
        # sampling showed as a top intake cost at plateau. Bounded by a
        # capacity semaphore so ring drains stop (and rings fill, and
        # workers shed) instead of queueing unboundedly behind a
        # saturated pool.
        import queue as _queue

        self.pool_size = min(128, max(64, 16 * max(1, self.n_workers)))
        self._workq: _queue.SimpleQueue = _queue.SimpleQueue()
        self._capacity = threading.Semaphore(self.pool_size * 2)
        for i in range(self.pool_size):
            t = threading.Thread(target=self._exec_loop, daemon=True,
                                 name=f"mpserve-exec-{i}")
            t.start()
            self._threads.append(t)
        # owner-side dedupe memo: (index, pql) -> _SharedExec while a
        # leader is between intake and wave submission
        self._memo: dict = {}
        self._memo_lock = threading.Lock()
        # owner-side counters (serving_* metrics block)
        self._mlock = threading.Lock()
        # accumulated final counters of REPLACED worker processes: the
        # live slots reset to zero when a new pid takes a worker id, so
        # summed serving_*_total series would otherwise go backwards on
        # every respawn (poison for Prometheus rate())
        self._ctl_base = {"requests": 0, "ring": 0, "proxied": 0,
                          "shed": 0, "ring_full": 0}
        self.deduped = 0
        self.batches = 0
        self.batched_requests = 0
        self.last_batch = 0
        self.respawns = 0
        self.reaped = 0
        self.responses_dropped = 0
        self.queries_served = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "OwnerRuntime":
        self.owner_port = self.server._http.server_address[1]
        self._sock_path = self._resolve_sock_path()
        if os.path.exists(self._sock_path):
            os.unlink(self._sock_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(MAX_WORKERS)
        self.ctl = ControlBlock.create(f"{self._token}-ctl")
        self._publish_flags()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="mpserve-accept")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._flags_loop, daemon=True,
                             name="mpserve-flags")
        t.start()
        self._threads.append(t)
        # resolve the public port with the first worker's socket, then
        # spawn everyone
        try:
            for wid in range(self.n_workers):
                self._ready[wid] = threading.Event()
                self._spawn(wid)
            deadline = time.monotonic() + self.READY_TIMEOUT_S
            for wid, ev in self._ready.items():
                if not ev.wait(max(0.1, deadline - time.monotonic())):
                    raise RuntimeError(
                        f"serving worker {wid} did not become ready "
                        f"within {self.READY_TIMEOUT_S}s"
                    )
        except Exception:
            self.close()
            raise
        self.logger.info(
            "multi-process serving: %d workers on port %d "
            "(owner on 127.0.0.1:%d, rings %dx%dB)",
            self.n_workers, self.port, self.owner_port,
            self.ring_slots, self.ring_slot_bytes,
        )
        return self

    def _resolve_sock_path(self) -> str:
        path = os.path.join(
            os.path.expanduser(self.server.holder.data_dir), "mpserve.sock"
        )
        if len(path) < 100:  # AF_UNIX sun_path limit
            return path
        return os.path.join("/tmp", f"{self._token}.sock")

    def _new_listen_socket(self) -> socket.socket:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((self.config.bind, self.port or self.config.port))
        sock.listen(128)
        if not self.port:
            self.port = sock.getsockname()[1]
        return sock

    def _spawn(self, wid: int) -> None:
        sock = self._new_listen_socket()
        sock.set_inheritable(True)
        env = dict(os.environ)
        # workers never touch the device; make sure a stray jax import
        # in a future worker-side module cannot grab the accelerator
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "serve-worker",
             "--handshake-sock", self._sock_path,
             "--listen-fd", str(sock.fileno()),
             "--worker-id", str(wid)],
            pass_fds=(sock.fileno(),), env=env, close_fds=True,
        )
        # the child inherited the fd; the owner MUST drop its copy, or a
        # SIGKILLed worker's socket would stay in the reuseport group
        # with nobody accepting — connections routed to it would hang
        sock.close()
        with self._lock:
            ws = self._workers.get(wid)
            if ws is None:
                ws = self._workers[wid] = _WorkerState(wid)
            ws.proc = proc

    # ------------------------------------------------------------ handshake

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            # daemon + untracked: one io thread per worker CHANNEL, and
            # channels churn with every respawn/re-handshake — keeping
            # references would grow without bound on a long-lived owner
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="mpserve-worker-io").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""
        ws = None
        gen = 0
        try:
            conn.settimeout(15.0)
            line, buf = self._read_line(conn, buf)
            hello = json.loads(line)["hello"]
            wid = int(hello["worker"])
            if not 0 <= wid < MAX_WORKERS:
                raise ValueError(f"bad worker id {wid}")
            hello_pid = int(hello.get("pid") or 0)
            with self._lock:
                ws = self._workers.get(wid)
                if (ws is not None and ws.proc is not None
                        and ws.proc.poll() is None
                        and hello_pid != ws.proc.pid):
                    # a stray claimant: an orphan from a previous owner
                    # incarnation racing the worker THIS runtime spawned
                    # for the same id. Two processes duelling over one
                    # worker slot would re-handshake each other's
                    # channel closed forever — refuse the orphan (it
                    # exits once its re-handshake window drains) and
                    # keep our own process.
                    ws = None
                    raise ValueError(
                        f"worker id {wid} already owned by pid "
                        f"{self._workers[wid].proc.pid} (claimant pid "
                        f"{hello_pid} refused)"
                    )
                if ws is None:
                    # a worker this runtime did not spawn (owner-restart
                    # re-handshake): adopt it — it still holds its
                    # listening socket
                    ws = self._workers[wid] = _WorkerState(wid)
                if self.ctl is not None:
                    slot = self.ctl.read_worker(wid)
                    if slot["pid"] and slot["pid"] != hello_pid:
                        # a NEW process is taking this worker id: fold
                        # the dead process's final counters into the
                        # owner-side base (keeps summed totals
                        # monotonic) and zero the slot before the new
                        # process's first write. Safe against racing
                        # writes: the claimant cannot write until it
                        # receives the cfg sent below, and the old
                        # process is gone.
                        with self._mlock:
                            self._ctl_base["requests"] += slot["requests"]
                            self._ctl_base["ring"] += slot["ringRequests"]
                            self._ctl_base["proxied"] += slot["proxied"]
                            self._ctl_base["shed"] += slot["shed"]
                            self._ctl_base["ring_full"] += slot["ringFull"]
                        self.ctl.write_worker(wid, 0, 0, 0, 0, 0, 0,
                                              0, 0, 0)
                ws.gen += 1
                gen = ws.gen
                old_conn, ws.conn = ws.conn, conn
                old_sub, old_rsp = ws.sub, ws.rsp
                ws.sub = ShmRing.create(f"{self._token}-{wid}g{gen}s",
                                        self.ring_slots,
                                        self.ring_slot_bytes)
                ws.rsp = ShmRing.create(f"{self._token}-{wid}g{gen}r",
                                        self.ring_slots,
                                        self.ring_slot_bytes)
            for ring in (old_sub, old_rsp):
                if ring is not None:
                    ring.close()
                    ring.unlink()
            if old_conn is not None:
                try:
                    old_conn.close()
                except OSError:
                    pass
            share = -(-self.config.qos_max_inflight // self.n_workers) \
                if self.config.qos_max_inflight > 0 else 0
            tshare = -(-self.config.qos_tenant_inflight // self.n_workers) \
                if self.config.qos_tenant_inflight > 0 else 0
            from pilosa_tpu.utils.tracing import global_tracer

            cfg = {
                "worker": wid, "gen": gen, "ownerPort": self.owner_port,
                "sub": ws.sub.name, "rsp": ws.rsp.name,
                "ctl": self.ctl.name,
                "maxWritesPerRequest": self.api.max_writes_per_request,
                "defaultDeadlineS": self.api.default_deadline_s,
                "qosMaxInflight": share, "qosTenantInflight": tshare,
                "traceSampleRate": global_tracer().sample_rate,
                "node": self.api.node_id(),
            }
            self._send_line(ws, {"cfg": cfg})
            line, buf = self._read_line(conn, buf)
            if not json.loads(line).get("ready"):
                raise ValueError("worker handshake: expected ready")
            conn.settimeout(None)
            ws.alive = True
            ws.started_at = time.time()
            ev = self._ready.get(wid)
            if ev is not None:
                ev.set()
            self._io_loop(ws, gen, conn, buf)
        except Exception as e:  # noqa: BLE001 — one worker's handshake
            if not self._closed.is_set():  # failure must not kill accept
                self.logger.warning("mpserve worker channel error: %s", e)
        finally:
            if ws is not None:
                self._reap(ws, gen)
            else:
                try:
                    conn.close()
                except OSError:
                    pass

    @staticmethod
    def _read_line(conn: socket.socket, buf: bytes) -> tuple[bytes, bytes]:
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError("handshake channel closed")
            buf += chunk
        line, _, rest = buf.partition(b"\n")
        return line, rest

    def _send_line(self, ws: _WorkerState, obj: dict) -> None:
        data = json.dumps(obj, separators=(",", ":")).encode() + b"\n"
        with ws.conn_lock:
            ws.conn.sendall(data)

    # --------------------------------------------------------------- intake

    def _io_loop(self, ws: _WorkerState, gen: int, conn: socket.socket,
                 buf: bytes) -> None:
        """Drain this worker's submit ring; sleep on the handshake
        socket (doorbells + control lines) only once the ring is
        observably empty AFTER declaring the wait — the coalesced-
        doorbell protocol (shmring.set_waiting), so a busy worker costs
        one doorbell syscall per owner SLEEP, not per record."""
        while not self._closed.is_set():
            sub = ws.sub
            if sub is not None:
                self._drain(ws)
                try:
                    sub.set_waiting()
                    if sub.depth() > 0:
                        continue  # raced a push: drain again, no sleep
                except (TypeError, ValueError):
                    pass  # ring torn down by a concurrent reap
            progressed = False
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                if line.startswith(b"{"):
                    self._control(ws, line)
                progressed = True  # a bare `!` just re-drains above
            if progressed:
                continue
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError("worker channel closed")
            buf += chunk

    def _control(self, ws: _WorkerState, line: bytes) -> None:
        try:
            msg = json.loads(line)
        except ValueError:
            return
        tree = msg.get("trace")
        if tree is not None:
            # a worker-side finished span tree (the edge root with the
            # owner's rpc.query subtree grafted): record it in the
            # owner's tracer so /debug/traces shows ONE tree per request
            from pilosa_tpu.utils.tracing import global_tracer

            global_tracer().record_foreign_tree(tree)

    def _drain(self, ws: _WorkerState) -> None:
        """Drain one doorbell's worth of submissions — capacity-gated:
        when the pool is saturated this loop BLOCKS, the submit ring
        fills, and the worker sheds 429 (backpressure end to end).

        Dedupe at intake: an eligible query (plain edge JSON read — no
        shards/opts/deadline/trace) identical to a leader whose wave has
        not yet been SUBMITTED joins that leader as a follower instead
        of consuming an executor thread — worker waves group-commit into
        the owner's micro-batched dispatches, and the follower pays only
        follower-grade accounting (_finish_followers)."""
        n = 0
        while True:
            # depth check BEFORE taking a capacity permit: with the
            # pool saturated, an io thread blocked in acquire() over an
            # EMPTY ring could not see its worker's EOF — exactly the
            # overload window where worker deaths need reaping
            ring = ws.sub
            try:
                if ring is None or ring.depth() == 0:
                    break
            except (TypeError, ValueError):
                break  # ring torn down by a concurrent reap
            # one blocking permit keeps the backpressure contract
            # (saturated pool → this drain stalls → ring fills → the
            # worker sheds 429); opportunistic non-blocking acquires
            # size a batch so ONE consumer-lock acquisition pops a
            # doorbell's worth of records — the per-record pop()
            # round-trip was the measured intake ceiling at plateau
            self._capacity.acquire()
            permits = 1
            while permits < 64 and self._capacity.acquire(blocking=False):
                permits += 1
            try:
                recs = ring.pop_many(permits)
            except (TypeError, ValueError):
                recs = []  # torn down mid-drain
            for _ in range(permits - len(recs)):
                self._capacity.release()
            for rec in recs:
                n += 1
                self._intake_frame(ws, rec)
        if n:
            with self._mlock:
                self.batches += 1
                self.batched_requests += n
                self.last_batch = n

    def _intake_frame(self, ws: _WorkerState, rec: bytes) -> None:
        """Route one popped submit record (its capacity permit is held
        by the caller and travels with the work item; every early
        return releases it)."""
        try:
            header, body = decode_frame(rec)
        except ValueError as e:
            self._capacity.release()
            self.logger.warning("mpserve: dropping bad frame: %s", e)
            return
        if (header.get("op", "q") == "q" and header.get("ro")
                and "sh" not in header and "o" not in header
                and "dl" not in header and "tr" not in header):
            key = (header.get("ix", ""), body)
            joined = False
            with self._memo_lock:
                ex = self._memo.get(key)
                if ex is not None and not ex.submitted.is_set():
                    ex.followers.append((ws, ws.gen, header))
                    joined = True
                else:
                    ex = _SharedExec()
                    self._memo[key] = ex
            if joined:
                self._capacity.release()
                with self._mlock:
                    self.deduped += 1
                return
            self._workq.put((ws, ws.gen, header, body, key, ex))
        else:
            self._workq.put((ws, ws.gen, header, body, None, None))

    # ------------------------------------------------------------ execution

    def _exec_loop(self) -> None:
        while True:
            item = self._workq.get()
            if item is None:
                return  # close() sentinel
            self._run_frame(*item)

    def _run_frame(self, ws: _WorkerState, gen: int, header: dict,
                   body: bytes, key, ex: _SharedExec | None) -> None:
        try:
            if header.get("op", "q") == "q":
                on_submitted = None
                if ex is not None:
                    # dedupe-join cutoff: once this leader's wave is
                    # SUBMITTED, late arrivals start a fresh leader —
                    # the same boundary the pipeline's own wave dedupe
                    # draws, so read-your-writes is identical across
                    # deployment shapes
                    def on_submitted():
                        self._close_memo(key, ex)
                meta, payload = self._serve_query(header, body,
                                                  on_submitted)
            else:
                meta = {"st": 400}
                payload = json.dumps(
                    {"error": f"unknown ring op {header.get('op')!r}"}
                ).encode()
            meta["id"] = header.get("id")
            self._respond(ws, gen, self._fit_frame(meta, payload))
            if ex is not None:
                # a leader that errored before submission never fired
                # on_submitted — close the memo either way, or its
                # followers (and every later identical query) wedge
                self._close_memo(key, ex)
                self._finish_followers(ex, meta, payload)
        finally:
            self._capacity.release()

    def _fit_frame(self, meta: dict, payload: bytes) -> bytes:
        """Encode a response frame, degrading to a small 500 when the
        record could NEVER fit the response ring — the worker's client
        gets a prompt, explicit error instead of hanging out its full
        timeout (and pinning its admission slot) on a frame the owner
        would silently fail to push."""
        frame = encode_frame(meta, payload)
        if -(-len(frame) // self.ring_slot_bytes) <= self.ring_slots:
            return frame
        body = json.dumps({"error": (
            f"response of {len(payload)} bytes exceeds the serving "
            f"ring ({self.ring_slots} slots x {self.ring_slot_bytes} "
            "bytes); raise ring-slot-bytes/ring-slots or narrow the "
            "query")}).encode()
        return encode_frame({"st": 500, "id": meta.get("id")}, body)

    def _close_memo(self, key, ex: _SharedExec) -> None:
        with self._memo_lock:
            ex.submitted.set()
            if self._memo.get(key) is ex:
                del self._memo[key]

    def _finish_followers(self, ex: _SharedExec, meta: dict,
                          payload: bytes) -> None:
        """Answer every follower that joined this leader before its
        wave submitted: same status + payload bytes (the queries were
        byte-identical), follower-grade accounting — one ledger fold,
        one SLO event, and egress billing per follower, so
        /debug/tenants and /debug/slo see N requests even though the
        device saw one execution (exactly what the pipeline's in-wave
        dedupe reports in single-process mode)."""
        if not ex.followers:
            return
        st = int(meta.get("st", 200))
        elapsed = float(meta.get("ex") or 0.0)
        error = st >= 500
        cache_hit = bool(meta.get("rc"))
        billed = cost_enabled()
        for fws, fgen, fheader in ex.followers:
            fmeta = {"st": st, "ex": meta.get("ex", 0.0),
                     "id": fheader.get("id")}
            if meta.get("ra") is not None:
                fmeta["ra"] = meta["ra"]
            self._respond(fws, fgen, self._fit_frame(fmeta, payload))
            tenant = fheader.get("t", "default")
            index = fheader.get("ix", "")
            if billed:
                self.api.cost.record_query(tenant, index, None, elapsed,
                                           error=error,
                                           result_cache_hit=cache_hit)
                self.api.cost.add_egress(tenant, index, len(payload))
                if st != 429:
                    self.api.slo.record(elapsed, error=error)
        with self._mlock:
            self.queries_served += len(ex.followers)

    def _serve_query(self, header: dict, body: bytes,
                     on_submitted=None):
        """Execute one ring-submitted edge JSON query — the owner half
        of server/http.py's ``post_query`` JSON branch. Admission
        already ran worker-side (``pre_admitted``); the WAL ACK barrier,
        cost/SLO accounting, and inflight tracking all run here exactly
        as in single-process mode."""
        from pilosa_tpu.server.api import ApiError  # heavy module: the
        # owner has it loaded long before the first frame, but hoisting
        # it would drag the full storage stack into worker imports
        # (worker.py imports this module)

        index = header.get("ix", "")
        tenant = header.get("t", "default")
        deadline = (Deadline.from_millis(int(header["dl"]))
                    if header.get("dl") else None)
        t0 = time.perf_counter()
        tracer = global_tracer()
        meta: dict = {}

        def run() -> bytes:
            try:
                cache_hit: list = []
                payload = self.api.query_json_bytes(
                    index, body.decode(), shards=header.get("sh"),
                    opts=header.get("o") or {}, tenant=tenant,
                    deadline=deadline, pre_admitted=True,
                    on_submitted=on_submitted,
                    cache_hit_out=cache_hit,
                )
                meta["st"] = 200
                if cache_hit:
                    # result-cache hit (serving/rescache.py): followers
                    # of this leader bill as cache hits too — they got
                    # the same cached bytes
                    meta["rc"] = True
                if cost_enabled():
                    # egress billing for the worker's response bytes —
                    # the handler's _note_egress, owner-side
                    self.api.cost.add_egress(tenant, index, len(payload))
                return payload
            except ApiError as e:
                # identical bytes to the handler's error path (_json
                # uses default json.dumps separators)
                meta["st"] = e.status
                ra = getattr(e, "retry_after", None)
                if ra is not None:
                    meta["ra"] = max(1, int(ra))
                return json.dumps({"error": str(e)}).encode()
            except Exception as e:  # noqa: BLE001 — 500, never dead slot
                meta["st"] = 500
                return json.dumps({"error": f"internal: {e}"}).encode()

        # DETACHED owner-side subtree (remote_span, not remote_root):
        # it is finished and shipped back in the response frame for the
        # WORKER to graft and return as one stitched tree over the
        # handshake channel — recording the bare subtree in this
        # process's finished ring too would put two trees per sampled
        # request on /debug/traces
        span = tracer.remote_span(header.get("tr"), "rpc.query",
                                  node=self.api.node_id(), index=index)
        if span is not None:
            with use_span(span):
                payload = run()
            span.finish()
            meta["tr"] = span.to_json()
        else:
            # no trace context: remote_root(None) is the SUPPRESS
            # handle — without it, inner tracer.span() sites would mint
            # their own sampled root trees for an unsampled request
            with tracer.remote_root(None, "rpc.query"):
                payload = run()
        meta["ex"] = round(time.perf_counter() - t0, 6)
        with self._mlock:
            self.queries_served += 1
        return meta, payload

    def _respond(self, ws: _WorkerState, gen: int, frame: bytes) -> None:
        """Push a response frame; NEVER wedge on a dead/slow worker —
        bounded retries while the worker generation is still live, then
        drop (the client's connection died with its worker anyway)."""
        deadline = time.monotonic() + 2.0
        while not self._closed.is_set():
            if ws.gen != gen or not ws.alive:
                break  # worker reaped/replaced: response has no reader
            ring = ws.rsp
            try:
                if ring is not None and ring.push(frame):
                    if ring.take_waiting():
                        self._doorbell(ws)
                    return
            except (RingFull, ValueError, OSError, TypeError):
                break
            if time.monotonic() > deadline:
                break
            time.sleep(0.0005)
        with self._mlock:
            self.responses_dropped += 1

    def _doorbell(self, ws: _WorkerState) -> None:
        try:
            with ws.conn_lock:
                if ws.conn is not None:
                    ws.conn.sendall(_DOORBELL)
        except OSError:
            pass  # EOF path reaps; responses already in the ring survive

    # ----------------------------------------------------------------- reap

    def _reap(self, ws: _WorkerState, gen: int) -> None:
        """A worker channel died. Reclaim its in-flight submit slots (the
        owner must not wedge on them — their clients never got an ack),
        tear down the rings, and respawn a replacement."""
        with self._lock:
            if ws.gen != gen:
                return  # already re-handshaked to a newer generation
            ws.alive = False
            sub, rsp, conn = ws.sub, ws.rsp, ws.conn
            ws.sub = ws.rsp = ws.conn = None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        if sub is not None:
            ws.dropped_inflight += sub.reclaim()
            sub.close()
            sub.unlink()
        if rsp is not None:
            rsp.close()
            rsp.unlink()
        with self._mlock:
            self.reaped += 1
        if self._closed.is_set():
            return
        # respawn on actual death, not on a re-handshake in flight. For
        # workers THIS runtime spawned, death is the process exiting
        # (the EOF can arrive moments before the SIGKILLed process is
        # reapable, so wait briefly instead of polling once). For
        # ADOPTED workers (owner-restart re-handshake gave us no Popen
        # handle) the only signal is that no newer generation handshakes
        # within the grace window — without this, every adopted worker
        # that later dies would silently shrink the public-port fleet.
        proc = ws.proc

        def respawn():
            if proc is not None:
                try:
                    proc.wait(timeout=3.0)
                except subprocess.TimeoutExpired:
                    return  # still alive: a reconnect, not a death
                time.sleep(self.RESPAWN_DELAY_S)
            else:
                time.sleep(max(self.RESPAWN_DELAY_S, 1.0))
            if self._closed.is_set() or ws.gen != gen:
                return  # shut down, or already re-handshaked
            with self._mlock:
                self.respawns += 1
            self.logger.warning(
                "serving worker %d (pid %s) died (exit %s) — respawning",
                ws.id, proc.pid if proc is not None else "adopted",
                proc.returncode if proc is not None else "?",
            )
            try:
                self._spawn(ws.id)
            except OSError as e:
                self.logger.warning("worker %d respawn failed: %s",
                                    ws.id, e)

        threading.Thread(target=respawn, daemon=True,
                         name="mpserve-respawn").start()

    # -------------------------------------------------------------- flags

    def _publish_flags(self) -> None:
        flags = 0
        reason = ""
        cluster = getattr(self.api, "cluster", None)
        if cluster is not None and getattr(cluster, "degraded", False):
            flags |= ControlBlock.FLAG_CLUSTER_DEGRADED
        health = getattr(self.server.holder, "health", None)
        if health is not None and health.degraded:
            flags |= ControlBlock.FLAG_STORAGE_DEGRADED
            reason = health.reason or ""
        if self.ctl is not None:
            self.ctl.set_flags(flags, reason)

    def _flags_loop(self) -> None:
        while not self._closed.wait(self.FLAGS_INTERVAL_S):
            try:
                self._publish_flags()
            except Exception:  # noqa: BLE001 — ticker must not die
                pass

    # ------------------------------------------------------------- surfaces

    def workers_json(self) -> list[dict]:
        with self._lock:
            workers = sorted(self._workers.values(), key=lambda w: w.id)
            return [w.to_json(self.ctl) for w in workers]

    def metrics(self) -> dict:
        with self._lock:
            workers = list(self._workers.values())
        alive = sum(1 for w in workers if w.alive)
        depth = sum(w.sub.depth() for w in workers if w.sub is not None)
        with self._mlock:
            ring_full = self._ctl_base["ring_full"]
            ring_requests = self._ctl_base["ring"]
            shed = self._ctl_base["shed"]
            proxied = self._ctl_base["proxied"]
        if self.ctl is not None:
            for w in workers:
                slot = self.ctl.read_worker(w.id)
                ring_full += slot["ringFull"]
                ring_requests += slot["ringRequests"]
                shed += slot["shed"]
                proxied += slot["proxied"]
        with self._mlock:
            avg = (self.batched_requests / self.batches
                   if self.batches else 0.0)
            return {
                "serving_workers": alive,
                "serving_ring_depth": depth,
                "serving_ring_full_total": ring_full,
                "serving_owner_batch_size": round(avg, 3),
                "serving_owner_batches_total": self.batches,
                "serving_owner_batched_requests_total":
                    self.batched_requests,
                "serving_ring_requests_total": ring_requests,
                "serving_worker_shed_total": shed,
                "serving_worker_proxied_total": proxied,
                "serving_worker_respawns_total": self.respawns,
                "serving_workers_reaped_total": self.reaped,
                "serving_responses_dropped_total": self.responses_dropped,
                "serving_ring_queries_total": self.queries_served,
                "serving_ring_deduped_total": self.deduped,
            }

    # ---------------------------------------------------------------- close

    def simulate_restart(self) -> None:
        """Test hook: tear down the owner half (listener + channels +
        rings) WITHOUT killing worker processes, then come back up —
        workers must detect the EOF and re-handshake (the owner-restart
        drill; tests/test_mpserve.py)."""
        with self._lock:
            conns = [w.conn for w in self._workers.values()
                     if w.conn is not None]
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        # the per-conn io threads observe EOF and reap (rings torn down)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with self._lock:
                if not any(w.alive for w in self._workers.values()):
                    break
            time.sleep(0.05)
        if os.path.exists(self._sock_path):
            os.unlink(self._sock_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(MAX_WORKERS)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="mpserve-accept").start()

    def wait_workers(self, n: int, timeout: float = 30.0) -> bool:
        """Block until ``n`` workers are alive (tests, chaos harness)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if sum(1 for w in self._workers.values() if w.alive) >= n:
                    return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            workers = list(self._workers.values())
        for ws in workers:
            if ws.proc is not None:
                try:
                    ws.proc.terminate()
                except OSError:
                    pass
        for ws in workers:
            if ws.proc is not None:
                try:
                    ws.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    ws.proc.kill()
                    ws.proc.wait(timeout=5)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for ws in workers:
            for ring in (ws.sub, ws.rsp):
                if ring is not None:
                    ring.close()
                    ring.unlink()
            ws.sub = ws.rsp = None
            if ws.conn is not None:
                try:
                    ws.conn.close()
                except OSError:
                    pass
        if self.ctl is not None:
            self.ctl.close()
            self.ctl.unlink()
        for _ in range(self.pool_size):
            self._workq.put(None)
        if self._sock_path and os.path.exists(self._sock_path):
            try:
                os.unlink(self._sock_path)
            except OSError:
                pass
