"""SO_REUSEPORT serving worker: the per-request host work, off the GIL
of the device owner.

One worker process = one inherited ``SO_REUSEPORT`` listening socket +
one shared-memory ring pair to the owner (serving/mpserve.py). The
worker runs everything that made the single-process request path cost
~1.7 ms of interpreter time — HTTP parse, the QoS envelope, PQL parse,
admission, degraded-mode shedding, response socket writes — and ships
only the execution itself to the device owner as a pickle-free frame.

Route split:

- ``POST /index/{i}/query`` (JSON, edge, unprofiled) → the ring.
- Everything else — imports (the WAL ACK rides the owner's handler
  untouched), protobuf bodies, ``?profile=true``, ``?remote=true``
  hops, schema, /internal/*, /debug/* — proxies verbatim to the
  owner's loopback listener over a keep-alive pool: byte-identical
  behavior with zero duplicated logic, on traffic that is rare or
  internal by construction.
- ``GET /debug/worker`` answers locally (this worker's own counters and
  ring round-trip quantiles — the only route that must NOT cross to the
  owner).

This module must stay importable WITHOUT jax or the storage/executor
stack: worker startup cost is what bounds respawn latency after a
crash, and a worker that initializes an accelerator runtime would fight
the owner for the device.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu.pql import ParseError, parse
from pilosa_tpu.qos import (
    DEADLINE_HEADER,
    TENANT_HEADER,
    AdmissionError,
    Deadline,
)
from pilosa_tpu.serving import mpserve
from pilosa_tpu.serving.shmring import (
    RingFull,
    ShmRing,
    decode_frame,
    encode_frame,
)
from pilosa_tpu.utils.tracing import global_tracer

_QUERY_RE = re.compile(r"^/index/([^/]+)/query$")

# Worker-side parse memo: the per-request PQL parse exists only to
# reject garbage before the ring and count write calls for the
# degraded/limit gates — a pure function of the raw bytes, so repeated
# query bodies (the dominant serving shape) pay one parse, not one per
# request (~8us of the measured per-request envelope). Values are
# (error_text | None, write_count); bounded by wholesale clear, like
# the plan cache's overflow rule. dict ops are GIL-atomic; a racing
# double-compute just stores the same value twice.
_PARSE_MEMO: dict[bytes, tuple[str | None, int]] = {}
_PARSE_MEMO_MAX = 1024

# headers forwarded on the proxy hop, both ways
_PROXY_REQ_HEADERS = (
    "Content-Type", "Accept", "Accept-Encoding",
    "X-Pilosa-Deadline-Ms", "X-Pilosa-Tenant", "X-Pilosa-Trace",
)
_PROXY_RSP_HEADERS = ("Content-Type", "Retry-After", "Content-Encoding")


class OwnerGone(Exception):
    """The device owner did not answer (died, restarting, or wedged)."""


class _Pending:
    __slots__ = ("ev", "meta", "payload", "err")

    def __init__(self):
        self.ev = threading.Event()
        self.meta = None
        self.payload = None
        self.err = None


class WorkerGateway:
    """The worker's side of the owner channel: handshake + rings +
    response dispatch + counters. One per worker process."""

    REHANDSHAKE_WINDOW_S = 15.0

    def __init__(self, sock_path: str, worker_id: int):
        self.sock_path = sock_path
        self.worker_id = worker_id
        # how long a worker keeps retrying the handshake after losing
        # the owner before giving up and exiting (env-overridable so
        # tests and chaos schedules don't wait out the full window)
        self.rehandshake_window_s = float(os.environ.get(
            "PILOSA_TPU_MP_REHANDSHAKE_S", self.REHANDSHAKE_WINDOW_S))
        self.gen = 0
        self.cfg: dict = {}
        self.conn: socket.socket | None = None
        self._conn_lock = threading.Lock()
        self.sub: ShmRing | None = None   # this worker produces
        self.rsp: ShmRing | None = None   # this worker consumes
        self.ctl: mpserve.ControlBlock | None = None
        self._pending: dict[int, _Pending] = {}
        self._plock = threading.Lock()
        self._next_id = 0
        self.admission = None
        # worker-local counters (mirrored into the control block)
        self._clock = threading.Lock()
        self.requests = 0
        self.ring_requests = 0
        self.proxied = 0
        self.shed = 0
        self.ring_full = 0
        self._rtt_us: deque = deque(maxlen=512)
        self._rtt_p50 = 0
        self._rtt_p99 = 0
        self.owner_port = 0
        self.proxy_pool = None
        self.alive = True
        # False while the owner channel is down (mid re-handshake):
        # submits fail fast with OwnerGone instead of pushing into a
        # dead ring and waiting out the full request timeout
        self.connected = False
        self._stats_written = 0.0

    # ------------------------------------------------------------ handshake

    def connect(self) -> None:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(15.0)
        conn.connect(self.sock_path)
        conn.sendall(json.dumps(
            {"hello": {"worker": self.worker_id, "pid": os.getpid(),
                       "gen": self.gen}},
            separators=(",", ":")).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError("owner closed during handshake")
            buf += chunk
        line, _, buf = buf.partition(b"\n")
        cfg = json.loads(line)["cfg"]
        old_sub, old_rsp, old_ctl = self.sub, self.rsp, self.ctl
        self.sub = ShmRing.attach(cfg["sub"])
        self.rsp = ShmRing.attach(cfg["rsp"])
        if old_ctl is None or old_ctl.name != cfg["ctl"]:
            # first connect, or a NEW owner process (fresh token →
            # fresh control segment): the old block belongs to a dead
            # owner — keeping it would read stale degraded flags and
            # write stats nobody scrapes
            self.ctl = mpserve.ControlBlock.attach(cfg["ctl"])
            if old_ctl is not None:
                old_ctl.close()
        for ring in (old_sub, old_rsp):
            if ring is not None:
                ring.close()
        self.cfg = cfg
        self.gen = cfg["gen"]
        self.owner_port = cfg["ownerPort"]
        if self.proxy_pool is None:
            from pilosa_tpu.parallel.connpool import ConnectionPool

            self.proxy_pool = ConnectionPool(max_per_host=32, timeout=300.0)
        if self.admission is None:
            from pilosa_tpu.qos import AdmissionController

            # per-worker share of the node's admission quota (the gate
            # runs HERE, before the ring — shed requests never cross)
            self.admission = AdmissionController(
                max_inflight=int(cfg.get("qosMaxInflight") or 0),
                tenant_max=int(cfg.get("qosTenantInflight") or 0),
            )
        else:
            # re-handshake: adopt the (possibly restarted-with-new-
            # config) owner's refreshed quotas in place — recreating
            # the controller would forget in-flight slots
            self.admission.max_inflight = int(
                cfg.get("qosMaxInflight") or 0)
            self.admission.tenant_max = int(
                cfg.get("qosTenantInflight") or 0)
        global_tracer().sample_rate = float(
            cfg.get("traceSampleRate") or 0.0
        )
        conn.sendall(b'{"ready":true}\n')
        conn.settimeout(None)
        with self._conn_lock:
            self.conn = conn
        self._buf = buf
        self.connected = True
        self.write_stats()

    def start_dispatcher(self) -> None:
        t = threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="mpserve-dispatch")
        t.start()

    def _dispatch_loop(self) -> None:
        while self.alive:
            conn = self.conn
            try:
                # drain, then declare the wait and re-check before
                # blocking (the coalesced-doorbell protocol — see
                # shmring.set_waiting): the owner rings the socket only
                # when this thread is actually asleep
                ring = self.rsp
                if ring is not None:
                    self._drain_responses()
                    ring.set_waiting()
                    if ring.depth() > 0:
                        continue
                if b"\n" in self._buf:
                    self._buf = self._buf.rpartition(b"\n")[2]
                    continue  # doorbell lines consumed; re-drain
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError("owner channel closed")
                self._buf += chunk
            except (OSError, AttributeError, ConnectionError, TypeError):
                if not self.alive:
                    return
                self.connected = False
                self._rehandshake()

    def _drain_responses(self) -> None:
        ring = self.rsp
        if ring is None:
            return
        for rec in ring.drain():
            try:
                meta, payload = decode_frame(rec)
            except ValueError:
                continue
            with self._plock:
                entry = self._pending.pop(meta.get("id"), None)
            if entry is not None:
                entry.meta = meta
                entry.payload = payload
                entry.ev.set()

    def _rehandshake(self) -> None:
        """The owner channel died: fail in-flight waits, then try to
        reconnect (an owner RESTART recreates the handshake socket at
        the same path). If the owner stays gone, exit — a worker without
        a device owner serves nothing useful."""
        with self._plock:
            pending, self._pending = self._pending, {}
        for entry in pending.values():
            entry.err = "device owner restarted"
            entry.ev.set()
        deadline = time.monotonic() + self.rehandshake_window_s
        while self.alive and time.monotonic() < deadline:
            try:
                self.connect()
                return
            except (OSError, ValueError, KeyError, ConnectionError):
                time.sleep(0.5)
        os._exit(0)

    # --------------------------------------------------------------- submit

    def submit(self, header: dict, body: bytes,
               timeout: float) -> tuple[dict, bytes]:
        """Push one query frame and wait for its response frame.
        Raises ``RingFull`` (→ 429 shed) or ``OwnerGone`` (→ 503)."""
        if not self.connected:
            raise OwnerGone("device owner channel is down (re-handshake "
                            "in progress)")
        with self._plock:
            self._next_id += 1
            rid = self._next_id
            entry = _Pending()
            self._pending[rid] = entry
        header["id"] = rid
        frame = encode_frame(header, body)
        t0 = time.perf_counter()
        ring = self.sub
        try:
            pushed = ring is not None and ring.push(frame)
        except RingFull:
            pushed = False  # record exceeds TOTAL ring capacity: same
            # shed as a momentarily-full ring, and no _pending leak
        if not pushed:
            with self._plock:
                self._pending.pop(rid, None)
            with self._clock:
                self.ring_full += 1
            raise RingFull("serving ring full")
        if ring.take_waiting():
            self._doorbell()
        if not entry.ev.wait(timeout):
            with self._plock:
                self._pending.pop(rid, None)
            raise OwnerGone(
                f"device owner did not answer within {timeout:.0f}s"
            )
        if entry.err is not None:
            raise OwnerGone(entry.err)
        total = time.perf_counter() - t0
        self._note_rtt(total - float(entry.meta.get("ex") or 0.0))
        return entry.meta, entry.payload

    def _doorbell(self) -> None:
        try:
            with self._conn_lock:
                if self.conn is not None:
                    self.conn.sendall(mpserve._DOORBELL)
        except OSError:
            pass  # dispatcher notices EOF and re-handshakes

    def send_trace(self, tree: dict) -> None:
        """Ship a finished worker-side span tree to the owner so its
        /debug/traces renders one tree per request."""
        try:
            data = json.dumps({"trace": tree},
                              separators=(",", ":")).encode() + b"\n"
            with self._conn_lock:
                if self.conn is not None:
                    self.conn.sendall(data)
        except (OSError, ValueError, TypeError):
            pass

    # ------------------------------------------------------------- counters

    def _note_rtt(self, overhead_s: float) -> None:
        us = max(0, int(overhead_s * 1e6))
        with self._clock:
            self._rtt_us.append(us)
            if len(self._rtt_us) % 32 == 0 or self._rtt_p50 == 0:
                srt = sorted(self._rtt_us)
                self._rtt_p50 = srt[len(srt) // 2]
                self._rtt_p99 = srt[min(len(srt) - 1,
                                        int(len(srt) * 0.99))]

    def count(self, **kw) -> None:
        with self._clock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)
        # mirror into the control block at a bounded rate — the slot is
        # an observability surface, not an accounting ledger
        now = time.monotonic()
        if now - self._stats_written > 0.05:
            self._stats_written = now
            self.write_stats()

    def write_stats(self) -> None:
        ctl = self.ctl
        if ctl is None:
            return
        with self._clock:
            try:
                ctl.write_worker(
                    self.worker_id, self.gen, os.getpid(), self.requests,
                    self.ring_requests, self.proxied, self.shed,
                    self.ring_full, self._rtt_p50, self._rtt_p99,
                )
            except (TypeError, ValueError):
                pass  # ctl torn down during shutdown

    def local_stats(self) -> dict:
        with self._clock:
            rtts = sorted(self._rtt_us)
            return {
                "worker": self.worker_id,
                "gen": self.gen,
                "pid": os.getpid(),
                "requests": self.requests,
                "ringRequests": self.ring_requests,
                "proxied": self.proxied,
                "shed": self.shed,
                "ringFull": self.ring_full,
                "ringRttP50Us": (rtts[len(rtts) // 2] if rtts else 0),
                "ringRttP99Us": (rtts[min(len(rtts) - 1,
                                          int(len(rtts) * 0.99))]
                                 if rtts else 0),
                "ringRttSamples": len(rtts),
            }

    def degraded_flags(self) -> int:
        ctl = self.ctl
        return ctl.flags() if ctl is not None else 0

    def close(self) -> None:
        self.alive = False
        with self._conn_lock:
            if self.conn is not None:
                try:
                    self.conn.close()
                except OSError:
                    pass
        for ring in (self.sub, self.rsp):
            if ring is not None:
                ring.close()
        if self.ctl is not None:
            self.ctl.close()


class WorkerHandler(BaseHTTPRequestHandler):
    """Slim HTTP handler: hot query route over the ring, everything
    else proxied to the owner. Keep-alive discipline (body drains,
    chunked rejection, buffered single-write responses) mirrors
    server/http.py's handler — the client must not be able to tell
    which deployment shape served it."""

    gw: WorkerGateway = None  # bound per process in worker_main
    protocol_version = "HTTP/1.1"
    timeout = 120
    wbufsize = -1

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -------------------------------------------------------------- helpers

    def _body(self) -> bytes:
        self._body_read = True
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _drain_body(self) -> None:
        if getattr(self, "_body_read", True):
            return
        self._body_read = True
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self.close_connection = True
            return
        while length > 0:
            chunk = self.rfile.read(min(length, 1 << 16))
            if not chunk:
                break
            length -= len(chunk)

    def _json(self, obj, status: int = 200,
              headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        self._raw(data, status=status, headers=headers)

    def _raw(self, data: bytes, content_type: str = "application/json",
             status: int = 200, headers: dict | None = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, method: str) -> None:
        self._body_read = False
        self.gw.count(requests=1)
        if "chunked" in (self.headers.get("Transfer-Encoding")
                         or "").lower():
            self._body_read = True
            self._json({"error": "chunked request bodies are not "
                                 "supported; send Content-Length"},
                       status=411, headers={"Connection": "close"})
            return
        parsed = urlparse(self.path)
        try:
            if method == "POST" and _QUERY_RE.match(parsed.path):
                index = _QUERY_RE.match(parsed.path).group(1)
                self._handle_query(index, parse_qs(parsed.query))
            elif method == "GET" and parsed.path == "/debug/worker":
                self._json(self.gw.local_stats())
            else:
                self._proxy(method, parsed)
        except Exception as e:  # noqa: BLE001 — 500, never a dead conn
            self._drain_body()
            self._json({"error": f"internal: {e}"}, status=500)
        else:
            self._drain_body()

    # ---------------------------------------------------------------- proxy

    def _proxy(self, method: str, parsed, body: bytes | None = None) -> None:
        """Forward one request verbatim to the owner's loopback
        listener and relay the response — the catch-all that keeps
        every non-hot route byte-identical to single-process mode."""
        if body is None:
            body = self._body() if method in ("POST", "DELETE") else b""
            if not body and method == "GET":
                self._body()  # drain a stray GET body for keep-alive
        headers = {}
        for name in _PROXY_REQ_HEADERS:
            val = self.headers.get(name)
            if val is not None:
                headers[name] = val
        path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
        url = f"http://127.0.0.1:{self.gw.owner_port}{path}"
        self.gw.count(proxied=1)
        try:
            resp = self.gw.proxy_pool.request(
                method, url, body=body or None, headers=headers,
            )
        except OSError as e:
            self._json({"error": f"device owner unreachable: {e}"},
                       status=502)
            return
        if resp.status == 204:
            self.send_response(204)
            self.end_headers()
            return
        self.send_response(resp.status)
        ct = resp.headers.get("Content-Type") or "application/json"
        self.send_header("Content-Type", ct)
        self.send_header("Content-Length", str(len(resp.data)))
        for name in _PROXY_RSP_HEADERS[1:]:
            val = resp.headers.get(name)
            if val is not None:
                self.send_header(name, val)
        self.end_headers()
        self.wfile.write(resp.data)

    # ---------------------------------------------------------------- query

    def _qos_envelope(self):
        """Tenant + deadline from headers — the same validation (and
        the same 400 text) as server/http.py's edge envelope."""
        tenant = (self.headers.get(TENANT_HEADER) or "default").strip()
        raw = self.headers.get(DEADLINE_HEADER)
        if raw is not None:
            try:
                millis = int(raw)
                if millis <= 0:
                    raise ValueError
            except ValueError:
                raise _EnvelopeError(
                    f"invalid {DEADLINE_HEADER} header {raw!r}: must be a "
                    "positive integer of milliseconds"
                ) from None
            return tenant, Deadline.from_millis(millis)
        default_s = float(self.gw.cfg.get("defaultDeadlineS") or 0.0)
        if default_s > 0:
            return tenant, Deadline.after(default_s)
        return tenant, None

    def _handle_query(self, index: str, query: dict) -> None:
        raw = self._body()
        content_type = self.headers.get("Content-Type", "")
        accept = self.headers.get("Accept", "")
        remote = bool(query and query.get("remote", ["false"])[0] == "true")
        profile = bool(query and
                       query.get("profile", ["false"])[0] == "true")
        if ("application/x-protobuf" in content_type
                or "application/x-protobuf" in accept
                or remote or profile):
            # protobuf negotiation, remote hops, and PROFILE are
            # rare/internal traffic: the owner's full handler answers
            # them byte-identically via the proxy
            self._proxy("POST", urlparse(self.path), body=raw)
            return
        try:
            tenant, deadline = self._qos_envelope()
        except _EnvelopeError as e:
            self._json({"error": str(e)}, status=400)
            return
        # worker-side parse: reject garbage before it crosses the ring,
        # and learn whether the request writes (for the degraded shed) —
        # memoized on the raw bytes (same bytes, same verdict)
        cached = _PARSE_MEMO.get(raw)
        if cached is None:
            try:
                cached = (None,
                          len(parse(raw.decode(errors="replace"))
                              .write_calls()))
            except ParseError as e:
                cached = (str(e), 0)
            if len(_PARSE_MEMO) >= _PARSE_MEMO_MAX:
                _PARSE_MEMO.clear()
            _PARSE_MEMO[raw] = cached
        perr, writes = cached
        if perr is not None:
            self._json({"error": perr}, status=400)
            return
        max_writes = int(self.gw.cfg.get("maxWritesPerRequest") or 0)
        if 0 < max_writes < writes:
            self._json({"error": (
                f"too many writes in request: {writes} > "
                f"max-writes-per-request {max_writes}")}, status=400)
            return
        if writes and not self._check_degraded():
            return
        # admission: this worker's share of the node quota, shed 429
        # WITHOUT a ring round trip
        slot = None
        try:
            slot = self.gw.admission.admit(tenant)
        except AdmissionError as e:
            self.gw.count(shed=1)
            self._json({"error": str(e)}, status=429,
                       headers={"Retry-After":
                                str(max(1, int(e.retry_after)))})
            return
        try:
            shards = None
            if query and "shards" in query:
                try:
                    shards = [int(s)
                              for s in query["shards"][0].split(",")]
                except ValueError:
                    self._json({"error": "invalid shards parameter "
                                f"{query['shards'][0]!r}"}, status=400)
                    return
            opts = {
                k: True for k in ("columnAttrs", "excludeColumns",
                                  "excludeRowAttrs")
                if query and query.get(k, ["false"])[0] == "true"
            }
            header: dict = {"op": "q", "ix": index, "t": tenant}
            if not writes:
                # read-only marker: ONLY frames the worker-side parse
                # proved write-free are eligible for the owner's
                # dedupe memo (a deduped write would mis-report its
                # per-call changed/unchanged result)
                header["ro"] = 1
            if deadline is not None:
                header["dl"] = deadline.to_millis()
            if shards is not None:
                header["sh"] = shards
            if opts:
                header["o"] = opts
            timeout = (deadline.remaining() + 5.0
                       if deadline is not None else 120.0)
            tracer = global_tracer()
            root_cm = tracer.request_root("http.query", index=index,
                                          tenant=tenant, worker=True)
            root = None
            try:
                with root_cm as root:
                    if root is not None:
                        header["tr"] = root.header_value()
                    meta, payload = self.gw.submit(header, raw, timeout)
                    if root is not None and meta.get("tr"):
                        # graft the owner-side subtree like a remote leg
                        root.add_remote(meta["tr"])
            except RingFull:
                self.gw.count(shed=1)
                self._json({"error": "serving ring full: the device "
                            "owner is saturated; retry after backoff"},
                           status=429, headers={"Retry-After": "1"})
                return
            except OwnerGone as e:
                self._json({"error": str(e)}, status=503,
                           headers={"Retry-After": "5"})
                return
            if root is not None:
                self.gw.send_trace(root.root().to_json())
            self.gw.count(ring_requests=1)
            headers = None
            if meta.get("ra") is not None:
                headers = {"Retry-After": str(max(1, int(meta["ra"])))}
            self._raw(payload, status=int(meta.get("st", 200)),
                      headers=headers)
        finally:
            if slot is not None:
                slot.release()

    def _check_degraded(self) -> bool:
        """Degraded-mode shedding, answered worker-side from the shared
        control block (no ring round-trip); the owner re-checks
        authoritatively for anything that still reaches it."""
        flags = self.gw.degraded_flags()
        if flags & mpserve.ControlBlock.FLAG_STORAGE_DEGRADED:
            self.gw.count(shed=1)
            self._json(
                {"error": mpserve.storage_degraded_msg(
                    self.gw.ctl.reason())},
                status=503, headers={"Retry-After": "5"})
            return False
        if flags & mpserve.ControlBlock.FLAG_CLUSTER_DEGRADED:
            self.gw.count(shed=1)
            self._json({"error": mpserve.CLUSTER_DEGRADED_MSG},
                       status=503, headers={"Retry-After": "5"})
            return False
        return True


class _EnvelopeError(Exception):
    pass


class WorkerHTTPServer(ThreadingHTTPServer):
    """Threading HTTP server over an ALREADY-BOUND listening socket
    (inherited from the owner with SO_REUSEPORT set)."""

    request_queue_size = 128
    disable_nagle_algorithm = True
    daemon_threads = True

    def __init__(self, sock: socket.socket, handler):
        super().__init__(sock.getsockname()[:2], handler,
                         bind_and_activate=False)
        self.socket.close()  # the unbound placeholder __init__ made
        self.socket = sock
        self.server_address = sock.getsockname()[:2]


def worker_main(sock_path: str, listen_fd: int, worker_id: int) -> int:
    """Entry point (``pilosa-tpu serve-worker`` — spawned by
    OwnerRuntime, never run by hand)."""
    gw = WorkerGateway(sock_path, worker_id)
    gw.connect()
    gw.start_dispatcher()
    lsock = socket.socket(fileno=listen_fd)
    handler = type("BoundWorkerHandler", (WorkerHandler,), {"gw": gw})
    server = WorkerHTTPServer(lsock, handler)
    try:
        server.serve_forever(poll_interval=0.5)
    except KeyboardInterrupt:
        pass
    finally:
        gw.close()
        server.server_close()
    return 0
