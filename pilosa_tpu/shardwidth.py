"""Shard-width constants (reference: shardwidth/shardwidth.go, SURVEY.md §2 #27).

The column axis is partitioned into shards of 2^20 columns. On device a
shard-row is a dense bit-vector packed into 32-bit words: 2^20 bits =
32768 uint32 words = 128 KiB. 32768 is a multiple of the TPU lane count
(128), so a row tiles cleanly onto the VPU; uint32 is the native vector
lane width.
"""

SHARD_WIDTH_EXP = 20
SHARD_WIDTH = 1 << SHARD_WIDTH_EXP  # columns per shard (reference: ShardWidth)

WORD_BITS = 32
WORDS_PER_SHARD = SHARD_WIDTH // WORD_BITS  # 32768 uint32 words per row


def shard_of(column_id: int) -> int:
    """Shard that owns an absolute column id (reference: col / ShardWidth)."""
    return column_id >> SHARD_WIDTH_EXP


def position(column_id: int) -> int:
    """Column position within its shard."""
    return column_id & (SHARD_WIDTH - 1)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1). Shared padding/bucketing rule for
    compiled-shape axes (shard blocks, GroupBy chunks, compressed blocks)."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def shard_groups(columns):
    """Group absolute column ids by shard for bulk writes.

    Returns (order, bounds, shards_sorted): ``order`` is the stable
    argsort of the shard of each column; ``bounds[i]:bounds[i+1]`` slices
    ``order``-permuted arrays to the rows of shard ``shards_sorted[bounds
    [i]]``. One implementation of the argsort/diff boundary walk shared
    by every import path (api.import_bits, Index.mark_columns_exist).
    """
    import numpy as np

    cols = np.asarray(columns, np.uint64)
    shards = (cols >> np.uint64(SHARD_WIDTH_EXP)).astype(np.int64)
    order = np.argsort(shards, kind="stable")
    shards_sorted = shards[order]
    bounds = np.concatenate(
        ([0], np.nonzero(np.diff(shards_sorted))[0] + 1, [cols.size])
    )
    return order, bounds, shards_sorted


def keep_last_unique(keys):
    """Sorted indices selecting the LAST occurrence of each unique key —
    the sequential last-write-wins semantics batched writes must match
    (np.unique keeps the FIRST, so dedupe the reversed array and map the
    indices back). Shared by Field.import_values and
    Fragment.import_mutex."""
    import numpy as np

    keys = np.asarray(keys)
    _, first_in_rev = np.unique(keys[::-1], return_index=True)
    return np.sort(keys.size - 1 - first_in_rev)
