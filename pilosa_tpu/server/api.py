"""Transport-neutral API façade.

Reference: api.go (SURVEY.md §2 #18) — validates, resolves index/field,
calls executor/holder; used by both the HTTP handler and the CLI so
in-process imports skip the network entirely.
"""

from __future__ import annotations

import collections
import datetime as dt
import threading

import numpy as np

from pilosa_tpu import __version__
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result import result_to_json
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP, shard_groups
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.wal import MODE_FLUSH_ONLY
from pilosa_tpu.storage.field import (
    TYPE_BOOL,
    TYPE_INT,
    TYPE_MUTEX,
    TYPE_TIME,
)
from pilosa_tpu.storage.view import VIEW_STANDARD
from pilosa_tpu.utils.cost import (
    QueryProfile,
    activate_cost,
    deactivate_cost,
    new_cost_context,
)


class ApiError(Exception):
    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


class ImportRoutingError(ApiError):
    """A routed import failed on one or more owners AFTER other owners'
    batches (fanned out concurrently) already landed. Partial application
    is explicit: ``failed_nodes`` names the owners whose batch did not
    apply, ``node_errors`` maps each to its error text, and ``applied``
    counts the bits/values the healthy owners acknowledged — the caller
    can retry idempotently (imports are set-unions / last-write-wins) or
    surface exactly what is missing."""

    def __init__(self, node_errors: dict[str, str], applied: int,
                 status: int = 502):
        detail = "; ".join(f"{n}: {m}" for n, m in sorted(node_errors.items()))
        super().__init__(
            f"import failed on node(s) {', '.join(sorted(node_errors))} "
            f"({applied} changes applied on healthy owners): {detail}",
            status,
        )
        self.failed_nodes = sorted(node_errors)
        self.node_errors = dict(node_errors)
        self.applied = applied


# Default width of the bounded worker pool applying independent local
# shard groups of one import batch (fragments carry their own locks, so
# groups are lock-disjoint). Overridden by the ``ingest-workers``
# ServerConfig knob. Default 1 (serial). Re-measured after the
# write-path merge kernels (roaring/merge_kernels.py) replaced the
# per-container merge loops: serial apply itself got ~3.3x faster
# (8 shard groups x 60k bits, tmpfs: 5.0-5.4 M rows/s at 1 worker vs
# 1.57 before), 2 workers lands within noise of serial and 4 workers
# loses ~15% to pool overhead on a saturated box. The per-group work is
# now one big numpy kernel call (which releases the GIL) plus a thin
# Python envelope, so modest overlap is possible where spare cores
# exist — but not enough, measured, to move the default. Raise the knob
# where fragment writes pay real disk latency (fsync'd disks, network
# filesystems) so groups overlap I/O stalls — see docs/INGEST.md.
INGEST_WORKERS_DEFAULT = 1


class API:
    def __init__(self, holder: Holder, cluster=None, stats=None):
        self.holder = holder
        self.executor = Executor(holder)
        self.cluster = cluster  # pilosa_tpu.parallel.cluster (M4+); may be None
        self.stats = stats
        self.started_at = dt.datetime.now(dt.timezone.utc)
        # long-query log (reference long-query-time server knob): queries
        # slower than the threshold are logged and kept in a ring buffer.
        self.long_query_time: float = 0.0  # seconds; 0 = off
        # deque(maxlen): append is atomic and bounded, so concurrent HTTP
        # handler threads can't interleave an append/trim pair (ADVICE r1)
        self.long_queries: collections.deque[dict] = collections.deque(maxlen=100)
        # exported from scrape one (/metrics); lock: += from concurrent
        # handler threads would lose increments (same hazard the deque
        # comment above documents)
        self.slow_queries_total = 0
        self._slow_lock = threading.Lock()
        # live JAX profiler capture (POST /debug/trace-device): one at a
        # time; empty dir string = default under the data dir
        self.trace_log_dir: str = ""
        self._device_trace_lock = threading.Lock()
        self.logger = None
        # reference max-writes-per-request server knob: reject queries
        # carrying more write calls than this (0 = unlimited)
        self.max_writes_per_request: int = 5000
        # Parallel ingest (docs/INGEST.md): local shard groups of one
        # import apply on a bounded pool (ingest-workers knob), and
        # routed batches fan out to owner nodes concurrently. The
        # fan-out width is attribute-only (benches pin it to 1 for a
        # serialized baseline).
        self.ingest_workers: int = INGEST_WORKERS_DEFAULT
        from pilosa_tpu.utils.pool import MAX_FANOUT

        self.ingest_fanout_workers: int = MAX_FANOUT
        # Coalescing serving pipeline (server/pipeline.py): read-only
        # requests ride Executor.submit through a wave-forming queue so
        # concurrent HTTP clients share micro-batched dispatches. Set
        # False to serve every request through blocking execute().
        self.serve_pipelined: bool = True
        # Host-path fast lane (docs/OPERATIONS.md): pre-serialized
        # response bytes + identical-query wave dedupe. False restores
        # the round-5 serving path (dict building + json.dumps per
        # request, no dedupe) — the bisection/baseline switch the
        # serving bench uses for its r5-shaped legacy mode.
        self.serve_fastlane: bool = True
        self._pipeline = None  # created lazily on first pipelined query
        self._pipeline_lock = threading.Lock()
        # Serving QoS (pilosa_tpu.qos): admission gate + hedge policy +
        # breakers. Default bundle has the gate OFF (0 = unlimited) and
        # stock hedge knobs; Server.open swaps in the configured one.
        from pilosa_tpu.qos import ServingQos

        self.qos = ServingQos()
        # server default request deadline in seconds (0 = none); a
        # client header always wins (server/http.py)
        self.default_deadline_s: float = 0.0
        # Query cost plane (docs/OBSERVABILITY.md): per-(tenant, index)
        # usage accounting behind GET /debug/tenants + tenant_* metrics,
        # and the SLO burn-rate engine behind GET /debug/slo + slo_*
        # gauges. Server.open swaps in the configured SLO objectives.
        from pilosa_tpu.qos.slo import SLOEngine
        from pilosa_tpu.utils.cost import CostLedger

        self.cost = CostLedger()
        self.slo = SLOEngine()
        # async TopN cache recount (recalculate_caches): one worker at a
        # time, a request landing mid-recount queues exactly one re-run
        self._recalc_lock = threading.Lock()
        self._recalc_thread: threading.Thread | None = None
        self._recalc_rerun = False
        # background integrity scrubber (parallel/scrub.py); Server.open
        # wires one when scrub-interval > 0. scrub_now() runs ad-hoc
        # passes without it.
        self.scrubber = None
        # multi-process serving runtime (serving/mpserve.py OwnerRuntime)
        # when this process is a device owner fronted by SO_REUSEPORT
        # workers; None in single-process mode.
        self.mpserve = None
        # heat-driven residency tiering worker (storage/tiering.py);
        # Server.open wires one when residency-promote-interval > 0.
        # The write-invalidated result cache itself is the process
        # global (serving/rescache.py — fragment write hooks reach it
        # without plumbing), configured by Server.open via
        # result-cache-bytes; both default OFF.
        self.tierer = None
        # autopilot placement planner (autopilot/planner.py); Server.open
        # wires one when autopilot-enabled = true. The placement-override
        # TABLE it writes lives on the cluster and is honored by every
        # node whenever non-empty — the kill switch gates only the
        # planner ticker, never table adoption, so placement stays
        # consistent cluster-wide under mixed configs.
        self.autopilot = None
        # CDC plane (pilosa_tpu/cdc/): Server.open wires a CdcTailer
        # when cdc-enabled = true on a multi-node member (peers' write
        # events feed the result-cache invalidation path, lifting the
        # cluster-edge refusal), and a CdcFollower when cdc-follow names
        # an upstream (this node serves stale-bounded reads off the
        # feed and rejects writes).
        self.cdc = None
        self.follower = None
        # elastic membership plane (autopilot/elastic.py): Server.open
        # wires an ElasticManager on every clustered node — graceful
        # drain must work with the autopilot ticker off. None on a bare
        # API (no server), where drain endpoints answer 503.
        self.elastic = None
        # declared follower staleness budget in seconds (cdc-staleness-
        # budget knob); a request's X-Pilosa-Max-Staleness header wins
        # when tighter
        self.cdc_staleness_budget_s: float = 1.0

    # ---------------------------------------------------------------- query

    def query_raw(self, index: str, pql: str, shards=None,
                  remote: bool = False, opts: dict | None = None,
                  tenant: str = "default", deadline=None,
                  profile_out: list | None = None,
                  pre_admitted: bool = False,
                  on_submitted=None):
        """Execute and return raw result objects (serializer-agnostic).

        QoS envelope: edge requests (``remote=False``) pass the admission
        gate first — shed requests raise ApiError 429 with a Retry-After
        hint and never reach the pipeline. ``deadline`` (qos.Deadline)
        threads through the executor and every inter-node hop; expiry
        maps to ApiError 504.

        Cost envelope (docs/OBSERVABILITY.md): every request runs under
        a CostContext (device-ms, container scans, cache hits — the
        tenant ledger's feed); ``profile_out`` (a list) additionally
        requests a PQL PROFILE — the finished per-AST-node tree,
        cluster legs grafted, is appended to it. Edge outcomes feed the
        SLO engine (429 sheds excluded: shedding is policy, not
        failure)."""
        import time

        from pilosa_tpu.executor.executor import PQLError
        from pilosa_tpu.pql import ParseError
        from pilosa_tpu.qos import AdmissionError, DeadlineExceeded
        from pilosa_tpu.utils.tracing import (
            global_query_tracker,
            global_tracer,
        )

        tracer = global_tracer()
        tracker = global_query_tracker()
        inflight = tracker.start(index, pql, tenant=tenant, remote=remote)
        inflight_token = (tracker.activate(inflight)
                          if inflight is not None else None)
        prof = (QueryProfile(index, pql, self.node_id())
                if profile_out is not None else None)
        ctx = new_cost_context(tenant, index, prof)
        if ctx is None:
            # cost plane disabled (kill switch): a profile would render
            # as a plausible-looking all-zero tree — mark it instead of
            # sending a debugger down a false trail
            prof = None
        cost_token = activate_cost(ctx)
        t_start = time.perf_counter()
        err_status = None
        slot = None
        try:
            if not remote and not pre_admitted:
                # pre_admitted: a serving worker's gate already admitted
                # this request before it crossed the shared-memory ring
                # (serving/worker.py) — double-gating would shed
                # requests the node as a whole has capacity for
                if inflight is not None:
                    inflight.stage = "admission"
                try:
                    with tracer.span("qos.admit", tenant=tenant):
                        slot = self.qos.admission.admit(tenant)
                except AdmissionError as e:
                    err = ApiError(str(e), 429)
                    err.retry_after = e.retry_after
                    raise err from e
            return self._query_raw_admitted(
                index, pql, shards, remote, opts, tenant, deadline,
                slot, inflight, tracer, on_submitted,
            )
        except ApiError as e:
            err_status = e.status
            raise
        except Exception:
            err_status = 500
            raise
        finally:
            deactivate_cost(cost_token)
            elapsed = time.perf_counter() - t_start
            if not remote and ctx is not None:
                # one ledger fold + one SLO event per edge request; the
                # cost kill switch (bench baselines) zeroes this path by
                # making ctx None
                error = err_status is not None and err_status >= 500
                self.cost.record_query(tenant, index, ctx, elapsed,
                                       error=error)
                if err_status != 429:
                    self.slo.record(elapsed, error=error)
            if profile_out is not None and err_status is None:
                profile_out.append(
                    prof.to_json(ctx) if prof is not None
                    else {"disabled": True,
                          "reason": "cost plane is disabled on this node"}
                )
            tracker.finish(inflight, inflight_token)

    def _query_raw_admitted(self, index, pql, shards, remote, opts,
                            tenant, deadline, slot, inflight, tracer,
                            on_submitted=None):
        import time

        from pilosa_tpu.executor.executor import PQLError
        from pilosa_tpu.parallel.cluster import ClusterDegradedError
        from pilosa_tpu.pql import ParseError
        from pilosa_tpu.qos import DeadlineExceeded

        t0 = time.perf_counter()
        try:
            if inflight is not None:
                inflight.stage = "parse"
            query = pql
            if isinstance(pql, str):
                from pilosa_tpu.pql import parse

                query = parse(pql)
            writes = (len(query.write_calls())
                      if hasattr(query, "write_calls") else 1)
            if 0 < self.max_writes_per_request < writes:
                raise ApiError(
                    f"too many writes in request: {writes} > "
                    f"max-writes-per-request {self.max_writes_per_request}"
                )
            if writes and not remote:
                # minority side of a partition is READ-ONLY: an acked
                # write here could be orphaned by the majority's resize
                # (docs/OPERATIONS.md failure model); shed with 503 +
                # Retry-After like the admission gate sheds with 429
                self._check_not_degraded_write()
            kwargs = {"shards": shards}
            if getattr(self.executor, "accepts_remote", False):
                kwargs["remote"] = remote
            if deadline is not None:
                kwargs["deadline"] = deadline
            # Read-only MICRO-BATCHABLE requests ride the coalescing
            # pipeline (waves of concurrent requests share device
            # dispatches — see server/pipeline.py). Requests carrying
            # writes, and host-eager reads (Rows etc.) that submit()
            # would evaluate fully on the dispatcher thread, keep the
            # eager path so request-thread concurrency is unchanged.
            from pilosa_tpu.executor.executor import pipeline_coalescable

            if (writes == 0 and self.serve_pipelined
                    and pipeline_coalescable(query)
                    and hasattr(self.executor, "submit")):
                if self._pipeline is None:
                    with self._pipeline_lock:
                        if self._pipeline is None:
                            from pilosa_tpu.server.pipeline import (
                                QueryPipeline,
                            )

                            self._pipeline = QueryPipeline(self)
                # plain edge reads (PQL string, no explicit shards, no
                # deadline, no result options) are dedupe-eligible:
                # identical queries landing in one wave submit once and
                # share results + pre-serialized response bytes
                key = None
                if (self.serve_fastlane and isinstance(pql, str)
                        and shards is None and deadline is None
                        and not remote and not opts):
                    # PROFILE requests stay dedupe-eligible: a deduped
                    # follower reports dedupeHit=true with near-zero
                    # measured cost — which is the truth (it rode the
                    # leader's execution); the leader's profile carries
                    # the full tree (server/pipeline.py tags both)
                    key = (index, pql)
                if inflight is not None:
                    inflight.stage = "pipeline.wave"
                deferreds = self._pipeline.run(index, query, kwargs,
                                               key=key)
                if on_submitted is not None:
                    # the wave containing this request has been formed
                    # and submitted: the multi-process owner uses this
                    # as the dedupe-join cutoff (serving/mpserve.py) —
                    # the same boundary the pipeline's own wave dedupe
                    # draws, so read-your-writes is preserved across
                    # deployment shapes
                    on_submitted()
                # Same stats/trace envelope as Executor.execute (shared
                # helper) — the timer here observes resolve latency,
                # i.e. what this request actually waited for.
                from pilosa_tpu.executor.executor import instrument_calls

                if inflight is not None:
                    inflight.stage = "executor.resolve"
                handles = iter(deferreds)
                results = instrument_calls(
                    index, query.calls,
                    lambda call: next(handles).result(),
                )
            else:
                if inflight is not None:
                    inflight.stage = "executor.execute"
                if on_submitted is not None:
                    on_submitted()  # eager path: executing right now
                results = self.executor.execute(index, query, **kwargs)
            if opts:
                results = self._apply_request_opts(index, results, opts)
            if writes:
                # attr writes change results (Row responses carry
                # attrs) WITHOUT a fragment write event — fence every
                # cached result of the index (serving/rescache.py);
                # bit writes already invalidated at their fragments.
                # On a multi-node edge, a routed write's fragment hook
                # fires on the OWNER, not here: fence the coordinator's
                # own cache too, so read-your-writes holds through the
                # write's node ahead of the CDC feed's bounded lag.
                remote_owned = (self.cluster is not None
                                and len(self.cluster.nodes) > 1
                                and not remote)
                if remote_owned or any(
                    c.name in ("SetRowAttrs", "SetColumnAttrs")
                    for c in query.write_calls()
                ):
                    from pilosa_tpu.serving import rescache

                    idx = self.holder.index(index)
                    if idx is not None:
                        rescache.invalidate_index_wide(idx.scope, index)
                # ACK gate: a 200 means DURABLE. In group mode this
                # parks the request until the commit thread has fsynced
                # the group containing its op records (one fsync covers
                # the whole wave of concurrent writers — storage/wal.py);
                # per-op already fsynced inline, flush-only promises
                # nothing, and both make this a no-op.
                if inflight is not None:
                    inflight.stage = "wal.barrier"
                self._ack_durable()
            return results
        except DeadlineExceeded as e:
            self.qos.note_deadline_expired()
            raise ApiError(str(e), 504) from e
        except ClusterDegradedError as e:
            # a read that needed shards owned by unreachable peers while
            # this node lacks quorum: 503 so clients back off and retry
            # against a healthy (majority-side) node
            raise self._degraded_error(str(e)) from e
        except (ParseError, PQLError) as e:
            raise ApiError(str(e)) from e
        finally:
            if slot is not None:
                slot.release()
            elapsed = time.perf_counter() - t0
            if self.long_query_time > 0 and elapsed >= self.long_query_time:
                from pilosa_tpu.utils.tracing import current_span

                entry = {
                    "index": index,
                    "pql": (pql if isinstance(pql, str)
                            else str(pql))[:1024],
                    "seconds": round(elapsed, 4),
                    "at": dt.datetime.now(dt.timezone.utc).isoformat(),
                }
                cur = current_span()
                if cur is not None:
                    # sampled offender: the ring keeps its FULL span tree
                    # (snapshot as-of now; open ancestors render with
                    # duration-to-date), so a slow query is explained,
                    # not just counted. Unsampled slow queries keep the
                    # text entry only — raise trace-sample-rate to
                    # explain a recurring one.
                    entry["traceId"] = cur.trace_id
                    entry["trace"] = cur.root().to_json()
                with self._slow_lock:
                    self.slow_queries_total += 1
                self.long_queries.append(entry)
                if self.logger is not None:
                    self.logger.warning(
                        "long query (%.3fs > %.3fs) on %s: %s",
                        elapsed, self.long_query_time, index, entry["pql"],
                    )

    def query(self, index: str, pql: str, shards=None, remote: bool = False,
              opts: dict | None = None, tenant: str = "default",
              deadline=None, profile_out: list | None = None) -> dict:
        results = self.query_raw(index, pql, shards=shards, remote=remote,
                                 opts=opts, tenant=tenant, deadline=deadline,
                                 profile_out=profile_out)
        return {"results": [result_to_json(r) for r in results]}

    def query_json_bytes(self, index: str, pql: str, shards=None,
                         remote: bool = False, opts: dict | None = None,
                         tenant: str = "default", deadline=None,
                         profile_out: list | None = None,
                         pre_admitted: bool = False,
                         on_submitted=None,
                         cache_hit_out: list | None = None) -> bytes:
        """The whole JSON response envelope, pre-serialized (serving fast
        lane): hot result shapes encode straight to bytes — memoized on
        the result objects, so a deduped wave of identical queries
        serializes once — instead of dict-building + json.dumps per
        request (see executor/result.py).

        Result cache (serving/rescache.py): a cache-eligible request —
        the exact ``_SharedDeferred`` dedupe eligibility, persisted
        across waves — is first answered from pre-serialized cached
        bytes (``cache_hit_out`` receives True so callers can tag the
        hit); a miss snapshots the write-version fence BEFORE execution
        and fills afterwards, so a write group-committing concurrently
        with the fill invalidates it (the insert refuses to land)."""
        from pilosa_tpu.executor.result import results_json_bytes

        scope = None
        snap = None
        if (not remote and shards is None and deadline is None and not opts
                and self.serve_fastlane and isinstance(pql, str)):
            from pilosa_tpu.serving.rescache import global_result_cache

            cache = global_result_cache()
            # A cluster edge result folds in remote data whose writes
            # land on OTHER nodes' fragments — cacheable only while the
            # CDC tailer is live, feeding peers' write events into the
            # invalidation path (pilosa_tpu/cdc/). Without it (or with
            # a peer's feed lagging) the edge refuses, and the reason is
            # counted so operators can watch the cache turn on
            # (/debug/rescache refusals).
            edge_ok = (self.cluster is None
                       or len(self.cluster.nodes) <= 1)
            if cache.enabled and not edge_ok:
                if self.cdc is not None and self.cdc.live():
                    edge_ok = True
                else:
                    cache.record_refusal(
                        "cluster-no-cdc" if self.cdc is None
                        else "cdc-stale")
            if cache.enabled and edge_ok:
                idx = self.holder.index(index)
                if idx is not None:
                    scope = idx.scope
                    payload = cache.peek(scope, index, pql)
                    if payload is not None:
                        return self._serve_result_cache_hit(
                            cache, scope, index, pql, payload, tenant,
                            profile_out, pre_admitted, on_submitted,
                            cache_hit_out,
                        )
                    if self._result_cacheable(pql):
                        # a MISS only for fillable queries: writes and
                        # host-eager reads must not dilute the hit rate
                        # operators gate on
                        cache.record_miss()
                        snap = cache.version()  # the fill-race cutoff
                    else:
                        scope = None
        results = self.query_raw(index, pql, shards=shards, remote=remote,
                                 opts=opts, tenant=tenant, deadline=deadline,
                                 profile_out=profile_out,
                                 pre_admitted=pre_admitted,
                                 on_submitted=on_submitted)
        payload = results_json_bytes(results)
        if snap is not None and scope is not None:
            from pilosa_tpu.pql import parse
            from pilosa_tpu.serving.rescache import query_field_deps

            query = parse(pql)  # memoized; the request already paid it
            cache.insert(scope, index, pql, payload,
                         query_field_deps(query), snap)
        return payload

    def _result_cacheable(self, pql: str) -> bool:
        """Read-only + pipeline-coalescable — the ``_SharedDeferred``
        dedupe eligibility family, persisted across waves. Parse errors
        defer to query_raw, which surfaces them properly."""
        from pilosa_tpu.executor.executor import pipeline_coalescable
        from pilosa_tpu.pql import parse

        try:
            query = parse(pql)  # memoized
        except Exception:
            return False
        return not query.write_calls() and pipeline_coalescable(query)

    def _serve_result_cache_hit(self, cache, scope, index, pql, payload,
                                tenant, profile_out, pre_admitted,
                                on_submitted, cache_hit_out) -> bytes:
        """The hit half of query_raw's request envelope: admission
        (unless the serving worker already admitted), inflight
        tracking, a trace span, ledger + SLO accounting — a cache hit
        is billed as a query with near-zero device-ms, never invisible.
        Heat is deliberately NOT recorded: residency should follow the
        traffic that actually executes, and a cache hit needs no
        device bytes (invalidation re-heats the shards on the next
        miss)."""
        import time

        from pilosa_tpu.qos import AdmissionError
        from pilosa_tpu.utils.tracing import (
            global_query_tracker,
            global_tracer,
        )

        tracer = global_tracer()
        tracker = global_query_tracker()
        inflight = tracker.start(index, pql, tenant=tenant, remote=False)
        inflight_token = (tracker.activate(inflight)
                          if inflight is not None else None)
        ctx = new_cost_context(tenant, index, None)
        t_start = time.perf_counter()
        err_status = None
        slot = None
        try:
            if not pre_admitted:
                if inflight is not None:
                    inflight.stage = "admission"
                try:
                    with tracer.span("qos.admit", tenant=tenant):
                        slot = self.qos.admission.admit(tenant)
                except AdmissionError as e:
                    err = ApiError(str(e), 429)
                    err.retry_after = e.retry_after
                    raise err from e
            if inflight is not None:
                inflight.stage = "rescache"
            with tracer.span("rescache.hit", index=index):
                cache.record_hit(scope, index, pql)
            if on_submitted is not None:
                # the dedupe-join cutoff (serving/mpserve.py): a cache
                # hit resolves immediately, so late identical arrivals
                # must start their own (equally cached) pass
                on_submitted()
            if cache_hit_out is not None:
                cache_hit_out.append(True)
            if profile_out is not None:
                # the honest near-zero tree: no parse, no plan, no
                # dispatch happened — the flag explains it, exactly as
                # dedupeHit does for in-wave followers
                if ctx is not None:
                    profile_out.append({
                        "node": self.node_id(), "index": index,
                        "pql": pql[:1024], "wave": 1,
                        "dedupeHit": False, "resultCacheHit": True,
                        "calls": [], "remote": [],
                        "totals": ctx.totals(),
                    })
                else:
                    profile_out.append(
                        {"disabled": True,
                         "reason": "cost plane is disabled on this node"})
            return payload
        except ApiError as e:
            err_status = e.status
            raise
        except Exception:
            err_status = 500
            raise
        finally:
            if slot is not None:
                slot.release()
            elapsed = time.perf_counter() - t_start
            if ctx is not None:
                error = err_status is not None and err_status >= 500
                # a 429-shed request never received the cached bytes:
                # billed as a query (like query_raw's shed path) but
                # not as a cache hit
                self.cost.record_query(
                    tenant, index, ctx, elapsed, error=error,
                    result_cache_hit=err_status is None,
                )
                if err_status != 429:
                    self.slo.record(elapsed, error=error)
            tracker.finish(inflight, inflight_token)

    def query_batch(self, items: list) -> list:
        """Execute a wave-batched internal request (/internal/query-batch):
        ``items`` is ``[(index, pql, shards), ...]`` (optionally a 4th
        element: the item's ``X-Pilosa-Trace`` context) — remote
        sub-queries a peer coalesced toward this node. Every item is
        SUBMITTED before any is resolved, so the batch shares
        micro-batched device dispatches exactly like a local wave
        (server/pipeline.py).

        Returns one outcome per item: ``("ok", [raw results])`` —
        ``("ok", [raw results], span_tree)`` when the item carried trace
        context — or ``("err", message, status)``; per-item isolation,
        one bad sub-query cannot poison its batchmates. Write calls are
        rejected per item: the batch route exists for coalesced reads,
        and remote write fan-out keeps its eager per-request
        semantics."""
        from pilosa_tpu.executor.executor import PQLError, instrument_calls
        from pilosa_tpu.pql import ParseError, parse
        from pilosa_tpu.utils.tracing import global_tracer, use_span

        tracer = global_tracer()
        submitted: list = []
        for item in items:
            index, pql, shards = item[0], item[1], item[2]
            trace_hdr = item[3] if len(item) > 3 else None
            # one remote-root span per traced batch item; its submit and
            # resolve phases re-activate it below so device spans nest
            # correctly, and the finished subtree rides the response
            # back to the coordinator's tree
            span = tracer.remote_span(trace_hdr, "rpc.query",
                                      node=self.node_id(), index=index,
                                      batched=True)
            try:
                query = parse(pql)
                if query.write_calls():
                    raise ApiError(
                        "writes are not allowed on /internal/query-batch")
                if self.holder.index(index) is None:
                    raise ApiError(f"index {index!r} not found", 404)
                kwargs = {"shards": shards}
                if getattr(self.executor, "accepts_remote", False):
                    kwargs["remote"] = True
                if hasattr(self.executor, "submit"):
                    if span is not None:
                        with use_span(span):
                            handles = self.executor.submit(index, query,
                                                           **kwargs)
                    else:
                        handles = self.executor.submit(index, query,
                                                       **kwargs)
                    submitted.append(("defs", index, query, handles, span))
                else:
                    submitted.append(
                        ("eager", index, query,
                         self.executor.execute(index, query, **kwargs),
                         span))
            except (ParseError, PQLError) as e:
                submitted.append(("err", str(e), 400))
            except ApiError as e:
                submitted.append(("err", str(e), e.status))
            except Exception as e:  # item-level internal error
                submitted.append(("err", f"internal: {e}", 500))
        out: list = []
        for entry in submitted:
            if entry[0] == "err":
                out.append(entry)
                continue
            kind, index, query, payload, span = entry
            try:
                if kind == "defs":
                    handles = iter(payload)
                    if span is not None:
                        with use_span(span):
                            results = instrument_calls(
                                index, query.calls,
                                lambda call: next(handles).result(),
                            )
                    else:
                        results = instrument_calls(
                            index, query.calls,
                            lambda call: next(handles).result(),
                        )
                else:
                    results = payload
                if span is not None:
                    tracer.finish_root(span)
                    out.append(("ok", results, span.to_json()))
                else:
                    out.append(("ok", results))
            except (ParseError, PQLError) as e:
                out.append(("err", str(e), 400))
            except ApiError as e:
                out.append(("err", str(e), e.status))
            except Exception as e:
                out.append(("err", f"internal: {e}", 500))
        return out

    def _degraded_error(self, message: str) -> ApiError:
        """503 + Retry-After for the degraded (minority-partition)
        read-only mode, counted on the QoS shed path so operators see
        partition sheds beside admission sheds."""
        from pilosa_tpu.utils.stats import global_stats

        global_stats().count("qos_shed", 1, {"reason": "cluster_degraded"})
        err = ApiError(message, 503)
        err.retry_after = 5.0
        return err

    def _check_not_degraded_write(self) -> None:
        """Shed edge writes while this node is the minority side of a
        partition (cluster.degraded — docs/OPERATIONS.md failure
        model) OR while its storage is degraded (ENOSPC/EIO tripped
        the StorageHealth latch — storage/integrity.py); locally-owned
        reads still serve either way. A CDC follower is read-only by
        construction — a write landing here would silently diverge the
        mirror from its upstream."""
        self._check_not_follower()
        self._check_not_storage_degraded()
        self._check_not_draining()
        cluster = self.cluster
        if cluster is None or not getattr(cluster, "degraded", False):
            return
        raise self._degraded_error(
            "cluster degraded (no member quorum): writes are shed on "
            "this node until the partition heals; locally-owned reads "
            "still serve"
        )

    def _check_not_draining(self) -> None:
        """Shed edge writes on the target of an in-flight drain
        (elastic plane): its shard groups are moving off, and an acked
        write landing mid-departure is exactly the lost-write window
        the drain closes by shedding FIRST. Reads keep serving the
        tail. 503 + Retry-After with the ``draining`` qos_shed
        reason."""
        cluster = self.cluster
        if cluster is None or not getattr(cluster, "draining", False):
            return
        from pilosa_tpu.qos import SHED_REASON_DRAINING
        from pilosa_tpu.utils.stats import global_stats

        global_stats().count("qos_shed", 1,
                             {"reason": SHED_REASON_DRAINING})
        err = ApiError(
            "node is draining: writes are shed while its shard groups "
            "move off; reads still serve until the drain completes",
            503,
        )
        err.retry_after = 5.0
        raise err

    def _check_not_storage_degraded(self) -> None:
        """503 + Retry-After while the disk is sick (a failed WAL
        fsync, snapshot, or .meta write tripped the read-only
        storage_degraded latch). Auto-clears when the health probe's
        write succeeds — clients that honor Retry-After ride it out."""
        health = getattr(self.holder, "health", None)
        if health is None or not health.degraded:
            return
        from pilosa_tpu.utils.stats import global_stats

        global_stats().count("qos_shed", 1, {"reason": "storage_degraded"})
        err = ApiError(
            f"storage degraded ({health.reason}): writes are shed on "
            "this node until a probe write succeeds; reads still serve",
            503,
        )
        err.retry_after = 5.0
        raise err

    def check_staleness(self, max_staleness_s: float | None = None) -> None:
        """Stale-bounded read gate for CDC followers: reject with 503 +
        Retry-After when this replica's feed lag exceeds the budget —
        the request's ``X-Pilosa-Max-Staleness`` header when given, the
        declared ``cdc-staleness-budget`` otherwise. A no-op on
        non-follower nodes (members answer fresh reads; a staleness
        budget is a follower contract)."""
        follower = self.follower
        if follower is None:
            return
        budget = self.cdc_staleness_budget_s
        if max_staleness_s is not None:
            budget = min(budget, max_staleness_s) if budget > 0 \
                else max_staleness_s
        if budget <= 0:
            return
        staleness = follower.staleness_s()
        if staleness > budget:
            from pilosa_tpu.utils.stats import global_stats

            global_stats().count("qos_shed", 1,
                                 {"reason": "follower_stale"})
            err = ApiError(
                f"read replica is {staleness:.3f}s stale, over the "
                f"{budget:.3f}s staleness budget; retry or relax "
                "X-Pilosa-Max-Staleness", 503,
            )
            # capped: an infinite staleness (still in initial sync)
            # must not overflow the Retry-After int rendering
            err.retry_after = min(30.0, max(0.1, staleness - budget))
            raise err

    def _ack_durable(self) -> None:
        """Group-commit durability barrier for the current request's
        writes (applied on THIS node — a routed write's remote portions
        are barriered by each replica before its own 200). In the
        fsyncing modes the key-translation log syncs too: a keyed
        write's bit without its key→ID mapping would recover attributed
        to a different key."""
        wal = getattr(self.holder, "wal", None)
        if wal is None or wal.mode == MODE_FLUSH_ONLY:
            return
        from pilosa_tpu.utils.tracing import global_tracer

        with global_tracer().span("wal.barrier"):
            translate = getattr(self.holder, "translate", None)
            if translate is not None:
                translate.sync()
            wal.barrier()

    def _apply_request_opts(self, index: str, results: list,
                            opts: dict) -> list:
        """Request-level result options (reference QueryRequest
        ColumnAttrs / ExcludeColumns / ExcludeRowAttrs — SURVEY.md §2
        #19 handler query args; exact reference spelling is MED, the
        URL-param names mirror the PQL Options() args). Applied on the
        coordinator AFTER the cross-node merge, to every
        row-materializing result of the request."""
        from pilosa_tpu.executor.executor import (
            column_attr_sets,
            strip_columns,
        )
        from pilosa_tpu.executor.result import RowResult

        idx = self.holder.index(index)
        out = []
        for res in results:
            if isinstance(res, RowResult):
                if opts.get("columnAttrs") and idx is not None:
                    res.column_attrs = column_attr_sets(idx, res)
                if opts.get("excludeRowAttrs"):
                    res.attrs = {}
                if opts.get("excludeColumns"):
                    res = strip_columns(res)
            out.append(res)
        return out

    # --------------------------------------------------------------- schema

    def _check_not_follower(self) -> None:
        """A CDC follower is read-only by construction — a local write
        (data or schema) would silently diverge the mirror from its
        upstream. The follower's own tail-apply bypasses the API and
        writes through the holder directly."""
        if self.follower is not None:
            raise ApiError(
                "this node is a CDC read replica (cdc-follow): writes "
                "must go to the upstream cluster", 403,
            )

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True) -> dict:
        self._check_not_follower()
        self._check_not_storage_degraded()  # schema writes hit .meta
        try:
            idx = self.holder.create_index(
                name, keys=keys, track_existence=track_existence
            )
        except ValueError as e:
            status = 409 if "already exists" in str(e) else 400
            raise ApiError(str(e), status) from e
        self._broadcast({"type": "create-index", "index": name, "keys": keys,
                         "trackExistence": track_existence})
        return idx.schema()

    def _broadcast(self, message: dict) -> None:
        if self.cluster is not None:
            self.cluster.send_sync(message)

    def delete_index(self, name: str) -> None:
        self._check_not_follower()
        try:
            self.holder.delete_index(name)
        except KeyError as e:
            raise ApiError(str(e), 404) from e
        self._broadcast({"type": "delete-index", "index": name})

    def create_field(self, index: str, name: str, options: dict | None = None) -> dict:
        self._check_not_follower()
        self._check_not_storage_degraded()  # schema writes hit .meta
        idx = self._index(index)
        try:
            opts = FieldOptions.from_dict(options or {})
            field = idx.create_field(name, opts)
        except ValueError as e:
            status = 409 if "already exists" in str(e) else 400
            raise ApiError(str(e), status) from e
        self._broadcast({"type": "create-field", "index": index, "field": name,
                         "options": field.options.to_dict()})
        return {"name": field.name, "options": field.options.to_dict()}

    def delete_field(self, index: str, name: str) -> None:
        self._check_not_follower()
        idx = self._index(index)
        try:
            idx.delete_field(name)
        except KeyError as e:
            raise ApiError(str(e), 404) from e
        self._broadcast({"type": "delete-field", "index": index, "field": name})

    def schema(self) -> dict:
        return {"indexes": self.holder.schema()}

    # --------------------------------------------------------------- import

    def import_bits(self, index: str, field: str, rows, columns,
                    timestamps=None, clear: bool = False,
                    remote: bool = False) -> int:
        """Bulk bit import (reference api.Import / fragment.bulkImport):
        batches are grouped by shard and written fragment-wise; in a
        cluster, each shard group is routed to every replica owner."""
        idx = self._index(index)
        fld = self._field(idx, field)
        if not remote:
            self._check_not_degraded_write()
        # validate BEFORE routing: the roaring bulk route ships pre-built
        # bitmaps that the receiving end cannot re-validate, so bad input
        # must 400 here, not corrupt or 500 downstream
        try:
            rows_i = np.asarray(rows, dtype=np.int64)
            columns_i = np.asarray(columns, dtype=np.int64)
        except OverflowError as e:
            raise ApiError(f"row/column id out of range: {e}") from e
        if rows_i.shape != columns_i.shape:
            raise ApiError("rows and columns must be the same length")
        if rows_i.size and (rows_i.min() < 0 or columns_i.min() < 0):
            raise ApiError("rows and columns must be non-negative")
        if timestamps is not None and len(timestamps) != rows_i.size:
            raise ApiError("timestamps must match rows length")
        if (fld.options.type == TYPE_BOOL and rows_i.size
                and rows_i.max() > 1):
            raise ApiError("bool field rows must be 0 (false) or 1 (true)")
        if not remote and self.cluster is not None and len(self.cluster.nodes) > 1:
            return self._route_import(
                index, field, rows_i, columns_i, timestamps, clear,
                values=None,
            )
        rows = rows_i.astype(np.uint64)
        columns = columns_i.astype(np.uint64)
        if rows.size == 0:
            return 0
        import time

        from pilosa_tpu.utils.pool import concurrent_map
        from pilosa_tpu.utils.stats import global_stats

        t0 = time.perf_counter()
        order, boundaries, shards_sorted = shard_groups(columns)
        rows, columns = rows[order], columns[order]
        ts_sorted = [timestamps[i] for i in order] if timestamps is not None else None
        # resolve the view ONCE before the fan-out below — Field.view's
        # create lock makes racing creation safe, but there is no reason
        # to funnel every worker through it
        view = None if clear else fld.view(VIEW_STANDARD, create=True)

        def apply_group(i: int) -> int:
            lo, hi = int(boundaries[i]), int(boundaries[i + 1])
            shard = int(shards_sorted[lo])
            pos = columns[lo:hi] & np.uint64(SHARD_WIDTH - 1)
            changed = 0
            if clear:
                for r, p in zip(rows[lo:hi].tolist(), pos.tolist()):
                    changed += fld.clear_bit(
                        int(r), (shard << SHARD_WIDTH_EXP) + int(p)
                    )
                return changed
            # existence rides the same group worker: the batch is
            # already shard-sorted, so the per-batch argsort inside
            # mark_columns_exist (a serial tail ~half as costly as the
            # data write itself) is skipped entirely
            idx.mark_columns_exist_shard(shard, pos)
            frag = view.fragment(shard, create=True)
            if fld.options.type in (TYPE_MUTEX, TYPE_BOOL):
                # single-value fields: the mutex-aware path clears each
                # column's previous row in the same pass — plain
                # bulk_import would leave columns set in several rows
                changed += frag.import_mutex(rows[lo:hi], pos)
            else:
                changed += frag.bulk_import(rows[lo:hi], pos)
            if ts_sorted is not None and fld.options.type == TYPE_TIME:
                # group the timestamped bits by quantum VIEW and write
                # each view's batch with one bulk_import (the standard
                # view already got them above) — a per-bit set_bit loop
                # re-walks view creation and re-writes standard per bit
                from pilosa_tpu.storage.view import views_for_time

                by_view: dict[str, list] = {}
                for j, ts in enumerate(ts_sorted[lo:hi]):
                    if not ts:
                        continue
                    for vname in views_for_time(
                        VIEW_STANDARD, fld.options.time_quantum,
                        _parse_ts(ts),
                    ):
                        by_view.setdefault(vname, []).append(lo + j)
                for vname, idxs in by_view.items():
                    sel = np.asarray(idxs, np.int64)
                    vfrag = fld.view(vname, create=True).fragment(
                        shard, create=True
                    )
                    vfrag.bulk_import(
                        rows[sel], columns[sel] & np.uint64(SHARD_WIDTH - 1)
                    )
            return changed

        n_groups = boundaries.size - 1
        if n_groups > 1 and self.ingest_workers > 1:
            # shard groups touch disjoint fragments (each with its own
            # lock): apply them on a bounded pool — numpy slicing and the
            # op-log fsync both release the GIL, so groups overlap
            changed = sum(concurrent_map(
                apply_group, range(n_groups),
                max_workers=self.ingest_workers,
            ))
        else:
            changed = sum(apply_group(i) for i in range(n_groups))
        elapsed = time.perf_counter() - t0
        from pilosa_tpu.utils.cost import cost_enabled

        if cost_enabled():
            # per-shard write heat for the import (one record per shard
            # group; the fragment-level hook only fires under a request
            # cost context, so this is the bulk path's single record)
            from pilosa_tpu.storage.heat import global_heat

            heat = global_heat()
            for i in range(n_groups):
                lo, hi = int(boundaries[i]), int(boundaries[i + 1])
                heat.record_write(index, field, int(shards_sorted[lo]),
                                  n=float(hi - lo), scope=idx.scope)
        stats = global_stats()
        tags = {"kind": "bits"}
        stats.count("ingest_rows", rows.size, tags=tags)
        stats.observe("ingest_batch_size", rows.size, tags=tags)
        stats.timing("ingest_apply", elapsed, tags=tags)
        if elapsed > 0:
            stats.gauge("ingest_rows_per_sec", rows.size / elapsed, tags=tags)
        if not clear and self.cluster is not None:
            self.cluster.note_local_shards(
                index, np.unique(shards_sorted).tolist()
            )
        self._ack_durable()  # the import 200 means durable, same as query
        return int(changed)

    def _route_import(self, index, field, rows, columns, timestamps, clear,
                      values=None) -> int:
        """Split an import batch by shard owner and fan out CONCURRENTLY
        (reference api.Import routing — SURVEY.md §3.3; fan-out mirrors
        the read path's concurrent_map, so routed wall time is the MAX of
        per-owner latencies, not the sum). Local portions apply with
        remote=True to stop recursion.

        Destination building is one ``shard_groups`` pass + numpy slices
        of the sort permutation — no per-shard ``np.nonzero`` rescans, no
        Python-list element copies. Per-node errors are captured (one
        dead replica cannot abort or hide the others' batches); imports
        are idempotent (set/clear unions, last-write-wins values), so a
        NODE fault earns one retry before surfacing. Any remaining
        failures raise ImportRoutingError naming the failed nodes and the
        count already applied elsewhere."""
        import time

        import numpy as np

        from pilosa_tpu.parallel.client import ClientError
        from pilosa_tpu.utils.pool import concurrent_map
        from pilosa_tpu.utils.stats import global_stats

        try:
            columns_arr = np.asarray(columns, dtype=np.int64)
            rows_arr = (np.asarray(rows, dtype=np.int64)
                        if values is None else None)
            values_arr = (np.asarray(values, dtype=np.int64)
                          if values is not None else None)
        except (OverflowError, ValueError) as e:
            raise ApiError(f"row/column/value out of range: {e}") from e
        if values_arr is not None and columns_arr.shape != values_arr.shape:
            raise ApiError("columns and values must be the same length")
        if columns_arr.size == 0:
            return 0
        ts_arr = (np.asarray(list(timestamps), dtype=object)
                  if timestamps is not None else None)

        bulk_roaring = False
        if values is None:
            # mutex/bool batches must NOT ride the roaring route: its
            # receiver unions blindly, so a remote replica would keep a
            # column's previous row set (single-value invariant broken,
            # replicas diverged) while the local replica cleared it via
            # import_mutex — ship them as import_bits so the remote end
            # re-runs the mutex-aware path
            fld_type = self._field(self._index(index), field).options.type
            bulk_roaring = (timestamps is None and not clear
                            and fld_type not in (TYPE_MUTEX, TYPE_BOOL))

        from pilosa_tpu.parallel.cluster import global_route_stats

        route_stats = global_route_stats()
        order, bounds, shards_sorted = shard_groups(columns_arr)
        local_parts: list[np.ndarray] = []
        remote_parts: dict[str, tuple[object, list[np.ndarray]]] = {}

        def dispatch(node, sel: np.ndarray) -> None:
            if node.id == self.cluster.local.id:
                local_parts.append(sel)
            else:
                remote_parts.setdefault(node.id, (node, []))[1].append(sel)

        for i in range(bounds.size - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            sel = order[lo:hi]
            shard = int(shards_sorted[lo])
            owners = self.cluster.shard_nodes(index, shard)
            # range-aware write routing (ROADMAP item 2 remainder): a
            # range-split shard's PLAIN SET slices go only to their span
            # owners — anti-entropy's union repair converges the other
            # union owners, which is exactly why only set batches may be
            # narrowed (a clear/mutex/BSI write a union owner missed can
            # never be repaired back out — see range_write_spans)
            spans = (self.cluster.range_write_spans(index, shard)
                     if bulk_roaring else None)
            if spans:
                offs = columns_arr[sel] - shard * SHARD_WIDTH
                covered = np.zeros(sel.size, bool)
                for rlo, rhi, span_nodes in spans:
                    m = (offs >= rlo) & (offs < rhi)
                    if not m.any():
                        continue
                    if span_nodes is None:
                        # a span owner departed: union fan-out carries
                        # this slice until the planner re-plans
                        route_stats.range_fallbacks += 1
                        continue
                    covered |= m
                    route_stats.range_slices += 1
                    for node in span_nodes:
                        dispatch(node, sel[m])
                rest = sel[~covered]
                if rest.size:
                    for node in owners:
                        dispatch(node, rest)
            else:
                route_stats.union_writes += 1
                for node in owners:
                    dispatch(node, sel)

        stats = global_stats()

        def send_once(node, sel: np.ndarray) -> int:
            if values_arr is not None:
                return self.cluster.client.import_values(
                    node.uri, index, field, columns_arr[sel],
                    values_arr[sel], clear=clear,
                )
            if bulk_roaring:
                # plain set-bit batches ship as per-shard roaring bodies
                # — O(bitmap bytes) on the wire (the import-roaring
                # endpoint already unions + tracks existence)
                return self._send_roaring_batch(
                    node, index, field, rows_arr[sel], columns_arr[sel]
                )
            return self.cluster.client.import_bits(
                node.uri, index, field, rows_arr[sel], columns_arr[sel],
                timestamps=(ts_arr[sel].tolist()
                            if ts_arr is not None else None),
                clear=clear,
            )

        def run_local(sel: np.ndarray) -> int:
            if values_arr is not None:
                return self.import_values(
                    index, field, columns_arr[sel], values_arr[sel],
                    clear=clear, remote=True,
                )
            return self.import_bits(
                index, field, rows_arr[sel], columns_arr[sel],
                timestamps=(ts_arr[sel].tolist()
                            if ts_arr is not None else None),
                clear=clear, remote=True,
            )

        def run_remote(node, parts: list[np.ndarray]) -> int:
            sel = parts[0] if len(parts) == 1 else np.concatenate(parts)
            t0 = time.perf_counter()
            try:
                try:
                    return send_once(node, sel)
                except ClientError as e:
                    # imports are idempotent, so a transport/5xx NODE
                    # fault earns one immediate retry (rides out a
                    # heartbeat blip without failing the whole batch);
                    # deterministic 4xx never retries — every replay
                    # would answer the same
                    if not e.is_node_fault:
                        raise
                    stats.count("ingest_retries", 1,
                                tags={"node": node.id})
                    return send_once(node, sel)
            finally:
                stats.timing("ingest_fanout", time.perf_counter() - t0,
                             tags={"node": node.id})

        tasks = []
        labels: list[str | None] = []
        if local_parts:
            sel = (local_parts[0] if len(local_parts) == 1
                   else np.concatenate(local_parts))
            tasks.append(lambda sel=sel: run_local(sel))
            labels.append(None)
        for node, parts in remote_parts.values():
            tasks.append(lambda node=node, parts=parts:
                         run_remote(node, parts))
            labels.append(node.id)

        t0 = time.perf_counter()
        outcomes = concurrent_map(
            lambda fn: fn(), tasks,
            max_workers=max(1, self.ingest_fanout_workers),
            return_exceptions=True,
        )
        stats.timing("ingest_route_wall", time.perf_counter() - t0)
        stats.observe("ingest_fanout_width", len(tasks))

        changed = 0
        node_errors: dict[str, str] = {}
        status = None
        for label, out in zip(labels, outcomes):
            if isinstance(out, Exception):
                name = label or self.cluster.local.id
                node_errors[name] = str(out)
                stats.count("ingest_node_errors", 1, tags={"node": name})
                # deterministic request errors (local validation, remote
                # 4xx) dominate the surfaced status — they mean the
                # REQUEST is bad, not the node
                if isinstance(out, ApiError):
                    status = out.status
                elif (isinstance(out, ClientError)
                      and not out.is_node_fault and status is None):
                    status = out.status
            else:
                changed += out
        if node_errors:
            raise ImportRoutingError(node_errors, changed,
                                     status=status or 502)
        if changed:
            # remote portions' fragment write hooks fired on the OWNER
            # nodes: fence the coordinator's own cached results for the
            # field so read-your-writes holds through this node ahead
            # of the CDC feed's bounded lag (serving/rescache.py)
            from pilosa_tpu.serving import rescache

            idx = self.holder.index(index)
            if idx is not None:
                rescache.invalidate_write(idx.scope, index, field)
        return changed

    def _send_roaring_batch(self, node, index, field, rows_arr,
                            cols_arr) -> int:
        """Ship one node's slice of a routed set-bit import as per-shard
        roaring bodies (fragment id space: row * SHARD_WIDTH + position).
        ``rows_arr``/``cols_arr`` are the node's already-sliced arrays."""
        import numpy as np

        from pilosa_tpu.parallel.cluster import global_route_stats
        from pilosa_tpu.roaring import RoaringBitmap
        from pilosa_tpu.roaring.format import serialize

        rows_arr = np.asarray(rows_arr).astype(np.uint64)
        cols = np.asarray(cols_arr).astype(np.uint64)
        order, bounds, shards_sorted = shard_groups(cols)
        rows_arr, cols = rows_arr[order], cols[order]
        changed = 0
        route_stats = global_route_stats()
        for i in range(bounds.size - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            ids = (rows_arr[lo:hi] * np.uint64(SHARD_WIDTH)
                   + (cols[lo:hi] & np.uint64(SHARD_WIDTH - 1)))
            data = serialize(RoaringBitmap.from_ids(np.unique(ids)))
            # per-acked-write wire accounting: the elastic bench's
            # write-amplification gate reads this before/after a split
            route_stats.wire_bytes += len(data)
            changed += self.cluster.client.import_roaring(
                node.uri, index, field, int(shards_sorted[lo]), data
            )
        return changed

    def import_values(self, index: str, field: str, columns, values,
                      clear: bool = False, remote: bool = False) -> int:
        idx = self._index(index)
        fld = self._field(idx, field)
        if not remote:
            self._check_not_degraded_write()
        if not remote and self.cluster is not None and len(self.cluster.nodes) > 1:
            return self._route_import(
                index, field, None, columns, None, clear, values=values
            )
        if fld.options.type != TYPE_INT:
            raise ApiError(f"field {field!r} is not an int field")
        if len(columns) != len(values):
            raise ApiError("columns and values must be the same length")
        try:
            cols_i = np.asarray(columns, dtype=np.int64)
        except OverflowError as e:  # ids beyond int64: clean 400, not 500
            raise ApiError(f"column id out of range: {e}") from e
        if cols_i.size and cols_i.min() < 0:
            raise ApiError(f"column {int(cols_i.min())} is negative")
        import time

        from pilosa_tpu.utils.stats import global_stats

        t0 = time.perf_counter()
        if clear:
            changed = 0
            for col in cols_i.tolist():
                try:
                    changed += fld.clear_value(int(col))
                except ValueError as e:
                    raise ApiError(str(e)) from e
        else:
            try:
                changed = fld.import_values(
                    cols_i.astype(np.uint64), values
                )
            except (ValueError, OverflowError) as e:
                raise ApiError(str(e)) from e
        elapsed = time.perf_counter() - t0
        from pilosa_tpu.utils.cost import cost_enabled

        if cost_enabled():
            from pilosa_tpu.storage.heat import global_heat

            heat = global_heat()
            shards_u, counts_u = np.unique(
                cols_i >> SHARD_WIDTH_EXP, return_counts=True)
            for shard, n in zip(shards_u.tolist(), counts_u.tolist()):
                heat.record_write(index, field, int(shard), n=float(n),
                                  scope=idx.scope)
        stats = global_stats()
        tags = {"kind": "values"}
        stats.count("ingest_rows", cols_i.size, tags=tags)
        stats.observe("ingest_batch_size", cols_i.size, tags=tags)
        stats.timing("ingest_apply", elapsed, tags=tags)
        if elapsed > 0:
            stats.gauge("ingest_rows_per_sec", cols_i.size / elapsed,
                        tags=tags)
        if not clear:
            idx.mark_columns_exist(cols_i)
            if self.cluster is not None:
                self.cluster.note_local_shards(
                    index,
                    np.unique(cols_i >> SHARD_WIDTH_EXP).tolist(),
                )
        self._ack_durable()
        return int(changed)

    def import_roaring(self, index: str, field: str, shard: int, data: bytes,
                       view: str = VIEW_STANDARD, remote: bool = False,
                       submitted_out: list | None = None) -> int:
        """``submitted_out`` (a list) receives the decoded bit count —
        the HTTP handler bills the tenant ledger by bits SUBMITTED, like
        the row/value import routes, not by bits that happened to
        change (an idempotent retry costs the server the same work)."""
        idx = self._index(index)
        fld = self._field(idx, field)
        if not remote:
            self._check_not_degraded_write()
        frag = fld.view(view, create=True).fragment(shard, create=True)
        from pilosa_tpu.roaring.format import load_any

        try:
            bitmap, _ = load_any(data)
            ids = bitmap.to_ids()
        except ValueError as e:
            raise ApiError(str(e)) from e
        if submitted_out is not None:
            submitted_out.append(int(ids.size))
        # max-writes-per-request applies to EDGE roaring bodies like the
        # JSON/protobuf import routes (a 100k-bit bitmap is no lighter
        # than 100k Set() calls); routed internal slices are exempt —
        # they carry pieces of an already-admitted edge batch
        limit = self.max_writes_per_request
        if not remote and 0 < limit < int(ids.size):
            raise ApiError(
                f"import-roaring body of {int(ids.size)} bits exceeds "
                f"max-writes-per-request {limit}; split the bitmap", 413,
            )
        try:
            changed = frag.add_ids(ids)
        except ValueError as e:
            raise ApiError(str(e)) from e
        from pilosa_tpu.utils.stats import global_stats

        stats = global_stats()
        stats.count("ingest_rows", int(ids.size), tags={"kind": "roaring"})
        stats.observe("ingest_batch_size", int(ids.size),
                      tags={"kind": "roaring"})
        from pilosa_tpu.utils.cost import cost_enabled

        if cost_enabled():
            from pilosa_tpu.storage.heat import global_heat

            global_heat().record_write(index, field, shard,
                                       n=float(ids.size), scope=idx.scope)
        positions = np.unique(ids & np.uint64(SHARD_WIDTH - 1))
        idx.mark_columns_exist(
            ((shard << SHARD_WIDTH_EXP) + positions.astype(np.int64)).tolist()
        )
        if self.cluster is not None:
            self.cluster.note_local_shards(index, [shard])
        self._ack_durable()
        return changed

    # --------------------------------------------------------------- export

    def export_csv(self, index: str, field: str) -> str:
        """CSV of row,column over the standard view (reference api.ExportCSV)."""
        idx = self._index(index)
        fld = self._field(idx, field)
        view = fld.view(VIEW_STANDARD)
        lines = []
        if view is not None:
            for shard in sorted(view.fragments):
                frag = view.fragment(shard)
                for row in frag.row_ids():
                    base = shard << SHARD_WIDTH_EXP
                    for pos in frag.row_columns(row).tolist():
                        lines.append(f"{row},{base + int(pos)}")
        return "\n".join(lines) + ("\n" if lines else "")

    # ---------------------------------------------------------------- info

    def status(self) -> dict:
        # maxWritesPerRequest rides /status so bulk clients (the CLI
        # importer) can clamp their batch size to this server's limit
        # instead of discovering it via 413s
        if self.cluster is not None:
            out = {
                "state": self.cluster.state,
                "nodes": self.cluster.nodes_json(),
                "localID": self.cluster.local.id,
                "maxWritesPerRequest": self.max_writes_per_request,
                # partition-tolerance surface (docs/OPERATIONS.md
                # failure model): the cluster epoch doubles as epoch
                # gossip (peers adopt the max they see), and
                # clusterDegraded tells operators/clients this node is
                # the minority side of a partition (read-only)
                "epoch": self.cluster.epoch,
                "clusterDegraded": bool(self.cluster.degraded),
            }
            # placement-override gossip rides /status (like the epoch):
            # joiners and heartbeat pollers adopt the freshest table
            # without a dedicated round trip. Omitted while no override
            # was ever minted so the common case stays byte-identical
            # to the pre-autopilot wire format.
            if self.cluster.placement.epoch > 0:
                out["placement"] = self.cluster.placement.to_json()
            # the drain record gossips the same way (elastic plane):
            # omitted until a drain has ever run, so the common wire
            # stays byte-identical
            if self.cluster.drain_record.get("epoch"):
                out["drain"] = dict(self.cluster.drain_record)
        else:
            out = {
                "state": "NORMAL",
                "nodes": [{"id": "local", "uri": "localhost",
                           "isCoordinator": True, "state": "NORMAL"}],
                "localID": "local",
                "maxWritesPerRequest": self.max_writes_per_request,
                "epoch": 0,
                "clusterDegraded": False,
            }
        # storage-integrity surface (docs/OPERATIONS.md integrity
        # runbook): storageDegraded = this node's disk tripped the
        # read-only latch (writes shed 503 until a probe write clears)
        health = getattr(self.holder, "health", None)
        out["storageDegraded"] = bool(health is not None
                                      and health.degraded)
        out["storageDegradedReason"] = (health.reason
                                        if health is not None else "")
        # multi-process serving surface (docs/OPERATIONS.md deployment
        # shapes): the worker table tells operators (and the chaos
        # harness) which SO_REUSEPORT workers are alive and which
        # generation each is on
        if self.mpserve is not None:
            out["servingWorkers"] = self.mpserve.workers_json()
        return out

    def info(self) -> dict:
        import jax

        devices = jax.devices()
        return {
            "shardWidth": SHARD_WIDTH,
            "cpuPhysicalCores": 0,
            "version": __version__,
            "devices": [
                {"id": d.id, "platform": d.platform, "kind": getattr(d, "device_kind", "")}
                for d in devices
            ],
        }

    def version(self) -> dict:
        return {"version": __version__}

    def node_id(self) -> str:
        return self.cluster.local.id if self.cluster is not None else "local"

    def cluster_metrics(self) -> dict:
        """Partition-tolerance series (epoch, quorum, heartbeat,
        fencing) for /metrics and /debug/vars — zeros with no cluster
        wired, so the series exist from scrape one either way."""
        if self.cluster is not None and hasattr(self.cluster, "metrics"):
            return self.cluster.metrics()
        return {
            "cluster_epoch": 0, "cluster_quorum": 1,
            "cluster_degraded": 0, "cluster_members": 1,
            "cluster_suspects": 0,
            "cluster_heartbeat_probes_total": 0,
            "cluster_heartbeat_failures_total": 0,
            "cluster_deaths_declared_total": 0,
            "cluster_deaths_vetoed_total": 0,
            "cluster_stale_epoch_rejects_total": 0,
            "cluster_quorum_denials_total": 0,
            "cluster_rejoins_total": 0,
            "cluster_cleanup_deferred_total": 0,
            "cluster_placement_overrides": 0,
            "cluster_placement_epoch": 0,
            "cluster_placement_ranges": 0,
            "elastic_drain_active": 0,
            "elastic_drain_epoch": 0,
            "elastic_draining": 0,
            "elastic_warm_heat_ordered_total": 0,
            "elastic_warm_verified_total": 0,
            "elastic_warm_verify_failed_total": 0,
        }

    def elastic_metrics(self) -> dict:
        """elastic_* drain series for /metrics and /debug/vars — zeros
        with no manager wired, so the series exist from scrape one."""
        if self.elastic is not None:
            return self.elastic.metrics()
        return {
            "elastic_drains_started_total": 0,
            "elastic_drains_completed_total": 0,
            "elastic_drains_failed_total": 0,
            "elastic_drains_aborted_total": 0,
            "elastic_drains_resumed_total": 0,
            "elastic_cursor_handoffs_total": 0,
            "elastic_drain_active": 0,
            "elastic_drain_epoch": 0,
        }

    def elastic_json(self) -> dict:
        """GET /debug/elastic: the drain state machine inspector."""
        if self.elastic is not None:
            return {"enabled": True, **self.elastic.to_json()}
        out = {"enabled": False, "drain": {}, "active": False,
               "draining": False, "metrics": self.elastic_metrics()}
        if self.cluster is not None:
            out["placement"] = self.cluster.placement.to_json()
        return out

    def drain_start(self, node: str) -> dict:
        """POST /cluster/drain/<node>: begin a coordinator-driven
        graceful drain of ``node`` (docs/OPERATIONS.md elastic
        operations runbook)."""
        from pilosa_tpu.autopilot.elastic import ElasticError

        if self.elastic is None:
            raise ApiError("elastic plane not wired on this node", 503)
        try:
            return self.elastic.start_drain(node)
        except ElasticError as e:
            raise ApiError(str(e), e.status)

    def drain_abort(self) -> dict:
        """DELETE /cluster/drain: abort the in-flight drain (the target
        un-sheds; already-moved groups stay where they landed)."""
        from pilosa_tpu.autopilot.elastic import ElasticError

        if self.elastic is None:
            raise ApiError("elastic plane not wired on this node", 503)
        try:
            return self.elastic.abort_drain()
        except ElasticError as e:
            raise ApiError(str(e), e.status)

    def drain_status(self) -> dict:
        """GET /cluster/drain: the drain record + latches."""
        if self.elastic is not None:
            return self.elastic.status()
        return {"drain": {}, "active": False, "draining": False}

    def observability_metrics(self) -> dict:
        """Tracing / inspector / slow-query series for /metrics and
        /debug/vars — every key present from scrape one, zeros included,
        like the other exporter blocks."""
        from pilosa_tpu.utils.tracing import (
            global_query_tracker,
            global_tracer,
        )

        out = {"slow_queries_total": self.slow_queries_total}
        out.update(global_tracer().metrics())
        out.update(global_query_tracker().metrics())
        return out

    def tenants_json(self, k: int = 10, by: str = "device_ms") -> dict:
        """GET /debug/tenants: the full per-(tenant, index) cost table
        plus the top-K offender view (docs/OBSERVABILITY.md)."""
        return {
            "tenants": self.cost.snapshot(),
            "top": self.cost.top(k, by=by),
            "by": by,
            "totals": self.cost.metrics(),
        }

    def start_device_trace(self, seconds: float) -> dict:
        """Capture a live JAX profiler trace around ``seconds`` of real
        traffic (POST /debug/trace-device) into the configured log dir.
        One capture at a time — the profiler is a process-global
        singleton, so a second concurrent request answers 409."""
        import os
        import time as _time

        from pilosa_tpu.utils.tracing import start_jax_trace

        seconds = float(seconds)
        if not 0 < seconds <= 60:
            raise ApiError("secs must be in (0, 60]")
        log_dir = os.path.expanduser(
            self.trace_log_dir
            or os.path.join(self.holder.data_dir, "jax-traces")
        )
        if not self._device_trace_lock.acquire(blocking=False):
            raise ApiError("a device trace capture is already running", 409)
        try:
            os.makedirs(log_dir, exist_ok=True)
            t0 = _time.perf_counter()
            with start_jax_trace(log_dir):
                _time.sleep(seconds)
            return {
                "logDir": log_dir,
                "seconds": round(_time.perf_counter() - t0, 3),
            }
        finally:
            self._device_trace_lock.release()

    def pipeline_metrics(self) -> dict:
        """Wave-coalescing counters for the exporters (zeros until the
        first pipelined query — the series must exist from scrape one so
        rate()/increase() windows are well-behaved)."""
        pipe = self._pipeline
        if pipe is None:
            return {"waves": 0, "coalesced": 0, "deduped": 0}
        return {"waves": pipe.waves, "coalesced": pipe.coalesced,
                "deduped": pipe.deduped}

    def fastlane_metrics(self) -> dict:
        """Serving fast-lane counters (connection pool + remote wave
        batching) for /metrics and /debug/vars — every key present from
        scrape one, zeros included, so rate() windows never see a series
        appear mid-flight."""
        out = {
            "pool_connections_created_total": 0,
            "pool_connections_reused_total": 0,
            "pool_connections_discarded_total": 0,
            "pool_requests_total": 0,
            "pool_idle_connections": 0,
            "remote_batches_total": 0,
            "remote_batched_queries_total": 0,
            "remote_batch_solo_total": 0,
            "remote_batch_fallbacks_total": 0,
        }
        pool = getattr(getattr(self.cluster, "client", None), "pool", None)
        if pool is not None:
            out.update(pool.metrics())
        batcher = getattr(self.executor, "_wave_batcher", None)
        if batcher is not None:
            out.update(batcher.metrics())
        return out

    def mp_metrics(self) -> dict:
        """Multi-process serving series (docs/OBSERVABILITY.md) —
        present from scrape one with zeros in single-process mode, like
        every sibling exporter block, so the deployment-shape flip
        never makes a series appear mid-flight."""
        if self.mpserve is not None:
            return self.mpserve.metrics()
        return {
            "serving_workers": 0,
            "serving_ring_depth": 0,
            "serving_ring_full_total": 0,
            "serving_owner_batch_size": 0.0,
            "serving_owner_batches_total": 0,
            "serving_owner_batched_requests_total": 0,
            "serving_ring_requests_total": 0,
            "serving_worker_shed_total": 0,
            "serving_worker_proxied_total": 0,
            "serving_worker_respawns_total": 0,
            "serving_workers_reaped_total": 0,
            "serving_responses_dropped_total": 0,
            "serving_ring_queries_total": 0,
            "serving_ring_deduped_total": 0,
        }

    def workers_json(self) -> dict:
        """GET /debug/workers: the worker table (id, generation, pid,
        liveness, ring depth, per-worker counters, ring round-trip
        quantiles)."""
        if self.mpserve is None:
            return {"enabled": False, "workers": []}
        return {
            "enabled": True,
            "port": self.mpserve.port,
            "ownerPort": self.mpserve.owner_port,
            "workers": self.mpserve.workers_json(),
        }

    def rescache_metrics(self) -> dict:
        """result_cache_* series (docs/OBSERVABILITY.md) — present from
        scrape one with zeros while the cache is disabled, like every
        sibling exporter block."""
        from pilosa_tpu.serving.rescache import global_result_cache

        return global_result_cache().metrics()

    def tiering_metrics(self) -> dict:
        """residency_tier_* pass counters (storage/tiering.py) — zeros
        with no tierer wired; the per-tier byte gauges ride the
        residency block."""
        if self.tierer is not None:
            return self.tierer.metrics()
        return {
            "residency_tier_passes_total": 0,
            "residency_tier_pass_promotions_total": 0,
            "residency_tier_pass_demotions_total": 0,
            "residency_tier_promoted_bytes_total": 0,
            "residency_tier_demoted_bytes_total": 0,
            "residency_tier_paced_sleep_seconds_total": 0.0,
            "residency_tier_last_pass_seconds": 0.0,
        }

    def autopilot_metrics(self) -> dict:
        """autopilot_* series (autopilot/planner.py) — zeros while the
        planner is off, EXCEPT the placement gauges, which read the
        cluster's override table directly: a node with the kill switch
        off still adopts (and must report) overrides minted elsewhere."""
        if self.autopilot is not None:
            return self.autopilot.metrics()
        placement = getattr(self.cluster, "placement", None)
        return {
            "autopilot_passes_total": 0,
            "autopilot_plans_total": 0,
            "autopilot_moves_planned_total": 0,
            "autopilot_moves_executed_total": 0,
            "autopilot_splits_total": 0,
            "autopilot_merges_total": 0,
            "autopilot_overrides_pruned_total": 0,
            "autopilot_passes_skipped_total": 0,
            "autopilot_placement_overrides":
                len(placement) if placement is not None else 0,
            "autopilot_placement_epoch":
                placement.epoch if placement is not None else 0,
            "autopilot_last_pass_seconds": 0.0,
            "autopilot_slo_burn_rate": 0.0,
        }

    def rescache_json(self, k: int = 100) -> dict:
        """GET /debug/rescache: the result-cache inspector — entry
        table hottest-first plus totals and config."""
        from pilosa_tpu.serving.rescache import global_result_cache

        cache = global_result_cache()
        out = cache.inspect(k=k)
        out["enabled"] = cache.enabled
        # the cluster-edge story in one place: why edges refused before
        # CDC (refusal-reason counters), and — once the tailer is live —
        # the per-peer feed lag that replaces the refusals
        if self.cdc is not None:
            out["cdc"] = {"live": self.cdc.live(),
                          "peerLag": self.cdc.peer_lag()}
        return out

    def durability_metrics(self) -> dict:
        """Write-path durability counters (group-commit WAL) for
        /metrics and /debug/vars — every key present from scrape one,
        zeros included, like the fast-lane block."""
        wal = getattr(self.holder, "wal", None)
        if wal is None:
            return {}
        return wal.metrics()

    # ------------------------------------------------------------------ CDC

    def wal_tail(self, since: int | None, max_bytes: int = 1 << 20,
                 cursor: str | None = None):
        """Serve one ``GET /internal/wal/tail`` poll: committed WAL
        records after ``since`` as ``(events, next_seq, durable_seq)``.
        ``since=None`` is the attach handshake — no events, just the
        durable high-water mark for the consumer to poll from (a fresh
        consumer owns nothing derived from the feed, so it needs no
        history). A named ``cursor`` registers/advances in the WAL's
        registry — the consumer's acknowledged position pins covered
        segments against GC up to the retention budget. Raises the
        storage plane's TailGone (HTTP layer maps it to 410)."""
        from pilosa_tpu.storage.wal import TailGone

        wal = getattr(self.holder, "wal", None)
        if wal is None or not wal.grouped:
            raise ApiError(
                "wal tail requires durability-mode=group on this node",
                501,
            )
        if since is None:
            durable = wal.durable_seq()
            if cursor:
                wal.register_cursor(cursor, durable)
            return [], durable, durable
        if cursor:
            if cursor not in wal.cursors():
                # the registry is in-memory: a poll naming a cursor this
                # WAL never registered proves the producer restarted
                # (its seq space reset) or force-reclaimed the laggard.
                # Answering 410 here closes the silent-gap window where
                # a restarted producer's fresh seq space races past the
                # consumer's stale position before the since > durable
                # check can catch it — attached consumers get hard
                # restart detection; cursorless polls keep best-effort
                # semantics.
                raise TailGone(wal.tail_floor(), wal.durable_seq())
            # advancing the cursor BEFORE the read: since acknowledges
            # everything at or below it, releasing segment pins early
            wal.register_cursor(cursor, since)
        try:
            return wal.read_tail(since, max_bytes=max_bytes)
        except TailGone:
            if cursor:
                # a gone cursor must stop pinning (and stop holding the
                # floor down): the consumer restarts from the handshake
                wal.drop_cursor(cursor)
            raise

    def cdc_metrics(self) -> dict:
        """cdc_* series (docs/OBSERVABILITY.md): producer-side tail
        counters ride durability_metrics (wal.metrics); this block is
        the consumer side — tailer per-peer lag and follower apply
        counters. Present from scrape one with zeros while CDC is off,
        like every sibling exporter block."""
        out = {
            "cdc_enabled": 1 if self.cdc is not None else 0,
            "cdc_live": 0,
            "cdc_peers": 0,
            "cdc_peer_lag_seconds_max": 0.0,
            "cdc_events_total": 0,
            "cdc_invalidations_total": 0,
            "cdc_resyncs_total": 0,
            "cdc_poll_errors_total": 0,
            "cdc_follower": 1 if self.follower is not None else 0,
            "cdc_follower_staleness_seconds": 0.0,
            "cdc_follower_applied_ops_total": 0,
        }
        if self.cdc is not None:
            out.update(self.cdc.metrics())
        if self.follower is not None:
            out.update(self.follower.metrics())
        return out

    def integrity_metrics(self) -> dict:
        """Storage-integrity series (docs/OBSERVABILITY.md): the
        degraded latch, verified-load / quarantine counters, and the
        scrubber's progress — every key present from scrape one, zeros
        included, like the sibling exporter blocks."""
        from pilosa_tpu.storage.integrity import global_integrity

        out = {
            "storage_degraded": 0,
            "storage_degraded_total": 0,
            "storage_recoveries_total": 0,
            "scrub_passes_total": 0,
            "scrub_fragments_scanned_total": 0,
            "scrub_bytes_total": 0,
            "scrub_corruptions_detected_total": 0,
            "scrub_read_repairs_total": 0,
            "scrub_self_heals_total": 0,
            "scrub_unrepaired_total": 0,
            "scrub_last_pass_seconds": 0.0,
            "scrub_paced_sleep_seconds": 0.0,
        }
        out.update(global_integrity().metrics())
        health = getattr(self.holder, "health", None)
        if health is not None:
            out.update(health.metrics())
        if self.scrubber is not None:
            out.update(self.scrubber.metrics())
        return out

    def scrub_now(self) -> dict:
        """One on-demand scrub pass (``POST /internal/scrub``, CLI
        ``check --host``). Uses the configured scrubber when one is
        running (sharing its pacing budget), an unpaced ad-hoc one
        otherwise."""
        scrubber = self.scrubber
        if scrubber is None:
            from pilosa_tpu.parallel.scrub import Scrubber

            # interval 0: no ticker thread — but keep the instance so
            # repeated on-demand passes accumulate into the scrub_*
            # series on /metrics
            scrubber = self.scrubber = Scrubber(self.holder,
                                                cluster=self.cluster)
        return scrubber.scrub_pass()

    def recalculate_caches(self, remote: bool = False) -> threading.Thread:
        """Authoritative recount of every fragment's TopN row cache
        (reference ``POST /recalculate-caches`` → api.RecalculateCaches:
        broadcast to peers, then recount locally). ``remote=True`` marks
        a peer-originated message: apply locally only, no re-broadcast.

        The local recount runs in a BACKGROUND worker (ADVICE r5): on a
        large holder the per-fragment row_counts() scans each take the
        fragment lock, so a synchronous recount in the cluster
        message-delivery path stalls heartbeats and message handling for
        seconds. The HTTP handler returns 204 once the work is queued; a
        recount requested while one is running queues exactly one re-run
        (it starts after the current pass, so it observes any writes the
        in-flight pass missed). Returns the worker thread so in-process
        callers (tests, CLI) can join it."""
        if not remote:
            self._broadcast({"type": "recalculate-caches"})

        def recount():
            from pilosa_tpu.serving import rescache

            while True:
                for idx in list(self.holder.indexes.values()):
                    for field in list(idx.fields.values()):
                        for view in list(field.views.values()):
                            for frag in list(view.fragments.values()):
                                frag.recalculate_cache()
                    # an authoritative recount can change TopN results
                    # with no write event: fence the index's cached
                    # responses (serving/rescache.py)
                    rescache.invalidate_index_wide(idx.scope, idx.name)
                with self._recalc_lock:
                    if not self._recalc_rerun:
                        self._recalc_thread = None
                        return
                    self._recalc_rerun = False

        with self._recalc_lock:
            t = self._recalc_thread
            if t is not None and t.is_alive():
                self._recalc_rerun = True
                return t
            t = threading.Thread(target=recount, daemon=True,
                                 name="recalculate-caches")
            self._recalc_thread = t
            t.start()
            return t

    def max_shards(self) -> dict:
        return {
            "standard": {
                name: (idx.available_shards() or [0])[-1]
                for name, idx in self.holder.indexes.items()
            }
        }

    def shard_nodes(self, index: str, shard: int,
                    col: int | None = None) -> list[dict]:
        if self.cluster:
            if col is not None:
                # range-split refinement (elastic plane): a shard-aware
                # client asking with a column gets the span owners
                # preferred for that column's range; every span owner
                # holds the whole fragment, so the fallback below is
                # always correct too
                nodes = self.cluster.range_read_nodes(
                    index, shard, int(col) - shard * SHARD_WIDTH)
                if nodes:
                    return [n.to_json() for n in nodes]
            return self.cluster.shard_nodes_json(index, shard)
        return [{"id": "local", "uri": "localhost"}]

    # -------------------------------------------------------------- helpers

    def _index(self, name: str):
        idx = self.holder.index(name)
        if idx is None:
            raise ApiError(f"index {name!r} not found", 404)
        return idx

    @staticmethod
    def _field(idx, name: str):
        fld = idx.field(name)
        if fld is None:
            raise ApiError(f"field {name!r} not found", 404)
        return fld


def _parse_ts(value):
    if value is None or value == "":
        # protobuf import bodies encode a missing per-bit timestamp as ""
        return None
    if isinstance(value, dt.datetime):
        return value
    return dt.datetime.fromisoformat(str(value))
