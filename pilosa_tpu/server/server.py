"""Server lifecycle: composition + tickers.

Reference: server.go (SURVEY.md §2 #20) — functional options compose the
holder, cluster, listeners, and background tickers (anti-entropy,
diagnostics, stats flush). Here ServerConfig plays the role of the option
set (populated from TOML/env/flags by pilosa_tpu.cli — SURVEY.md §5.6),
and tickers are daemon threads.
"""

from __future__ import annotations

import threading

from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import make_http_server
from pilosa_tpu.storage import Holder
from pilosa_tpu.utils.logger import new_standard_logger


class ServerConfig:
    def __init__(
        self,
        data_dir: str = "~/.pilosa_tpu",
        bind: str = "localhost",
        port: int = 10101,
        anti_entropy_interval: float = 600.0,
        replica_n: int = 1,
        verbose: bool = False,
        device_budget_bytes: int | None = None,
        name: str = "",
        advertise: str = "",
        seeds: list[str] | None = None,
        heartbeat_interval: float = 5.0,
        heartbeat_timeout: float = 2.0,
        use_mesh: bool | None = None,
        mesh_groups: int = 0,
        topn_quantized_ranking: bool = False,
        tracing: bool = False,
        trace_sample_rate: float = 0.0,
        trace_log_dir: str = "",
        diagnostics_endpoint: str = "",
        statsd: str = "",
        long_query_time: float = 0.0,
        max_writes_per_request: int = 5000,
        ingest_workers: int = 1,
        tls_certificate: str = "",
        tls_key: str = "",
        tls_skip_verify: bool = False,
        qos_max_inflight: int = 0,
        qos_tenant_inflight: int = 0,
        qos_default_deadline: float = 0.0,
        qos_hedge_delay: float = 0.25,
        qos_hedge_budget: float = 0.05,
        qos_breaker_threshold: int = 5,
        qos_breaker_cooldown: float = 5.0,
        client_pool_size: int = 8,
        remote_batch: bool = True,
        sync_workers: int = 8,
        repair_max_bytes_per_sec: int = 0,
        repair_max_inflight: int = 0,
        repair_compression: bool = True,
        durability_mode: str = "group",
        group_commit_max_ms: float = 2.0,
        group_commit_max_ops: int = 256,
        slow_query_ring: int = 100,
        heat_half_life: float = 300.0,
        slo_objectives: list[str] | None = None,
        slo_windows: list[str] | None = None,
        verify_on_load: bool = True,
        scrub_interval: float = 0.0,
        scrub_max_bytes_per_sec: int = 0,
        serving_workers: int = 0,
        ring_slots: int = 1024,
        ring_slot_bytes: int = 65536,
        result_cache_bytes: int = 0,
        residency_promote_interval: float = 0.0,
        residency_promote_heat: float = 4.0,
        residency_demote_heat: float = 1.0,
        residency_host_tier_bytes: int = 1 << 30,
        autopilot_enabled: bool = False,
        autopilot_interval: float = 30.0,
        autopilot_heat_budget: float = 1.5,
        autopilot_max_moves: int = 4,
        autopilot_min_dwell: float = 0.0,
        autopilot_split_threshold: float = 0.0,
        autopilot_split_ways: int = 2,
        cdc_enabled: bool = False,
        cdc_max_retention_bytes: int = 64 << 20,
        cdc_poll_interval: float = 0.05,
        cdc_max_batch_bytes: int = 1 << 20,
        cdc_follow: str = "",
        cdc_staleness_budget: float = 1.0,
    ):
        self.data_dir = data_dir
        self.bind = bind
        self.port = port
        self.anti_entropy_interval = anti_entropy_interval
        self.replica_n = replica_n
        self.verbose = verbose
        self.device_budget_bytes = device_budget_bytes
        self.name = name
        self.advertise = advertise
        self.seeds = seeds or []
        self.heartbeat_interval = heartbeat_interval
        # Tight dedicated timeout for liveness probes (heartbeat, quorum
        # checks, death corroboration): a hung peer must not stall the
        # loop that detects every OTHER failure (docs/OPERATIONS.md
        # failure model).
        self.heartbeat_timeout = float(heartbeat_timeout)
        if self.heartbeat_timeout <= 0:
            raise ValueError(
                f"invalid heartbeat-timeout {heartbeat_timeout!r} "
                "(want > 0)"
            )
        self.use_mesh = use_mesh  # None = auto (mesh when >1 device)
        # 2-D mesh factorization (docs/OPERATIONS.md multi-chip mesh):
        # 0/1 = flat 1-D mesh; >1 = hierarchical groups x shards
        # reductions with the compressed inter-group lane
        if mesh_groups < 0:
            raise ValueError(
                f"invalid mesh-groups {mesh_groups!r} (want >= 0)"
            )
        self.mesh_groups = mesh_groups
        # EQuARX quantized TopN/GroupBy candidate ranking (default off):
        # ranking counts cross the inter-group wire as 8-bit scaled
        # lanes; final results stay byte-identical via the
        # widened-window exact recount (docs/OPERATIONS.md "Multi-chip
        # mesh"). Only meaningful with the mesh executor; harmless
        # (lossless pass-through) on a flat mesh.
        self.topn_quantized_ranking = bool(topn_quantized_ranking)
        # Distributed tracing (docs/OBSERVABILITY.md): `tracing = true`
        # is the legacy always-on switch (rate 1.0); `trace-sample-rate`
        # sets probabilistic sampling directly (0 = off, zero-overhead).
        # `trace-log-dir` is where POST /debug/trace-device writes live
        # JAX profiler captures (default: <data-dir>/jax-traces).
        self.tracing = tracing
        self.trace_sample_rate = float(trace_sample_rate)
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(
                f"invalid trace-sample-rate {trace_sample_rate!r} "
                "(want 0.0..1.0)"
            )
        self.trace_log_dir = trace_log_dir
        self.diagnostics_endpoint = diagnostics_endpoint
        self.statsd = statsd
        self.long_query_time = long_query_time
        self.max_writes_per_request = max_writes_per_request
        # bounded pool width for applying one import's independent local
        # shard groups (docs/INGEST.md); 1 = serial apply
        self.ingest_workers = ingest_workers
        self.tls_certificate = tls_certificate
        self.tls_key = tls_key
        self.tls_skip_verify = tls_skip_verify
        # Serving QoS (docs/QOS.md): admission gate (0 = unlimited),
        # server-default request deadline (0 = none), hedged replica
        # reads (initial delay before the p95 tracker warms up; budget as
        # a fraction of primary reads), per-node circuit breakers.
        self.qos_max_inflight = qos_max_inflight
        self.qos_tenant_inflight = qos_tenant_inflight
        self.qos_default_deadline = qos_default_deadline
        self.qos_hedge_delay = qos_hedge_delay
        self.qos_hedge_budget = qos_hedge_budget
        self.qos_breaker_threshold = qos_breaker_threshold
        self.qos_breaker_cooldown = qos_breaker_cooldown
        # Serving fast lane (docs/OPERATIONS.md): keep-alive connections
        # retained per peer by the internal client's pool, and whether
        # same-node remote sub-queries group-commit onto
        # /internal/query-batch.
        self.client_pool_size = client_pool_size
        self.remote_batch = remote_batch
        # Anti-entropy / resize data plane (docs/OPERATIONS.md): pipeline
        # width for the fragment diff/fetch/apply pass, token-bucket
        # pacing of repair transfers (bytes/sec; 0 = unpaced), inflight
        # transfer cap (0 = unbounded), and zlib Content-Encoding on
        # fragment/delta payloads.
        self.sync_workers = sync_workers
        self.repair_max_bytes_per_sec = repair_max_bytes_per_sec
        self.repair_max_inflight = repair_max_inflight
        self.repair_compression = repair_compression
        # Write-path durability (docs/OPERATIONS.md): how an acked
        # write reaches disk — `group` (one fsync per commit group, the
        # default), `per-op` (fsync per write), or `flush-only` (the
        # round-5 behavior: OS buffer only). The group knobs bound how
        # long a record may wait for its group's fsync and how large a
        # group may grow.
        from pilosa_tpu.storage.wal import DURABILITY_MODES

        if durability_mode not in DURABILITY_MODES:
            raise ValueError(
                f"invalid durability-mode {durability_mode!r} "
                f"(want one of {', '.join(DURABILITY_MODES)})"
            )
        self.durability_mode = durability_mode
        self.group_commit_max_ms = float(group_commit_max_ms)
        self.group_commit_max_ops = int(group_commit_max_ops)
        # Query cost plane (docs/OBSERVABILITY.md): slow-query ring
        # capacity behind /debug/queries/slow (the threshold is
        # long-query-time above), per-shard heat decay half-life, and
        # declarative SLO objectives with their burn-rate windows.
        # Objectives validate at CONFIG time (a typo'd spec must fail
        # startup, not silently never alert) — same policy as
        # trace-sample-rate.
        self.slow_query_ring = int(slow_query_ring)
        if self.slow_query_ring < 1:
            raise ValueError(
                f"invalid slow-query-ring {slow_query_ring!r} (want >= 1)"
            )
        self.heat_half_life = float(heat_half_life)
        if self.heat_half_life <= 0:
            raise ValueError(
                f"invalid heat-half-life {heat_half_life!r} (want > 0)"
            )
        self.slo_objectives = list(slo_objectives or [])
        self.slo_windows = list(slo_windows or [])
        # Storage integrity plane (docs/OPERATIONS.md integrity
        # runbook): verify-on-load checks fragment snapshots against
        # their checksum sidecars at open (corrupt files quarantine
        # instead of serving); scrub-interval > 0 runs the background
        # scrubber that re-verifies owned fragments' DISK bytes on a
        # scrub-max-bytes-per-sec token-bucket budget and read-repairs
        # rot from healthy replicas.
        self.verify_on_load = _parse_bool(verify_on_load)
        self.scrub_interval = float(scrub_interval)
        if self.scrub_interval < 0:
            raise ValueError(
                f"invalid scrub-interval {scrub_interval!r} (want >= 0)"
            )
        self.scrub_max_bytes_per_sec = int(scrub_max_bytes_per_sec)
        # Multi-process serving tier (docs/OPERATIONS.md deployment
        # shapes): serving-workers > 0 runs N SO_REUSEPORT worker
        # processes fronting this (device-owner) process over
        # per-worker shared-memory rings; 0 = classic single-process.
        # ring-slots/ring-slot-bytes size each direction of a worker's
        # ring pair (fixed-slot, so memory is slots x bytes, bounded).
        from pilosa_tpu.serving.mpserve import MAX_WORKERS

        self.serving_workers = int(serving_workers)
        if not 0 <= self.serving_workers <= MAX_WORKERS:
            raise ValueError(
                f"invalid serving-workers {serving_workers!r} "
                f"(want 0..{MAX_WORKERS})"
            )
        self.ring_slots = int(ring_slots)
        if self.ring_slots < 2:
            raise ValueError(
                f"invalid ring-slots {ring_slots!r} (want >= 2)"
            )
        self.ring_slot_bytes = int(ring_slot_bytes)
        if self.ring_slot_bytes < 256:
            raise ValueError(
                f"invalid ring-slot-bytes {ring_slot_bytes!r} "
                "(want >= 256)"
            )
        # Skewed-traffic actuators (docs/OPERATIONS.md skewed traffic):
        # the write-invalidated result cache (bytes of pre-serialized
        # hot responses; 0 = off) and the heat-driven residency tiering
        # worker (promote/demote pass interval; 0 = off) with its
        # hysteresis thresholds — promote must sit above demote or
        # borderline shards would thrash host<->device every pass.
        self.result_cache_bytes = int(result_cache_bytes)
        if self.result_cache_bytes < 0:
            raise ValueError(
                f"invalid result-cache-bytes {result_cache_bytes!r} "
                "(want >= 0)"
            )
        self.residency_promote_interval = float(residency_promote_interval)
        if self.residency_promote_interval < 0:
            raise ValueError(
                "invalid residency-promote-interval "
                f"{residency_promote_interval!r} (want >= 0)"
            )
        self.residency_promote_heat = float(residency_promote_heat)
        self.residency_demote_heat = float(residency_demote_heat)
        if self.residency_demote_heat < 0:
            raise ValueError(
                f"invalid residency-demote-heat {residency_demote_heat!r} "
                "(want >= 0)"
            )
        if self.residency_promote_heat <= self.residency_demote_heat:
            raise ValueError(
                f"residency-promote-heat {residency_promote_heat!r} must "
                f"exceed residency-demote-heat {residency_demote_heat!r} "
                "(the gap IS the hysteresis dead band)"
            )
        self.residency_host_tier_bytes = int(residency_host_tier_bytes)
        if self.residency_host_tier_bytes < 0:
            raise ValueError(
                "invalid residency-host-tier-bytes "
                f"{residency_host_tier_bytes!r} (want >= 0)"
            )
        # Autopilot placement plane (docs/OPERATIONS.md autopilot):
        # the kill switch is OFF by default — with it off no placement
        # overrides are ever minted and shard placement stays
        # byte-identical to the pure hash ring. heat-budget is a
        # multiple of the mean per-node heat (> 1; the planner acts on
        # nodes above it, and the gap between mean and budget IS the
        # hysteresis dead band); max-moves bounds one pass (further
        # shaped down by the repair pacer); min-dwell is the post-move
        # immunity window (0 = auto: two intervals).
        self.autopilot_enabled = _parse_bool(autopilot_enabled)
        self.autopilot_interval = float(autopilot_interval)
        if self.autopilot_interval <= 0:
            raise ValueError(
                f"invalid autopilot-interval {autopilot_interval!r} "
                "(want > 0; use autopilot-enabled=false to turn the "
                "planner off)"
            )
        self.autopilot_heat_budget = float(autopilot_heat_budget)
        if self.autopilot_heat_budget <= 1.0:
            raise ValueError(
                f"invalid autopilot-heat-budget {autopilot_heat_budget!r} "
                "(want > 1.0: the margin over mean node heat IS the "
                "hysteresis dead band)"
            )
        self.autopilot_max_moves = int(autopilot_max_moves)
        if self.autopilot_max_moves < 1:
            raise ValueError(
                f"invalid autopilot-max-moves {autopilot_max_moves!r} "
                "(want >= 1)"
            )
        self.autopilot_min_dwell = float(autopilot_min_dwell)
        if self.autopilot_min_dwell < 0:
            raise ValueError(
                f"invalid autopilot-min-dwell {autopilot_min_dwell!r} "
                "(want >= 0; 0 = two intervals)"
            )
        # Elastic sub-shard split/merge (docs/OPERATIONS.md elastic
        # operations): a shard hotter than split-threshold x mean node
        # load is split into split-ways column ranges spread across
        # nodes; 0 disables the splitter (whole-shard placement only).
        self.autopilot_split_threshold = float(autopilot_split_threshold)
        if self.autopilot_split_threshold < 0:
            raise ValueError(
                f"invalid autopilot-split-threshold "
                f"{autopilot_split_threshold!r} (want >= 0; 0 disables "
                "sub-shard splits)"
            )
        self.autopilot_split_ways = int(autopilot_split_ways)
        if self.autopilot_split_ways < 2:
            raise ValueError(
                f"invalid autopilot-split-ways {autopilot_split_ways!r} "
                "(want >= 2: a split needs at least two ranges)"
            )
        # CDC backbone (docs/OPERATIONS.md Replication & CDC):
        # cdc-enabled runs the peer tailer that makes cluster-edge
        # result caching safe; cdc-max-retention-bytes bounds how much
        # WAL history consumer cursors may pin against segment GC
        # (beyond the budget, reclaim wins and the lagging consumer
        # gets 410 + restart-from-snapshot); cdc-follow points a
        # non-member read replica at an upstream node's URI;
        # cdc-staleness-budget is the follower's declared read-lag
        # bound (X-Pilosa-Max-Staleness can only tighten it, 0 = no
        # bound).
        self.cdc_enabled = _parse_bool(cdc_enabled)
        self.cdc_max_retention_bytes = int(cdc_max_retention_bytes)
        if self.cdc_max_retention_bytes < 0:
            raise ValueError(
                f"invalid cdc-max-retention-bytes "
                f"{cdc_max_retention_bytes!r} (want >= 0)"
            )
        self.cdc_poll_interval = float(cdc_poll_interval)
        if self.cdc_poll_interval <= 0:
            raise ValueError(
                f"invalid cdc-poll-interval {cdc_poll_interval!r} "
                "(want > 0)"
            )
        self.cdc_max_batch_bytes = int(cdc_max_batch_bytes)
        if self.cdc_max_batch_bytes <= 0:
            raise ValueError(
                f"invalid cdc-max-batch-bytes {cdc_max_batch_bytes!r} "
                "(want > 0)"
            )
        self.cdc_follow = str(cdc_follow or "")
        self.cdc_staleness_budget = float(cdc_staleness_budget)
        if self.cdc_staleness_budget < 0:
            raise ValueError(
                f"invalid cdc-staleness-budget {cdc_staleness_budget!r} "
                "(want >= 0; 0 = unbounded)"
            )
        from pilosa_tpu.qos.slo import SLOEngine

        # build once to validate; Server.open builds the live engine
        SLOEngine.from_config(self.slo_objectives, self.slo_windows)

    @property
    def tls_enabled(self) -> bool:
        return bool(self.tls_certificate and self.tls_key)

    @classmethod
    def from_dict(cls, d: dict) -> "ServerConfig":
        # Accept snake_case for EVERY knob by normalizing up front —
        # the per-field d.get("kebab", d.get("snake", ...)) fallbacks
        # below predate this and had drifted (several newer knobs only
        # answered to kebab); the knob-parity contract test now pins
        # the whole surface (tests/test_config_parity.py).
        d = dict(d)
        for k in list(d):
            if isinstance(k, str) and "_" in k:
                d.setdefault(k.replace("_", "-"), d[k])
        tls = d.get("tls") if isinstance(d.get("tls"), dict) else {}
        return cls(
            data_dir=d.get("data-dir", d.get("data_dir", "~/.pilosa_tpu")),
            bind=d.get("bind", "localhost"),
            port=int(d.get("port", 10101)),
            anti_entropy_interval=float(
                d.get("anti-entropy-interval", d.get("anti_entropy_interval", 600.0))
            ),
            replica_n=int(d.get("replica-n", d.get("replica_n", 1))),
            verbose=_parse_bool(d.get("verbose", False)),
            name=d.get("name", ""),
            advertise=d.get("advertise", ""),
            seeds=_parse_list(d.get("seeds", d.get("gossip-seeds", []))),
            heartbeat_interval=float(d.get("heartbeat-interval", 5.0)),
            heartbeat_timeout=_parse_duration(
                d.get("heartbeat-timeout", d.get("heartbeat_timeout", 2.0))
            ),
            tracing=_parse_bool(d.get("tracing", False)),
            trace_sample_rate=float(
                d.get("trace-sample-rate", d.get("trace_sample_rate", 0.0))
            ),
            trace_log_dir=d.get("trace-log-dir",
                                d.get("trace_log_dir", "")),
            diagnostics_endpoint=d.get("diagnostics-endpoint", ""),
            statsd=d.get("statsd", ""),
            long_query_time=_parse_duration(
                d.get("long-query-time", d.get("long_query_time", 0.0))
            ),
            max_writes_per_request=int(
                d.get("max-writes-per-request",
                      d.get("max_writes_per_request", 5000))
            ),
            ingest_workers=int(
                d.get("ingest-workers", d.get("ingest_workers", 1))
            ),
            tls_certificate=d.get("tls-certificate", tls.get("certificate", "")),
            tls_key=d.get("tls-key", tls.get("key", "")),
            tls_skip_verify=_parse_bool(
                d.get("tls-skip-verify", tls.get("skip-verify", False))
            ),
            device_budget_bytes=(
                int(d["device-budget-bytes"])
                if d.get("device-budget-bytes") not in (None, "") else None
            ),
            use_mesh=(
                _parse_bool(d["use-mesh"])
                if d.get("use-mesh") not in (None, "") else None
            ),
            mesh_groups=int(d.get("mesh-groups", 0) or 0),
            topn_quantized_ranking=_parse_bool(
                d.get("topn-quantized-ranking", False)
            ),
            qos_max_inflight=int(d.get("qos-max-inflight", 0)),
            qos_tenant_inflight=int(d.get("qos-tenant-inflight", 0)),
            qos_default_deadline=_parse_duration(
                d.get("qos-default-deadline", 0.0)
            ),
            qos_hedge_delay=_parse_duration(d.get("qos-hedge-delay", 0.25)),
            qos_hedge_budget=float(d.get("qos-hedge-budget", 0.05)),
            qos_breaker_threshold=int(d.get("qos-breaker-threshold", 5)),
            qos_breaker_cooldown=_parse_duration(
                d.get("qos-breaker-cooldown", 5.0)
            ),
            client_pool_size=int(
                d.get("client-pool-size", d.get("client_pool_size", 8))
            ),
            remote_batch=_parse_bool(d.get("remote-batch", True)),
            sync_workers=int(
                d.get("sync-workers", d.get("sync_workers", 8))
            ),
            repair_max_bytes_per_sec=int(
                d.get("repair-max-bytes-per-sec",
                      d.get("repair_max_bytes_per_sec", 0))
            ),
            repair_max_inflight=int(
                d.get("repair-max-inflight",
                      d.get("repair_max_inflight", 0))
            ),
            repair_compression=_parse_bool(
                d.get("repair-compression",
                      d.get("repair_compression", True))
            ),
            durability_mode=str(
                d.get("durability-mode", d.get("durability_mode", "group"))
            ),
            group_commit_max_ms=float(
                d.get("group-commit-max-ms",
                      d.get("group_commit_max_ms", 2.0))
            ),
            group_commit_max_ops=int(
                d.get("group-commit-max-ops",
                      d.get("group_commit_max_ops", 256))
            ),
            slow_query_ring=int(
                d.get("slow-query-ring", d.get("slow_query_ring", 100))
            ),
            heat_half_life=_parse_duration(
                d.get("heat-half-life", d.get("heat_half_life", 300.0))
            ),
            slo_objectives=_parse_list(
                d.get("slo-objectives", d.get("slo_objectives", []))
            ),
            slo_windows=_parse_list(
                d.get("slo-windows", d.get("slo_windows", []))
            ),
            verify_on_load=_parse_bool(
                d.get("verify-on-load", d.get("verify_on_load", True))
            ),
            scrub_interval=_parse_duration(
                d.get("scrub-interval", d.get("scrub_interval", 0.0))
            ),
            scrub_max_bytes_per_sec=int(
                d.get("scrub-max-bytes-per-sec",
                      d.get("scrub_max_bytes_per_sec", 0))
            ),
            serving_workers=int(
                d.get("serving-workers", d.get("serving_workers", 0))
            ),
            ring_slots=int(
                d.get("ring-slots", d.get("ring_slots", 1024))
            ),
            ring_slot_bytes=int(
                d.get("ring-slot-bytes", d.get("ring_slot_bytes", 65536))
            ),
            result_cache_bytes=int(
                d.get("result-cache-bytes", d.get("result_cache_bytes", 0))
            ),
            residency_promote_interval=_parse_duration(
                d.get("residency-promote-interval",
                      d.get("residency_promote_interval", 0.0))
            ),
            residency_promote_heat=float(
                d.get("residency-promote-heat",
                      d.get("residency_promote_heat", 4.0))
            ),
            residency_demote_heat=float(
                d.get("residency-demote-heat",
                      d.get("residency_demote_heat", 1.0))
            ),
            residency_host_tier_bytes=int(
                d.get("residency-host-tier-bytes",
                      d.get("residency_host_tier_bytes", 1 << 30))
            ),
            autopilot_enabled=_parse_bool(
                d.get("autopilot-enabled", False)
            ),
            autopilot_interval=_parse_duration(
                d.get("autopilot-interval", 30.0)
            ),
            autopilot_heat_budget=float(
                d.get("autopilot-heat-budget", 1.5)
            ),
            autopilot_max_moves=int(
                d.get("autopilot-max-moves", 4)
            ),
            autopilot_min_dwell=_parse_duration(
                d.get("autopilot-min-dwell", 0.0)
            ),
            autopilot_split_threshold=float(
                d.get("autopilot-split-threshold",
                      d.get("autopilot_split_threshold", 0.0))
            ),
            autopilot_split_ways=int(
                d.get("autopilot-split-ways",
                      d.get("autopilot_split_ways", 2))
            ),
            cdc_enabled=_parse_bool(d.get("cdc-enabled", False)),
            cdc_max_retention_bytes=int(
                d.get("cdc-max-retention-bytes", 64 << 20)
            ),
            cdc_poll_interval=_parse_duration(
                d.get("cdc-poll-interval", 0.05)
            ),
            cdc_max_batch_bytes=int(
                d.get("cdc-max-batch-bytes", 1 << 20)
            ),
            cdc_follow=d.get("cdc-follow", ""),
            cdc_staleness_budget=_parse_duration(
                d.get("cdc-staleness-budget", 1.0)
            ),
        )

    def to_dict(self) -> dict:
        return {
            "data-dir": self.data_dir,
            "bind": self.bind,
            "port": self.port,
            "anti-entropy-interval": self.anti_entropy_interval,
            "replica-n": self.replica_n,
            "verbose": self.verbose,
            "name": self.name,
            "advertise": self.advertise,
            "seeds": self.seeds,
            "heartbeat-interval": self.heartbeat_interval,
            "heartbeat-timeout": self.heartbeat_timeout,
            "tracing": self.tracing,
            "trace-sample-rate": self.trace_sample_rate,
            "trace-log-dir": self.trace_log_dir,
            "diagnostics-endpoint": self.diagnostics_endpoint,
            "statsd": self.statsd,
            "long-query-time": self.long_query_time,
            "max-writes-per-request": self.max_writes_per_request,
            "ingest-workers": self.ingest_workers,
            "tls-certificate": self.tls_certificate,
            "tls-key": self.tls_key,
            "tls-skip-verify": self.tls_skip_verify,
            "device-budget-bytes": self.device_budget_bytes,
            "use-mesh": self.use_mesh,
            "mesh-groups": self.mesh_groups,
            "topn-quantized-ranking": self.topn_quantized_ranking,
            "qos-max-inflight": self.qos_max_inflight,
            "qos-tenant-inflight": self.qos_tenant_inflight,
            "qos-default-deadline": self.qos_default_deadline,
            "qos-hedge-delay": self.qos_hedge_delay,
            "qos-hedge-budget": self.qos_hedge_budget,
            "qos-breaker-threshold": self.qos_breaker_threshold,
            "qos-breaker-cooldown": self.qos_breaker_cooldown,
            "client-pool-size": self.client_pool_size,
            "remote-batch": self.remote_batch,
            "sync-workers": self.sync_workers,
            "repair-max-bytes-per-sec": self.repair_max_bytes_per_sec,
            "repair-max-inflight": self.repair_max_inflight,
            "repair-compression": self.repair_compression,
            "durability-mode": self.durability_mode,
            "group-commit-max-ms": self.group_commit_max_ms,
            "group-commit-max-ops": self.group_commit_max_ops,
            "slow-query-ring": self.slow_query_ring,
            "heat-half-life": self.heat_half_life,
            "slo-objectives": self.slo_objectives,
            "slo-windows": self.slo_windows,
            "verify-on-load": self.verify_on_load,
            "scrub-interval": self.scrub_interval,
            "scrub-max-bytes-per-sec": self.scrub_max_bytes_per_sec,
            "serving-workers": self.serving_workers,
            "ring-slots": self.ring_slots,
            "ring-slot-bytes": self.ring_slot_bytes,
            "result-cache-bytes": self.result_cache_bytes,
            "residency-promote-interval": self.residency_promote_interval,
            "residency-promote-heat": self.residency_promote_heat,
            "residency-demote-heat": self.residency_demote_heat,
            "residency-host-tier-bytes": self.residency_host_tier_bytes,
            "autopilot-enabled": self.autopilot_enabled,
            "autopilot-interval": self.autopilot_interval,
            "autopilot-heat-budget": self.autopilot_heat_budget,
            "autopilot-max-moves": self.autopilot_max_moves,
            "autopilot-min-dwell": self.autopilot_min_dwell,
            "autopilot-split-threshold": self.autopilot_split_threshold,
            "autopilot-split-ways": self.autopilot_split_ways,
            "cdc-enabled": self.cdc_enabled,
            "cdc-max-retention-bytes": self.cdc_max_retention_bytes,
            "cdc-poll-interval": self.cdc_poll_interval,
            "cdc-max-batch-bytes": self.cdc_max_batch_bytes,
            "cdc-follow": self.cdc_follow,
            "cdc-staleness-budget": self.cdc_staleness_budget,
        }


def _parse_duration(value) -> float:
    """Seconds from a float or a Go-style duration string ('1m30s',
    '500ms' — the reference's TOML uses Go durations). One shared
    grammar for every knob (utils/durations.py; the SLO spec parser
    uses the same one)."""
    from pilosa_tpu.utils.durations import parse_duration

    return parse_duration(value)


def _parse_bool(value) -> bool:
    """TOML gives real bools; env vars give strings ('false', '0', ...)."""
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "t", "yes", "on")
    return bool(value)


def _parse_list(value) -> list[str]:
    if isinstance(value, str):
        return [v.strip() for v in value.split(",") if v.strip()]
    return list(value)


class Server:
    def __init__(self, config: ServerConfig | None = None):
        self.config = config or ServerConfig()
        self.logger = new_standard_logger(verbose=self.config.verbose)
        self.holder = Holder(
            self.config.data_dir,
            durability_mode=self.config.durability_mode,
            group_commit_max_ms=self.config.group_commit_max_ms,
            group_commit_max_ops=self.config.group_commit_max_ops,
            verify_on_load=self.config.verify_on_load,
        )
        self.api = API(self.holder)
        self._http = None
        self._http_thread = None
        self._mpserve = None  # OwnerRuntime when serving-workers > 0
        self._anti_entropy_timer: threading.Timer | None = None
        self._heartbeat_timer: threading.Timer | None = None
        self._closed = threading.Event()

    @property
    def port(self) -> int:
        """The PUBLIC serving port: the SO_REUSEPORT workers' port in
        multi-process mode (the owner's full server moves to loopback),
        the single HTTP listener's otherwise."""
        if self._mpserve is not None:
            return self._mpserve.port
        return self._http.server_address[1] if self._http else self.config.port

    def open(self) -> "Server":
        from pilosa_tpu.storage import residency

        if self.config.device_budget_bytes:
            residency.set_global_row_cache(
                residency.DeviceRowCache(
                    self.config.device_budget_bytes,
                    host_budget_bytes=self.config
                    .residency_host_tier_bytes,
                )
            )
        else:
            residency.global_row_cache().host_budget_bytes = \
                self.config.residency_host_tier_bytes
        # write-invalidated result cache (serving/rescache.py): the
        # process global — fragment write hooks invalidate through it —
        # sized here; 0 keeps it disabled (and clears leftovers from a
        # previous in-process server)
        from pilosa_tpu.serving.rescache import global_result_cache

        global_result_cache().configure(
            self.config.result_cache_bytes,
            half_life_s=self.config.heat_half_life,
        )
        self.holder.open()
        self.api.long_query_time = self.config.long_query_time
        # slow-query ring capacity (slow-query-ring knob): replace the
        # default deque so /debug/queries/slow keeps as many offenders
        # as the operator asked for
        import collections as _collections

        self.api.long_queries = _collections.deque(
            maxlen=self.config.slow_query_ring
        )
        from pilosa_tpu.qos.slo import SLOEngine
        from pilosa_tpu.storage.heat import global_heat

        self.api.slo = SLOEngine.from_config(
            self.config.slo_objectives, self.config.slo_windows
        )
        global_heat().half_life_s = self.config.heat_half_life
        self.api.max_writes_per_request = self.config.max_writes_per_request
        self.api.ingest_workers = max(1, self.config.ingest_workers)
        self.api.logger = self.logger
        if self.config.statsd:
            # statsd sink must be wired BEFORE anything captures the
            # global stats client (ServingQos below) — a late swap would
            # leave qos counting sheds into the discarded default client
            from pilosa_tpu.utils.stats import StatsdStatsClient, set_global_stats

            host, _, port = self.config.statsd.partition(":")
            set_global_stats(
                StatsdStatsClient(host or "127.0.0.1", int(port or 8125))
            )
        from pilosa_tpu.qos import ServingQos
        from pilosa_tpu.utils.stats import global_stats

        self.api.qos = ServingQos(
            max_inflight=self.config.qos_max_inflight,
            tenant_max=self.config.qos_tenant_inflight,
            hedge_delay=self.config.qos_hedge_delay,
            hedge_budget=self.config.qos_hedge_budget,
            breaker_threshold=self.config.qos_breaker_threshold,
            breaker_cooldown=self.config.qos_breaker_cooldown,
            stats=global_stats(),
        )
        self.api.default_deadline_s = self.config.qos_default_deadline
        # Multi-process serving (docs/OPERATIONS.md deployment shapes):
        # with serving-workers > 0 the public port belongs to the
        # SO_REUSEPORT worker processes and THIS process — the device
        # owner — keeps its full HTTP surface on loopback (workers
        # proxy every non-hot route to it). Platforms that can't run
        # the shape fall back to single-process with a warning instead
        # of failing startup.
        mp_workers = 0
        if self.config.serving_workers > 0:
            from pilosa_tpu.serving.mpserve import mp_unsupported_reason

            reason = mp_unsupported_reason(self.config)
            if reason is None:
                mp_workers = self.config.serving_workers
            else:
                self.logger.warning(
                    "multi-process serving disabled: %s "
                    "(falling back to single-process mode)", reason,
                )
        if mp_workers:
            self._http = make_http_server(self.api, "127.0.0.1", 0)
        else:
            self._http = make_http_server(self.api, self.config.bind,
                                          self.config.port)
        if self.config.tls_enabled:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.config.tls_certificate, self.config.tls_key)
            # Wrap per-connection with the handshake deferred: accept() stays
            # cheap in the single accept loop; the handshake runs on first
            # read inside that connection's handler thread, so a stalled
            # client can't block other connections.
            plain_get_request = self._http.get_request

            def tls_get_request():
                conn, addr = plain_get_request()
                conn = ctx.wrap_socket(
                    conn, server_side=True, do_handshake_on_connect=False
                )
                return conn, addr

            self._http.get_request = tls_get_request
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._http_thread.start()
        # tracer BEFORE the serving workers: each worker copies the
        # sample rate out of the handshake cfg, which reads the live
        # global tracer
        from pilosa_tpu.utils.tracing import global_tracer

        rate = self.config.trace_sample_rate
        if rate <= 0 and self.config.tracing:
            rate = 1.0  # legacy `tracing = true`: always-on
        global_tracer().sample_rate = rate
        if mp_workers:
            from pilosa_tpu.serving.mpserve import OwnerRuntime

            self._mpserve = OwnerRuntime(self).start()
            self.api.mpserve = self._mpserve
        self._wire_cluster()
        # CDC backbone (docs/OPERATIONS.md Replication & CDC): the
        # retention budget applies whenever the grouped WAL exists (a
        # registered cursor may pin covered segments up to it); the
        # peer tailer runs only with cdc-enabled, a follower mirror
        # only with cdc-follow. Both ride the cluster's internal
        # client, so feed transfers share the RepairPacer + deflate
        # posture with the sync data plane.
        wal = getattr(self.holder, "wal", None)
        if wal is not None:
            wal.cdc_retention_bytes = self.config.cdc_max_retention_bytes
        self.api.cdc_staleness_budget_s = self.config.cdc_staleness_budget
        if self.config.cdc_enabled:
            from pilosa_tpu.cdc.tailer import CdcTailer

            self.api.cdc = CdcTailer(
                self.api, self.api.cluster.client,
                poll_interval=self.config.cdc_poll_interval,
                max_batch_bytes=self.config.cdc_max_batch_bytes,
                cursor_name=f"tailer:{self.api.cluster.local.id}",
                logger=self.logger,
            )
            self.api.cdc.start()
        if self.config.cdc_follow:
            from pilosa_tpu.cdc.tailer import CdcFollower

            self.api.follower = CdcFollower(
                self.api, self.api.cluster.client,
                self.config.cdc_follow,
                poll_interval=self.config.cdc_poll_interval,
                max_batch_bytes=self.config.cdc_max_batch_bytes,
                cursor_name=f"follower:{self.api.cluster.local.id}",
                logger=self.logger,
            )
            self.api.follower.start()
        # Elastic membership plane (docs/OPERATIONS.md elastic
        # operations): wired on every node — not just when autopilot is
        # on — so whichever node is the acting coordinator can drive a
        # drain, and can resume one adopted from a failed coordinator.
        from pilosa_tpu.autopilot.elastic import ElasticManager

        self.api.elastic = ElasticManager(
            self.api.cluster, logger=self.logger
        )
        if self.config.residency_promote_interval > 0:
            from pilosa_tpu.storage.heat import global_heat as _gh
            from pilosa_tpu.storage.residency import (
                global_row_cache as _grc,
            )
            from pilosa_tpu.storage.tiering import ResidencyTierer

            # promotion uploads share the node's RepairPacer: tiering
            # competes with repair for the same host<->device and wire
            # budgets, and must never starve serving of either
            self.api.tierer = ResidencyTierer(
                cache=_grc(), heat=_gh(),
                interval_s=self.config.residency_promote_interval,
                promote_heat=self.config.residency_promote_heat,
                demote_heat=self.config.residency_demote_heat,
                pacer=self.api.cluster.client.pacer,
                logger=self.logger,
            ).start()
        if self.config.autopilot_enabled:
            from pilosa_tpu.autopilot import Autopilot
            from pilosa_tpu.storage.heat import global_heat as _ap_heat

            # rebalance transfers ride the SAME RepairPacer as repair
            # and tiering: the autopilot's moves are maintenance traffic
            # and must never starve serving of wire or device budget
            self.api.autopilot = Autopilot(
                self.api.cluster, heat=_ap_heat(), slo=self.api.slo,
                interval_s=self.config.autopilot_interval,
                heat_budget=self.config.autopilot_heat_budget,
                max_moves=self.config.autopilot_max_moves,
                min_dwell_s=self.config.autopilot_min_dwell or None,
                split_threshold=self.config.autopilot_split_threshold,
                split_ways=self.config.autopilot_split_ways,
                pacer=self.api.cluster.client.pacer,
                logger=self.logger,
            ).start()
        self.logger.info(
            "listening on %s://%s:%d (data-dir %s, node %s)",
            "https" if self.config.tls_enabled else "http",
            self.config.bind, self.port, self.holder.data_dir,
            self.api.cluster.local.id,
        )
        self.api.trace_log_dir = self.config.trace_log_dir
        from pilosa_tpu.utils.diagnostics import DiagnosticsCollector

        self._diagnostics = DiagnosticsCollector(
            self.api, self.config.diagnostics_endpoint
        )
        self._diagnostics.start()
        if self.config.scrub_interval > 0:
            from pilosa_tpu.parallel.scrub import Scrubber
            from pilosa_tpu.utils.stats import global_stats as _gs

            self.api.scrubber = Scrubber(
                self.holder, cluster=self.api.cluster,
                interval_s=self.config.scrub_interval,
                max_bytes_per_sec=self.config.scrub_max_bytes_per_sec,
                stats=_gs(), logger=self.logger,
            ).start()
        self._schedule_anti_entropy()
        self._schedule_heartbeat()
        return self

    def _wire_cluster(self) -> None:
        """Build the cluster + executor stack: local mesh executor wrapped
        by the cluster router (reference server.go composition)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.parallel.cluster import Cluster, Node
        from pilosa_tpu.parallel.cluster_exec import ClusterExecutor

        name = self.config.name or f"node-{self.port}"
        scheme = "https" if self.config.tls_enabled else "http"
        uri = self.config.advertise or f"{scheme}://{self.config.bind}:{self.port}"
        cluster = Cluster(
            Node(name, uri), replica_n=self.config.replica_n, holder=self.holder,
            insecure_tls=self.config.tls_skip_verify,
            pool_size=self.config.client_pool_size,
        )
        cluster.api = self.api
        cluster.logger = self.logger
        cluster.sync_workers = max(1, self.config.sync_workers)
        cluster.heartbeat_timeout = self.config.heartbeat_timeout
        # fault-injection identity (testing/faults.py): label outbound
        # traffic with this node's name and register the name→endpoint
        # mapping when a plane is installed, so partition rules written
        # against node names match this node's wire both ways
        from pilosa_tpu.testing import faults as _faults

        cluster.client.pool.fault_source = name
        _plane = _faults.active()
        if _plane is not None:
            # register the ADVERTISED endpoint — the hostname:port
            # peers dial (and the connpool keys traffic by) — not the
            # bind address, which differs under advertise= or wildcard
            # binds and would make name-addressed rules miss this node
            _plane.name_endpoint(name, uri.split("://", 1)[-1])
        # repair/resize data-plane shaping: one pacer per node's internal
        # client, shared by every transfer path (manifest deltas,
        # per-block fallbacks, whole-fragment resize fetches)
        from pilosa_tpu.parallel.pacer import RepairPacer
        from pilosa_tpu.utils.stats import global_stats as _stats

        cluster.client.pacer = RepairPacer(
            max_bytes_per_sec=self.config.repair_max_bytes_per_sec,
            max_inflight=self.config.repair_max_inflight,
            stats=_stats(),
        )
        cluster.client.compress_repair = self.config.repair_compression
        self.api.cluster = cluster

        use_mesh = self.config.use_mesh
        if use_mesh is None:
            import jax

            use_mesh = len(jax.devices()) > 1
        if use_mesh:
            from pilosa_tpu.parallel.dist import DistExecutor

            local = DistExecutor(
                self.holder,
                groups=self.config.mesh_groups or None,
                quantized_ranking=self.config.topn_quantized_ranking,
            )
        else:
            local = Executor(self.holder)
        self.api.executor = ClusterExecutor(
            local, cluster, qos=self.api.qos,
            remote_batch=self.config.remote_batch,
        )

        for seed in self.config.seeds:
            try:
                cluster.join(seed)
                break
            except Exception as e:
                self.logger.warning("join via %s failed: %s", seed, e)

    def close(self) -> None:
        self._closed.set()
        if self._mpserve is not None:
            # workers first: they proxy to the owner listener below, and
            # a worker outliving its owner would re-handshake into a
            # closing runtime
            self._mpserve.close()
            self._mpserve = None
            self.api.mpserve = None
        if self.api.scrubber is not None:
            self.api.scrubber.close()
        if self.api.autopilot is not None:
            self.api.autopilot.close()
            self.api.autopilot = None
        if self.api.elastic is not None:
            self.api.elastic.close()
            self.api.elastic = None
        if self.api.tierer is not None:
            self.api.tierer.close()
            self.api.tierer = None
        if self.api.cdc is not None:
            self.api.cdc.stop()
            self.api.cdc = None
        if self.api.follower is not None:
            self.api.follower.stop()
            self.api.follower = None
        if self._anti_entropy_timer is not None:
            self._anti_entropy_timer.cancel()
        if self._heartbeat_timer is not None:
            self._heartbeat_timer.cancel()
        if getattr(self, "_diagnostics", None) is not None:
            self._diagnostics.close()
        if self._http:
            self._http.shutdown()
            self._http.server_close()
        cluster = getattr(self.api, "cluster", None)
        if cluster is not None:
            pool = getattr(cluster.client, "pool", None)
            if pool is not None:
                pool.close()  # drop idle keep-alive connections to peers
        self.holder.close()

    def _schedule_anti_entropy(self) -> None:
        interval = self.config.anti_entropy_interval
        if interval <= 0:
            return

        def tick():
            if self._closed.is_set():
                return
            try:
                self.run_anti_entropy()
            except Exception as e:  # ticker must not die
                self.logger.warning("anti-entropy failed: %s", e)
            self._schedule_anti_entropy()

        timer = threading.Timer(interval, tick)
        timer.daemon = True
        timer.start()
        self._anti_entropy_timer = timer

    def _schedule_heartbeat(self) -> None:
        interval = self.config.heartbeat_interval
        if interval <= 0:
            return

        def tick():
            if self._closed.is_set():
                return
            try:
                if self.api.cluster is not None and len(self.api.cluster.nodes) > 1:
                    self.api.cluster.heartbeat()
                    # drain resumption rides the heartbeat tick: if this
                    # node became acting coordinator while a gossiped
                    # drain record is still active, pick up the state
                    # machine where the dead coordinator left it
                    if self.api.elastic is not None:
                        self.api.elastic.maybe_resume()
            except Exception as e:
                self.logger.warning("heartbeat failed: %s", e)
            self._schedule_heartbeat()

        timer = threading.Timer(interval, tick)
        timer.daemon = True
        timer.start()
        self._heartbeat_timer = timer

    def run_anti_entropy(self) -> None:
        """Replica repair pass (reference monitorAntiEntropy →
        HolderSyncer.SyncHolder — SURVEY.md §3.5). With no cluster peers
        configured this is a no-op."""
        if self.api.cluster is not None:
            self.api.cluster.sync_holder()
