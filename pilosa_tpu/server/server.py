"""Server lifecycle: composition + tickers.

Reference: server.go (SURVEY.md §2 #20) — functional options compose the
holder, cluster, listeners, and background tickers (anti-entropy,
diagnostics, stats flush). Here ServerConfig plays the role of the option
set (populated from TOML/env/flags by pilosa_tpu.cli — SURVEY.md §5.6),
and tickers are daemon threads.
"""

from __future__ import annotations

import threading

from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import make_http_server
from pilosa_tpu.storage import Holder
from pilosa_tpu.utils.logger import new_standard_logger


class ServerConfig:
    def __init__(
        self,
        data_dir: str = "~/.pilosa_tpu",
        bind: str = "localhost",
        port: int = 10101,
        anti_entropy_interval: float = 600.0,
        replica_n: int = 1,
        verbose: bool = False,
        device_budget_bytes: int | None = None,
    ):
        self.data_dir = data_dir
        self.bind = bind
        self.port = port
        self.anti_entropy_interval = anti_entropy_interval
        self.replica_n = replica_n
        self.verbose = verbose
        self.device_budget_bytes = device_budget_bytes

    @classmethod
    def from_dict(cls, d: dict) -> "ServerConfig":
        return cls(
            data_dir=d.get("data-dir", d.get("data_dir", "~/.pilosa_tpu")),
            bind=d.get("bind", "localhost"),
            port=int(d.get("port", 10101)),
            anti_entropy_interval=float(
                d.get("anti-entropy-interval", d.get("anti_entropy_interval", 600.0))
            ),
            replica_n=int(d.get("replica-n", d.get("replica_n", 1))),
            verbose=_parse_bool(d.get("verbose", False)),
        )

    def to_dict(self) -> dict:
        return {
            "data-dir": self.data_dir,
            "bind": self.bind,
            "port": self.port,
            "anti-entropy-interval": self.anti_entropy_interval,
            "replica-n": self.replica_n,
            "verbose": self.verbose,
        }


def _parse_bool(value) -> bool:
    """TOML gives real bools; env vars give strings ('false', '0', ...)."""
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "t", "yes", "on")
    return bool(value)


class Server:
    def __init__(self, config: ServerConfig | None = None):
        self.config = config or ServerConfig()
        self.logger = new_standard_logger(verbose=self.config.verbose)
        self.holder = Holder(self.config.data_dir)
        self.api = API(self.holder)
        self._http = None
        self._http_thread = None
        self._anti_entropy_timer: threading.Timer | None = None
        self._closed = threading.Event()

    @property
    def port(self) -> int:
        return self._http.server_address[1] if self._http else self.config.port

    def open(self) -> "Server":
        if self.config.device_budget_bytes:
            from pilosa_tpu.storage import residency

            residency.set_global_row_cache(
                residency.DeviceRowCache(self.config.device_budget_bytes)
            )
        self.holder.open()
        self._http = make_http_server(self.api, self.config.bind, self.config.port)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._http_thread.start()
        self.logger.info(
            "listening on http://%s:%d (data-dir %s)",
            self.config.bind, self.port, self.holder.data_dir,
        )
        self._schedule_anti_entropy()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._anti_entropy_timer is not None:
            self._anti_entropy_timer.cancel()
        if self._http:
            self._http.shutdown()
            self._http.server_close()
        self.holder.close()

    def _schedule_anti_entropy(self) -> None:
        interval = self.config.anti_entropy_interval
        if interval <= 0:
            return

        def tick():
            if self._closed.is_set():
                return
            try:
                self.run_anti_entropy()
            except Exception as e:  # ticker must not die
                self.logger.warning("anti-entropy failed: %s", e)
            self._schedule_anti_entropy()

        timer = threading.Timer(interval, tick)
        timer.daemon = True
        timer.start()
        self._anti_entropy_timer = timer

    def run_anti_entropy(self) -> None:
        """Replica repair pass (reference monitorAntiEntropy →
        HolderSyncer.SyncHolder — SURVEY.md §3.5). With no cluster peers
        configured this is a no-op."""
        if self.api.cluster is not None:
            self.api.cluster.sync_holder()
