"""Coalescing query pipeline for the serving path.

The reference serves N concurrent HTTP queries with ~linear scaling
because each request's mapReduce runs in its own goroutines and the
compute device IS the host CPU (SURVEY.md §2 #12, §3.2). On a TPU
backend the scarce resource is DISPATCHES: every host→device round trip
pays a fixed latency floor (tens of ms through a tunneled runtime), so N
concurrent requests that each dispatch alone serialize into N floors no
matter how many handler threads the HTTP server has.

This stage restores the reference's concurrency profile the TPU way:

- Request threads enqueue and block on a Future; a single dispatcher
  thread drains the queue in WAVES and pushes every waiting request
  through ``executor.submit`` BEFORE any result is resolved. Same-shape
  reductions across the wave coalesce into micro-batched device programs
  (executor/batch.py), so the whole wave shares dispatches.
- The dispatcher hands back the per-call ``Deferred`` handles; each
  REQUEST thread resolves its own. Readbacks and cross-node fan-outs
  therefore run concurrently across requests, and one slow peer cannot
  convoy the queue behind it — the dispatcher never blocks on I/O.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future


class QueryPipeline:
    """Wave-coalescing front end over ``executor.submit``.

    Created lazily by the API façade; reads ``api.executor`` at dispatch
    time so the server can swap in DistExecutor/ClusterExecutor after
    construction (server.py wiring) without re-plumbing.
    """

    def __init__(self, api):
        self._api = api
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.waves = 0          # dispatch waves formed (observability)
        self.coalesced = 0      # requests that shared a wave with others

    # ------------------------------------------------------------- frontend

    def run(self, index: str, query, kwargs: dict) -> list:
        """Queue one request; returns its per-call Deferreds once the
        whole wave containing it has been submitted. The caller resolves
        them (concurrently across request threads)."""
        self._ensure_thread()
        fut: Future = Future()
        self._q.put((index, query, kwargs, fut))
        return fut.result()

    # ----------------------------------------------------------- dispatcher

    def _ensure_thread(self):
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="query-pipeline"
                )
                self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            wave = [item]
            while True:
                try:
                    wave.append(self._q.get_nowait())
                except queue.Empty:
                    break
            executor = self._api.executor
            self.waves += 1
            if len(wave) > 1:
                self.coalesced += len(wave)
            # Submit the ENTIRE wave before completing any future: the
            # executor's micro-batcher flushes a pending group on its
            # first result(), so a request thread resuming early would
            # split the wave's shared dispatch.
            done = []
            for index, q, kwargs, fut in wave:
                try:
                    done.append((fut, executor.submit(index, q, **kwargs)))
                except BaseException as e:
                    fut.set_exception(e)
            for fut, defs in done:
                fut.set_result(defs)
