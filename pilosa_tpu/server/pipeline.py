"""Coalescing query pipeline for the serving path.

The reference serves N concurrent HTTP queries with ~linear scaling
because each request's mapReduce runs in its own goroutines and the
compute device IS the host CPU (SURVEY.md §2 #12, §3.2). On a TPU
backend the scarce resource is DISPATCHES: every host→device round trip
pays a fixed latency floor (tens of ms through a tunneled runtime), so N
concurrent requests that each dispatch alone serialize into N floors no
matter how many handler threads the HTTP server has.

This stage restores the reference's concurrency profile the TPU way:

- Request threads enqueue and block on a Future; a single dispatcher
  thread drains the queue in WAVES and pushes every waiting request
  through ``executor.submit`` BEFORE any result is resolved. Same-shape
  reductions across the wave coalesce into micro-batched device programs
  (executor/batch.py), so the whole wave shares dispatches.
- The dispatcher hands back the per-call ``Deferred`` handles; each
  REQUEST thread resolves its own. Readbacks and cross-node fan-outs
  therefore run concurrently across requests, and one slow peer cannot
  convoy the queue behind it — the dispatcher never blocks on I/O.
"""

from __future__ import annotations

import contextvars
import queue
import threading
import time
from concurrent.futures import Future

from pilosa_tpu.utils.cost import current_cost


class _SharedDeferred:
    """Deferred handle shared by deduped wavemates: the first resolver
    computes (executor Deferreds are not safe to resolve concurrently),
    everyone else gets the memoized value — or the memoized exception,
    re-raised per request so error semantics match a solo submit."""

    __slots__ = ("_deferred", "_lock", "_done", "_value", "_error")

    def __init__(self, deferred):
        self._deferred = deferred
        self._lock = threading.Lock()
        self._done = False
        self._value = None
        self._error = None

    def result(self):
        with self._lock:
            if not self._done:
                try:
                    self._value = self._deferred.result()
                except BaseException as e:
                    self._error = e
                self._done = True
                self._deferred = None
        if self._error is not None:
            # per-caller copies: concurrent raises of ONE instance would
            # mutate its __traceback__/__context__ across threads (the
            # wave batcher clones for the same reason — _clone_error)
            import copy

            try:
                err = copy.copy(self._error)
            except Exception:
                err = self._error  # uncopyable custom exception: degrade
            raise err
        return self._value


class QueryPipeline:
    """Wave-coalescing front end over ``executor.submit``.

    Created lazily by the API façade; reads ``api.executor`` at dispatch
    time so the server can swap in DistExecutor/ClusterExecutor after
    construction (server.py wiring) without re-plumbing.
    """

    # Adaptive gather (see _loop): once the inter-arrival gap drops
    # under PRESSURE_GAP_S the dispatcher holds a forming wave open for
    # up to GATHER_WINDOW_S (or until GATHER_CAP requests) so closed-
    # loop clients arriving a millisecond apart share a dispatch. Under
    # pressure the added latency is bounded by the window; with sparse
    # traffic the gap check keeps the zero-wait fast path.
    GATHER_WINDOW_S = 0.002
    # Just under the ~5 ms inter-arrival gap of 16 closed-loop clients
    # on an ~80 ms-RTT tunnel: measured on-chip, 16 clients lose ~6% to
    # a window that cannot grow their waves, while 64/128 clients
    # (1-2 ms gaps) gain 0/+27% from it — the gate should open between
    # those regimes.
    PRESSURE_GAP_S = 0.004
    GATHER_CAP = 16  # window-phase fallback when no executor is wired;
                     # the live executor's microbatch_max wins otherwise

    def __init__(self, api):
        self._api = api
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._last_arrival = 0.0
        self._recent_gap = float("inf")  # gap between the last 2 arrivals
        self._last_wave_size = 0  # latch breaker: did the window pay off?
        self.waves = 0          # dispatch waves formed (observability)
        self.coalesced = 0      # requests that shared a wave with others
        self.deduped = 0        # requests served off an identical wavemate

    # ------------------------------------------------------------- frontend

    def run(self, index: str, query, kwargs: dict, key=None) -> list:
        """Queue one request; returns its per-call Deferreds once the
        whole wave containing it has been submitted. The caller resolves
        them (concurrently across request threads).

        ``key`` (optional) marks the request dedupe-eligible: wavemates
        carrying the SAME key are submitted once and share the resulting
        Deferreds (behind a memoizing wrapper, so concurrent resolves are
        race-free). The API façade only passes a key for plain edge reads
        — no explicit shards, no deadline, no result options — where
        identical PQL strings are guaranteed identical requests."""
        from pilosa_tpu.utils.tracing import global_tracer

        self._ensure_thread()
        now = time.monotonic()
        # benign races: both fields are plain floats read heuristically
        self._recent_gap = now - self._last_arrival
        self._last_arrival = now
        fut: Future = Future()
        # the dispatcher thread submits on this request's behalf: hand it
        # a COPY of this context so spans started during submit (device
        # dispatch, remote fan-out departure) join this request's trace
        # instead of being orphaned on the pipeline thread
        ctx = contextvars.copy_context()
        self._q.put((index, query, kwargs, fut, key, ctx))
        with global_tracer().span("pipeline.wave") as span:
            defs = fut.result()
            if span is not None:
                span.tags["wave"] = getattr(fut, "wave_size", 1)
                if getattr(fut, "dedupe_hit", False):
                    span.tags["deduped"] = True
        cost = current_cost()
        if cost is not None and cost.profile is not None:
            # PROFILE wave facts: how many requests shared this wave and
            # whether this one rode an identical wavemate (a dedupe hit
            # explains near-zero device counters in the tree)
            cost.profile.wave_size = getattr(fut, "wave_size", 1)
            cost.profile.dedupe_hit = bool(getattr(fut, "dedupe_hit",
                                                   False))
        return defs

    # ----------------------------------------------------------- dispatcher

    def _ensure_thread(self):
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="query-pipeline"
                )
                self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            wave = [item]
            self._gather(wave)
            executor = self._api.executor
            self.waves += 1
            if len(wave) > 1:
                self.coalesced += len(wave)
            # Submit the ENTIRE wave before completing any future: the
            # executor's micro-batcher flushes a pending group on its
            # first result(), so a request thread resuming early would
            # split the wave's shared dispatch.
            done = []
            # identical dedupe-eligible wavemates submit ONCE and share
            # the leader's Deferreds; the shared handles memoize their
            # resolution so the N-1 followers pay neither the dispatch
            # nor the readback (and the followers' responses reuse the
            # leader's pre-serialized result bytes — executor/result.py)
            leaders: dict = {}
            wave_size = len(wave)
            for index, q, kwargs, fut, key, ctx in wave:
                fut.wave_size = wave_size  # read by the request's span
                shared = leaders.get(key) if key is not None else None
                if shared is not None:
                    self.deduped += 1
                    fut.dedupe_hit = True
                    done.append((fut, shared))
                    continue
                try:
                    # submit under the REQUEST's captured context: spans
                    # and inspector updates started inside land in that
                    # request's trace, not on the dispatcher thread
                    defs = ctx.run(executor.submit, index, q, **kwargs)
                except BaseException as e:
                    fut.set_exception(e)
                    continue
                if key is not None:
                    # wrapped only when shareable: followers' resolves
                    # must be race-free against the leader's
                    defs = [_SharedDeferred(d) for d in defs]
                    leaders[key] = defs
                done.append((fut, defs))
            for fut, defs in done:
                fut.set_result(defs)

    def _gather(self, wave: list) -> None:
        """Grow a forming wave: greedy drain, then — only while arrivals
        are close together (concurrent load) — hold the wave open up to
        GATHER_WINDOW_S for stragglers.

        Why the window matters: under saturation each dispatch carries a
        fixed host+runtime cost, and a drain-only dispatcher outruns the
        arrival rate, so waves degenerate to ~1 request and throughput
        caps at 1/dispatch-cost no matter how many clients pile on
        (measured: 128 concurrent clients scored BELOW 64). Holding the
        wave open for ~an inter-arrival gap converts concurrency into
        batch size instead. The pressure gate keeps sparse traffic on
        the zero-wait path."""
        while True:
            # unbounded: already-queued requests are free to take, and a
            # mixed-shape backlog needs the whole wave in one submit to
            # fill per-shape micro-batch groups (capping here would
            # split shapes across waves and flush partial groups)
            try:
                wave.append(self._q.get_nowait())
            except queue.Empty:
                break
        if self._recent_gap >= self.PRESSURE_GAP_S:
            self._last_wave_size = len(wave)
            return
        # Latch breaker (ADVICE r5): a single fast closed-loop client
        # keeps _recent_gap ≈ window + service < PRESSURE_GAP_S, so the
        # gap signal alone holds the window open forever while every
        # wave dispatches at size 1 — the window buys nothing and costs
        # 2 ms per query. Require evidence of actual concurrency: either
        # this wave already drained >1 requests, or the previous wave
        # did. A real burst re-opens the window within one wave (the
        # backlog makes the greedy drain multi-request).
        if len(wave) == 1 and self._last_wave_size <= 1:
            self._last_wave_size = len(wave)
            return
        # WAITING past one full micro-batch buys nothing, so the window
        # phase caps at the live executor's batch limit (falls back to
        # the class constant when unwired, e.g. unit tests). The cap
        # counts UNIQUE submissions, not wave members: dedupe-eligible
        # wavemates carrying a key already in the wave share the
        # leader's submission and consume no micro-batch slot, so a
        # hot-query burst may ride one wave far past the batch limit —
        # under the multi-process serving tier this is where worker
        # waves group-commit into one owner dispatch.
        cap = getattr(getattr(self._api, "executor", None),
                      "microbatch_max", None) or self.GATHER_CAP

        def item_key(item):
            # run() enqueues (index, query, kwargs, fut, key, ctx);
            # gather-window unit tests enqueue bare sentinels — treat
            # anything else as keyless (always unique)
            return item[4] if isinstance(item, tuple) and len(item) >= 5 \
                else None

        seen_keys: set = set()
        unique = 0

        def note(item) -> None:
            nonlocal unique
            key = item_key(item)
            if key is None or key not in seen_keys:
                unique += 1
                if key is not None:
                    seen_keys.add(key)

        for item in wave:
            note(item)
        deadline = time.monotonic() + self.GATHER_WINDOW_S
        try:
            while unique < cap:
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                try:
                    item = self._q.get(timeout=left)
                except queue.Empty:
                    return
                wave.append(item)
                note(item)
        finally:
            self._last_wave_size = len(wave)
