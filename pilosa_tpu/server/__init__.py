"""Serving layer: transport-neutral API façade + HTTP handler + lifecycle.

Reference: api.go, http/handler.go, server.go (SURVEY.md §2 #18–20).
"""

from pilosa_tpu.server.api import API, ApiError
from pilosa_tpu.server.http import HTTPHandler, make_http_server
from pilosa_tpu.server.server import Server, ServerConfig
