"""HTTP handler: the reference's REST surface on the stdlib http server.

Reference: http/handler.go (SURVEY.md §2 #19). External routes:

  POST   /index/{index}/query                 PQL → {"results": [...]}
  POST   /index/{index}                       create index
  GET    /index/{index}                       index schema
  DELETE /index/{index}
  POST   /index/{index}/field/{field}         create field
  DELETE /index/{index}/field/{field}
  POST   /index/{i}/field/{f}/import          JSON bit batches
  POST   /index/{i}/field/{f}/import-value    JSON value batches
  POST   /index/{i}/field/{f}/import-roaring/{shard}  roaring bytes
  GET    /export?index=&field=                CSV
  GET    /schema | /status | /info | /version | /metrics
  GET    /internal/shards/max
  POST   /internal/cluster/message            (cluster control — M4+)
  GET    /internal/fragment/blocks|data       (anti-entropy / resize)

Responses are JSON (the reference also negotiates protobuf; JSON is the
wire format here — the serving tier is host-side control plane, never on
the TPU hot path).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu.server.api import API, ApiError
from pilosa_tpu.utils.cost import cost_enabled

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("POST", re.compile(r"^/index/([^/]+)/query$"), "post_query"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)/import$"), "post_import"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)/import-value$"), "post_import_value"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)/import-roaring/(\d+)$"), "post_import_roaring"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)$"), "post_field"),
    ("DELETE", re.compile(r"^/index/([^/]+)/field/([^/]+)$"), "delete_field"),
    ("POST", re.compile(r"^/index/([^/]+)$"), "post_index"),
    ("GET", re.compile(r"^/index/([^/]+)$"), "get_index"),
    ("DELETE", re.compile(r"^/index/([^/]+)$"), "delete_index"),
    ("GET", re.compile(r"^/schema$"), "get_schema"),
    ("GET", re.compile(r"^/status$"), "get_status"),
    ("GET", re.compile(r"^/info$"), "get_info"),
    ("GET", re.compile(r"^/version$"), "get_version"),
    ("GET", re.compile(r"^/export$"), "get_export"),
    ("GET", re.compile(r"^/metrics$"), "get_metrics"),
    ("POST", re.compile(r"^/recalculate-caches$"), "post_recalculate_caches"),
    ("POST", re.compile(r"^/internal/query-batch$"), "post_query_batch"),
    ("GET", re.compile(r"^/internal/shards/max$"), "get_shards_max"),
    ("GET", re.compile(r"^/internal/shards/list$"), "get_shards_list"),
    ("GET", re.compile(r"^/internal/sync/manifest$"), "get_sync_manifest"),
    ("POST", re.compile(r"^/internal/sync/blocks$"), "post_sync_blocks"),
    ("GET", re.compile(r"^/internal/wal/tail$"), "get_wal_tail"),
    ("POST", re.compile(r"^/internal/scrub$"), "post_scrub"),
    ("GET", re.compile(r"^/internal/fragment/blocks$"), "get_fragment_blocks"),
    ("GET", re.compile(r"^/internal/fragment/block/data$"), "get_fragment_block_data"),
    ("GET", re.compile(r"^/internal/fragment/data$"), "get_fragment_data"),
    ("GET", re.compile(r"^/internal/fragment/nodes$"), "get_fragment_nodes"),
    ("GET", re.compile(r"^/internal/fragments$"), "get_fragments_catalog"),
    ("POST", re.compile(r"^/internal/cluster/message$"), "post_cluster_message"),
    ("GET", re.compile(r"^/internal/attrs/blocks$"), "get_attr_blocks"),
    ("GET", re.compile(r"^/internal/attrs/block/data$"), "get_attr_block_data"),
    ("POST", re.compile(r"^/internal/translate/keys$"), "post_translate_keys"),
    ("GET", re.compile(r"^/internal/translate/data$"), "get_translate_data"),
    ("GET", re.compile(r"^/internal/schema$"), "get_schema"),
    ("GET", re.compile(r"^/debug/faults$"), "get_faults"),
    ("POST", re.compile(r"^/debug/faults$"), "post_faults"),
    ("DELETE", re.compile(r"^/debug/faults$"), "delete_faults"),
    ("GET", re.compile(r"^/debug/traces$"), "get_traces"),
    ("GET", re.compile(r"^/debug/tenants$"), "get_tenants"),
    ("GET", re.compile(r"^/debug/heatmap$"), "get_heatmap"),
    ("GET", re.compile(r"^/debug/rescache$"), "get_rescache"),
    ("GET", re.compile(r"^/debug/autopilot$"), "get_autopilot"),
    ("GET", re.compile(r"^/debug/elastic$"), "get_elastic"),
    ("POST", re.compile(r"^/cluster/drain/([^/]+)$"), "post_drain"),
    ("DELETE", re.compile(r"^/cluster/drain$"), "delete_drain"),
    ("GET", re.compile(r"^/cluster/drain$"), "get_drain"),
    ("GET", re.compile(r"^/debug/slo$"), "get_slo"),
    ("GET", re.compile(r"^/debug/workers$"), "get_workers"),
    ("GET", re.compile(r"^/debug/queries$"), "get_inflight_queries"),
    ("GET", re.compile(r"^/debug/queries/slow$"), "get_long_queries"),
    ("GET", re.compile(r"^/debug/long-queries$"), "get_long_queries"),
    ("POST", re.compile(r"^/debug/trace-device$"), "post_trace_device"),
    ("GET", re.compile(r"^/debug/vars$"), "get_debug_vars"),
    ("GET", re.compile(r"^/debug/pprof/?$"), "get_pprof"),
]


class HTTPHandler(BaseHTTPRequestHandler):
    api: API = None  # set by make_http_server
    protocol_version = "HTTP/1.1"
    # idle keep-alive reaper: a persistent connection that sends nothing
    # for this long is closed (handle_one_request catches the socket
    # timeout), so pooled-but-abandoned client connections cannot pin
    # handler threads forever
    timeout = 120
    # buffered response writes: status line + headers + body leave as
    # ONE syscall/packet per response (handle_one_request flushes after
    # each request) instead of a header write then a body write —
    # responses here are always full Content-Length'd bodies, never
    # streamed, so buffering costs nothing
    wbufsize = -1

    # quiet logging; the server wires its own logger
    def log_message(self, fmt, *args):
        pass

    def setup(self):
        super().setup()
        # connection-count oracle for keep-alive reuse: requests ≫
        # connections proves clients are riding persistent connections.
        # The socket is also registered so server_close can hard-close
        # established keep-alive connections — without that, a "closed"
        # node would keep serving old peers' pooled connections forever
        # (its handler threads outlive the listener), which is graceful
        # drain, not death.
        lock = getattr(self.server, "metrics_lock", None)
        if lock is not None:
            with lock:
                self.server.connections_opened += 1
                self.server.open_connections.add(self.connection)

    def finish(self):
        lock = getattr(self.server, "metrics_lock", None)
        if lock is not None:
            with lock:
                self.server.open_connections.discard(self.connection)
        super().finish()

    def _dispatch(self, method: str):
        self._body_read = False
        lock = getattr(self.server, "metrics_lock", None)
        if lock is not None:
            with lock:
                self.server.requests_served += 1
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            # _body/_drain_body only understand Content-Length; chunk
            # framing left in rfile would be parsed as the next request
            # line and poison every later exchange on this connection —
            # reject with 411 and close (RFC 7230 §3.3.3 option)
            self._body_read = True
            # Connection: close both tells the client AND (via
            # send_header's side effect) sets close_connection here
            self._json({"error": "chunked request bodies are not "
                                 "supported; send Content-Length"},
                       status=411, headers={"Connection": "close"})
            return
        parsed = urlparse(self.path)
        for m, pattern, handler in _ROUTES:
            if m != method:
                continue
            match = pattern.match(parsed.path)
            if match:
                try:
                    getattr(self, handler)(*match.groups(), query=parse_qs(parsed.query))
                except ApiError as e:
                    headers = None
                    retry_after = getattr(e, "retry_after", None)
                    if retry_after is not None:
                        # shed at admission: tell the client when to come
                        # back instead of letting it hammer a full queue
                        headers = {"Retry-After": str(max(1, int(retry_after)))}
                    self._drain_body()
                    self._json({"error": str(e)}, status=e.status,
                               headers=headers)
                except Exception as e:  # internal error → 500, not a crash
                    self._drain_body()
                    self._json({"error": f"internal: {e}"}, status=500)
                else:
                    # a handler that never read its body (GET with a
                    # stray body, early-return route) must not leave the
                    # bytes to corrupt the NEXT request on this
                    # keep-alive connection
                    self._drain_body()
                return
        self._drain_body()
        self._json({"error": "not found"}, status=404)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -------------------------------------------------------------- helpers

    def _body(self) -> bytes:
        self._body_read = True
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _drain_body(self) -> None:
        """Consume an unread request body so the error (or body-less)
        response leaves the connection aligned on the next request —
        leftover body bytes would be parsed as a request line and poison
        every later exchange on a keep-alive connection."""
        if getattr(self, "_body_read", True):
            return
        self._body_read = True
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self.close_connection = True
            return
        while length > 0:
            chunk = self.rfile.read(min(length, 1 << 16))
            if not chunk:
                break
            length -= len(chunk)

    def _json_body(self) -> dict:
        raw = self._body()
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ApiError(f"invalid JSON body: {e}") from e

    def _json(self, obj, status: int = 200, headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _qos_envelope(self, remote: bool = False):
        """Tenant + deadline from request headers (the QoS request
        envelope — docs/QOS.md). The deadline header carries remaining
        budget in ms; absent, the server default applies (0 = none) —
        but only to EDGE requests: a remote sub-query's budget belongs
        to its root, and minting a local default for it would let one
        peer's tighter config 504 (and so DEGRADE) healthy nodes."""
        from pilosa_tpu.qos import DEADLINE_HEADER, TENANT_HEADER, Deadline

        tenant = (self.headers.get(TENANT_HEADER) or "default").strip()
        raw = self.headers.get(DEADLINE_HEADER)
        if raw is not None:
            try:
                millis = int(raw)
                if millis <= 0:
                    raise ValueError
            except ValueError:
                raise ApiError(
                    f"invalid {DEADLINE_HEADER} header {raw!r}: must be a "
                    "positive integer of milliseconds"
                ) from None
            return tenant, Deadline.from_millis(millis)
        if not remote and self.api.default_deadline_s > 0:
            return tenant, Deadline.after(self.api.default_deadline_s)
        return tenant, None

    def _staleness_gate(self) -> None:
        """Stale-bounded reads on a CDC follower (docs/OPERATIONS.md
        Replication & CDC): parse ``X-Pilosa-Max-Staleness`` (the
        shared Go-duration grammar — utils/durations.py) and refuse
        503 + Retry-After when this node's replica lag exceeds the
        tighter of the header and the configured budget. A no-op on
        every node that isn't a follower — primaries serve their own
        writes and owe no staleness bound."""
        if self.api.follower is None:
            return
        from pilosa_tpu.qos import STALENESS_HEADER

        raw = self.headers.get(STALENESS_HEADER)
        budget = None
        if raw is not None:
            from pilosa_tpu.utils.durations import parse_duration

            try:
                budget = parse_duration(raw)
            except ValueError as e:
                raise ApiError(
                    f"invalid {STALENESS_HEADER} header {raw!r}: {e}"
                ) from e
        self.api.check_staleness(budget)

    def _note_egress(self, tenant: str, index: str, nbytes: int,
                     remote: bool) -> None:
        """Fold one edge query response's bytes into the tenant ledger
        (docs/OBSERVABILITY.md). Remote hops are exempt — they carry
        pieces of an edge request already accounted on the
        coordinator."""
        if not remote and cost_enabled():
            self.api.cost.add_egress(tenant, index, nbytes)

    def _note_ingest(self, index: str, rows: int, remote: bool) -> None:
        """Fold one edge import's row count into the tenant ledger.
        Tenant attribution via the QoS tenant header, like queries;
        routed internal slices are exempt (already accounted at the
        edge)."""
        from pilosa_tpu.qos import TENANT_HEADER

        if not remote and cost_enabled():
            tenant = (self.headers.get(TENANT_HEADER) or "default").strip()
            self.api.cost.add_ingest(tenant, index, rows)

    def _text(self, text: str, content_type: str = "text/plain") -> None:
        data = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, data: bytes, headers: dict | None = None) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # Payloads below this size skip the compression attempt: zlib headers
    # plus the CPU round trip cost more than the bytes saved.
    COMPRESS_MIN_BYTES = 256

    def _bytes_negotiated(self, data: bytes,
                          headers: dict | None = None) -> None:
        """Octet-stream body with optional zlib Content-Encoding,
        negotiated per request: compressed ONLY when the client
        advertised ``Accept-Encoding: deflate`` (the repair client's
        ``repair-compression`` knob controls whether it does) AND
        compression actually shrinks the payload — so plain clients,
        old-wire peers, and incompressible bodies all get identity
        bytes. Roaring fragment payloads compress dramatically (Chambi
        et al. 1402.6407), which is where resize transfer time lives.
        ``headers`` ride either branch (the CDC tail route's seq
        positions must survive the compression decision)."""
        accept = (self.headers.get("Accept-Encoding") or "").lower()
        if "deflate" in accept and len(data) >= self.COMPRESS_MIN_BYTES:
            import zlib

            compressed = zlib.compress(data, 6)
            if len(compressed) < len(data):
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Encoding", "deflate")
                self.send_header("Content-Length", str(len(compressed)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(compressed)
                return
        self._bytes(data, headers)

    def _raw(self, data: bytes, content_type: str = "application/json",
             status: int = 200) -> None:
        """Pre-serialized response body (serving fast lane): no dict
        building, no json.dumps — the bytes were encoded once upstream."""
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # --------------------------------------------------------------- routes

    def post_query(self, index, query=None):
        raw = self._body()
        content_type = self.headers.get("Content-Type", "")
        accept = self.headers.get("Accept", "")
        proto_in = "application/x-protobuf" in content_type
        proto_out = "application/x-protobuf" in accept
        want_profile = bool(
            query and query.get("profile", ["false"])[0] == "true"
        )
        if want_profile and proto_out:
            # the profile rides only the JSON envelope; silently paying
            # the profiling overhead and dropping the tree would send a
            # debugger down a false trail (checked before the wire-
            # availability 406 so the answer is deterministic)
            raise ApiError(
                "profile=true requires a JSON response (drop the "
                "application/x-protobuf Accept header)"
            )

        if proto_in or proto_out:
            from pilosa_tpu import wire

            if not wire.available():
                raise ApiError("protobuf wire format unavailable", 406)

        if proto_in:
            from pilosa_tpu.wire.serializer import decode_query_request

            pql, shards, remote, opts = decode_query_request(raw)
        else:
            pql = raw.decode()
            shards = None
            if query and "shards" in query:
                shards = [
                    _int_param(s, "shards") for s in query["shards"][0].split(",")
                ]
            remote = bool(query and query.get("remote", ["false"])[0] == "true")
            opts = {}
        # request-level result options also ride URL params for either
        # body encoding (reference handler query args)
        opts.update({
            k: True for k in ("columnAttrs", "excludeColumns",
                              "excludeRowAttrs")
            if query and query.get(k, ["false"])[0] == "true"
        })

        tenant, deadline = self._qos_envelope(remote=remote)
        self._staleness_gate()
        # PQL PROFILE (docs/OBSERVABILITY.md): ?profile=true returns a
        # per-AST-node execution profile beside the results; remote hops
        # carry the flag so the coordinator's envelope holds one
        # stitched per-node tree (the trace-graft pattern below)
        profile_out: list | None = [] if want_profile else None

        # Tracing roots (utils/tracing.py): an EDGE request makes the
        # sampling decision here (one tree per request, or a suppressed
        # context so inner sites can't root their own); a REMOTE
        # sub-query carrying X-Pilosa-Trace joins the coordinator's
        # trace and returns its finished span subtree in the response so
        # the caller renders ONE cluster-wide tree.
        from pilosa_tpu.utils.tracing import TRACE_HEADER, global_tracer

        tracer = global_tracer()
        trace_hdr = self.headers.get(TRACE_HEADER) if remote else None
        if remote:
            root_cm = tracer.remote_root(
                trace_hdr, "rpc.query", node=self.api.node_id(),
                index=index,
            )
        else:
            root_cm = tracer.request_root("http.query", index=index,
                                          tenant=tenant)
        with root_cm as root:
            if not proto_out:
                if self.api.serve_fastlane:
                    # fast lane: the response envelope arrives
                    # pre-serialized (hot shapes encode straight to
                    # bytes; identical deduped wavemates share one
                    # encoding — executor/result.py)
                    payload = self.api.query_json_bytes(
                        index, pql, shards=shards, remote=remote,
                        opts=opts, tenant=tenant, deadline=deadline,
                        profile_out=profile_out)
                    if root is not None and trace_hdr:
                        # splice the finished subtree into the closing
                        # brace of the pre-serialized envelope — sampled
                        # remote hops are rare (rate-bounded), so the
                        # fast lane's zero-build path is untouched
                        root.finish()
                        payload = (payload[:-1] + b',"trace":'
                                   + json.dumps(
                                       root.to_json(),
                                       separators=(",", ":")).encode()
                                   + b"}")
                    if profile_out:
                        # same splice as the trace graft: profiled
                        # requests are rare debugging traffic, the
                        # zero-build fast lane stays untouched
                        payload = (payload[:-1] + b',"profile":'
                                   + json.dumps(
                                       profile_out[0],
                                       separators=(",", ":")).encode()
                                   + b"}")
                    self._note_egress(tenant, index, len(payload), remote)
                    self._raw(payload)
                else:  # r5-shaped legacy path (serve_fastlane = False)
                    out = self.api.query(index, pql, shards=shards,
                                         remote=remote, opts=opts,
                                         tenant=tenant, deadline=deadline,
                                         profile_out=profile_out)
                    if root is not None and trace_hdr:
                        root.finish()
                        out["trace"] = root.to_json()
                    if profile_out:
                        out["profile"] = profile_out[0]
                    # encode here (not via _json) so the legacy path
                    # bills egress like the fast lane does
                    data = json.dumps(out).encode()
                    self._note_egress(tenant, index, len(data), remote)
                    self._raw(data)
                return
            from pilosa_tpu.wire.serializer import (
                encode_error,
                encode_results,
            )

            retry_after = None
            try:
                results = self.api.query_raw(index, pql, shards=shards,
                                             remote=remote, opts=opts,
                                             tenant=tenant,
                                             deadline=deadline,
                                             profile_out=profile_out)
                trace_json = None
                if root is not None and trace_hdr:
                    root.finish()
                    trace_json = root.to_json()
                payload = encode_results(results, trace=trace_json)
                status = 200
            except ApiError as e:
                payload = encode_error(str(e))
                status = e.status
                retry_after = getattr(e, "retry_after", None)
            self._note_egress(tenant, index, len(payload), remote)
            self.send_response(status)
            self.send_header("Content-Type", "application/x-protobuf")
            self.send_header("Content-Length", str(len(payload)))
            if retry_after is not None:
                # admission shed: same backoff hint the JSON route sends
                self.send_header("Retry-After",
                                 str(max(1, int(retry_after))))
            self.end_headers()
            self.wfile.write(payload)

    def post_query_batch(self, query=None):
        """Cluster-wide wave batching receiver: several remote
        sub-queries from one peer, executed with every item submitted
        before any resolves (shared micro-batched dispatches), answered
        positionally. Per-item errors ride inside the 200 envelope —
        item isolation, not request failure."""
        raw = self._body()
        content_type = self.headers.get("Content-Type", "")
        accept = self.headers.get("Accept", "")
        if ("application/x-protobuf" in content_type
                or "application/x-protobuf" in accept):
            from pilosa_tpu import wire

            if not wire.available():
                raise ApiError("protobuf wire format unavailable", 406)
            from pilosa_tpu.wire.serializer import (
                decode_batch_request,
                encode_batch_responses,
            )

            outcomes = self.api.query_batch(decode_batch_request(raw))
            self._raw(encode_batch_responses(outcomes),
                      "application/x-protobuf")
            return
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            raise ApiError(f"invalid JSON body: {e}") from e
        items = [
            (q.get("index", ""), q.get("query", ""),
             [int(s) for s in (q.get("shards") or [])],
             q.get("trace") or None)
            for q in body.get("queries", [])
        ]
        from pilosa_tpu.executor.result import results_json_bytes

        parts = []
        for outcome in self.api.query_batch(items):
            if outcome[0] == "ok":
                # identical bytes to a per-query /index/{i}/query
                # response — the batch route must be a pure transport
                # optimization (gated by `make serving-smoke`); a traced
                # item (rare, sample-rate-bounded) splices its span
                # subtree into the envelope like the per-query route
                part = results_json_bytes(outcome[1])
                if len(outcome) > 2 and outcome[2] is not None:
                    part = (part[:-1] + b',"trace":'
                            + json.dumps(outcome[2],
                                         separators=(",", ":")).encode()
                            + b"}")
                parts.append(part)
            else:
                parts.append(json.dumps(
                    {"error": outcome[1], "status": outcome[2]},
                    separators=(",", ":"),
                ).encode())
        self._raw(b'{"responses":[' + b",".join(parts) + b"]}")

    def post_index(self, index, query=None):
        body = self._json_body()
        opts = body.get("options", {})
        self._json(
            self.api.create_index(
                index,
                keys=opts.get("keys", False),
                track_existence=opts.get("trackExistence", True),
            )
        )

    def get_index(self, index, query=None):
        idx = self.api._index(index)
        self._json(idx.schema())

    def delete_index(self, index, query=None):
        self.api.delete_index(index)
        self._json({})

    def post_field(self, index, field, query=None):
        body = self._json_body()
        self._json(self.api.create_field(index, field, body.get("options", {})))

    def delete_field(self, index, field, query=None):
        self.api.delete_field(index, field)
        self._json({})

    def post_import(self, index, field, query=None):
        remote = bool(query and query.get("remote", ["false"])[0] == "true")
        if "application/x-protobuf" in self.headers.get("Content-Type", ""):
            from pilosa_tpu import wire

            if not wire.available():
                raise ApiError("protobuf wire format unavailable", 406)
            from pilosa_tpu.wire.serializer import decode_import_request

            rows, columns, timestamps, clear = decode_import_request(self._body())
        else:
            body = self._json_body()
            rows, columns = body.get("rows", []), body.get("columns", [])
            timestamps = body.get("timestamps")
            clear = bool(body.get("clear", False))
        self._check_import_size(len(columns), remote)
        changed = self.api.import_bits(
            index, field, rows, columns, timestamps=timestamps, clear=clear,
            remote=remote,
        )
        self._note_ingest(index, len(columns), remote)
        self._json({"changed": changed})

    def post_import_value(self, index, field, query=None):
        remote = bool(query and query.get("remote", ["false"])[0] == "true")
        if "application/x-protobuf" in self.headers.get("Content-Type", ""):
            from pilosa_tpu import wire

            if not wire.available():
                raise ApiError("protobuf wire format unavailable", 406)
            from pilosa_tpu.wire.serializer import decode_import_value_request

            columns, values, clear = decode_import_value_request(self._body())
        else:
            body = self._json_body()
            columns, values = body.get("columns", []), body.get("values", [])
            clear = bool(body.get("clear", False))
        self._check_import_size(len(columns), remote)
        changed = self.api.import_values(
            index, field, columns, values, clear=clear, remote=remote,
        )
        self._note_ingest(index, len(columns), remote)
        self._json({"changed": changed})

    def _check_import_size(self, n: int, remote: bool) -> None:
        """Apply max-writes-per-request to EDGE import bodies (the same
        knob the query path enforces — a 100k-row import is no lighter
        than 100k Set() calls). Remote hops are exempt: they carry
        slices of an already-admitted edge batch, and a routed slice
        must never bounce off a peer with a tighter config. 413 so bulk
        clients (CLI --batch-size) can split-and-retry distinguishably
        from validation 400s."""
        limit = self.api.max_writes_per_request
        if not remote and 0 < limit < n:
            raise ApiError(
                f"import batch of {n} rows exceeds max-writes-per-request "
                f"{limit}; split the batch (the CLI clamps --batch-size "
                "to this server's limit automatically)", 413,
            )

    def post_import_roaring(self, index, field, shard, query=None):
        remote = bool(query and query.get("remote", ["false"])[0] == "true")
        submitted: list = []
        changed = self.api.import_roaring(index, field, int(shard),
                                          self._body(), remote=remote,
                                          submitted_out=submitted)
        # bill bits SUBMITTED (like the row/value routes) — billing
        # bits-changed would make a tenant's ledger depend on which
        # wire format its loader picked, not on the data it pushed
        self._note_ingest(index, submitted[0] if submitted else changed,
                          remote)
        self._json({"changed": changed})

    def get_schema(self, query=None):
        self._json(self.api.schema())

    def get_status(self, query=None):
        self._json(self.api.status())

    def get_info(self, query=None):
        self._json(self.api.info())

    def get_version(self, query=None):
        self._json(self.api.version())

    def post_scrub(self, query=None):
        """Trigger one integrity scrub pass (CLI ``check --host``,
        operators mid-incident): verify owned fragments' disk bytes,
        quarantine + read-repair any rot, return the pass record."""
        self._body()  # drain for keep-alive alignment
        self._json(self.api.scrub_now())

    def post_recalculate_caches(self, query=None):
        """Reference parity: authoritative per-node TopN cache recount;
        204 No Content on success, as upstream."""
        self._body()  # drain: unread bytes would corrupt keep-alive reuse
        self.api.recalculate_caches()
        # RFC 7230 §3.3.2: no Content-Length on a 204
        self.send_response(204)
        self.end_headers()

    def get_metrics(self, query=None):
        from pilosa_tpu.storage.residency import global_row_cache
        from pilosa_tpu.utils.stats import global_stats, prometheus_block

        stats = global_stats()
        seen: set = set()  # page-wide family-metadata dedupe
        text = stats.prometheus_text(seen)
        prefix = getattr(stats, "prefix", "pilosa_tpu")
        text += global_row_cache().prometheus_lines(prefix, seen=seen)
        # wave coalescing health: queries/waves ratio is the batch
        # factor operators size concurrency against (OPERATIONS.md);
        # exported as 0 from scrape one so rate() windows never see the
        # series appear mid-flight. Every block below renders through
        # prometheus_block, which leads each family with # HELP/# TYPE
        # (docs/OBSERVABILITY.md — a stock Prometheus scrape must ingest
        # the whole page).
        pm = self.api.pipeline_metrics()
        text += prometheus_block(
            {"waves_total": pm["waves"],
             "coalesced_requests_total": pm["coalesced"],
             "deduped_requests_total": pm["deduped"]},
            prefix, "serving", seen=seen,
        )
        # serving fast lane (connection pool, remote wave batching, HTTP
        # keep-alive oracle): all series present from scrape one, zeros
        # included, like the qos block below
        fastlane = self.api.fastlane_metrics()
        lock = getattr(self.server, "metrics_lock", None)
        if lock is not None:
            with lock:
                fastlane["http_connections_total"] = \
                    self.server.connections_opened
                fastlane["http_requests_total"] = self.server.requests_served
        text += prometheus_block(fastlane, prefix, "serving",
                                  seen=seen)
        # multi-process serving tier (docs/OPERATIONS.md deployment
        # shapes): worker count, ring depth/backpressure, owner batch
        # sizes — zeros in single-process mode, from scrape one
        text += prometheus_block(self.api.mp_metrics(), prefix,
                                 seen=seen)
        # skewed-traffic actuators (docs/OPERATIONS.md skewed traffic):
        # the write-invalidated result cache and the heat-driven
        # residency tiering pass — zeros while disabled, from scrape one
        text += prometheus_block(self.api.rescache_metrics(), prefix,
                                 seen=seen)
        text += prometheus_block(self.api.tiering_metrics(), prefix,
                                 seen=seen)
        # autopilot placement plane (docs/OPERATIONS.md autopilot):
        # planner passes/plans/moves plus the placement-override gauges —
        # the gauges stay live even with the planner off, because this
        # node still adopts overrides minted by the coordinator
        text += prometheus_block(self.api.autopilot_metrics(), prefix,
                                 seen=seen)
        # elastic membership plane (docs/OPERATIONS.md elastic
        # operations): drain state-machine counters plus join warm-up
        # heat-ordering/byte-verify counters — zeros from scrape one;
        # the drain gauges stay live on every node via record gossip
        text += prometheus_block(self.api.elastic_metrics(), prefix,
                                 seen=seen)
        # write-path durability (group-commit WAL): zeros from scrape
        # one, same rate()-window reasoning as the blocks around it
        text += prometheus_block(self.api.durability_metrics(), prefix,
                                 "wal", seen=seen)
        # CDC plane (docs/OPERATIONS.md Replication & CDC): tailer
        # liveness + per-peer lag, invalidation/resync counters,
        # follower staleness and applied ops — producer-side tail
        # counters ride the wal block above; zeros while CDC is off
        text += prometheus_block(self.api.cdc_metrics(), prefix,
                                 seen=seen)
        # storage-integrity plane (docs/OPERATIONS.md integrity
        # runbook): degraded latch, verified-load/quarantine counters,
        # scrubber progress — zeros from scrape one like the rest
        text += prometheus_block(self.api.integrity_metrics(), prefix,
                                 seen=seen)
        # host-path roaring kernels (docs/OPERATIONS.md host-path
        # kernels): batched decode/set-op call counts and materialized
        # id volume — zeros from scrape one; a flat kernel_calls rate
        # under load means traffic is all residency hits
        from pilosa_tpu.roaring.kernels import global_kernel_stats

        text += prometheus_block(global_kernel_stats().metrics(), prefix,
                                 seen=seen)
        # write-path fast lane (docs/OPERATIONS.md): whole-batch merge
        # kernel counters + range-aware write-routing counters — zeros
        # from scrape one; loop_fallbacks rising under bulk load means
        # batches are arriving below the kernel cutover size
        from pilosa_tpu.parallel.cluster import global_route_stats
        from pilosa_tpu.roaring.merge_kernels import global_merge_stats

        text += prometheus_block(global_merge_stats().metrics(), prefix,
                                 seen=seen)
        text += prometheus_block(global_route_stats().metrics(), prefix,
                                 seen=seen)
        # multi-chip reduction plane (docs/OPERATIONS.md multi-chip
        # mesh): per-dispatch reduction-lane bytes, dense-equivalent vs
        # actual encoded inter-group traffic plus roaring row gathers —
        # zeros on flat 1-D meshes, where the plane is pass-through
        from pilosa_tpu.parallel.reduction import global_reduce_stats

        text += prometheus_block(global_reduce_stats().snapshot(), prefix,
                                 "dist_reduce", seen=seen)
        # serving-QoS series (admission/deadline/hedge/breaker): emitted
        # from scrape one, zeros included, for the same rate()-window
        # reason as the wave counters above
        text += prometheus_block(self.api.qos.metrics(), prefix, "qos",
                                  seen=seen)
        # observability plane: trace sampling counters, in-flight
        # inspector gauges, and the slow-query ring's counter
        text += prometheus_block(self.api.observability_metrics(), prefix,
                                  seen=seen)
        # partition-tolerance plane (docs/OPERATIONS.md failure model):
        # epoch, quorum/degraded gauges, heartbeat + fencing counters
        text += prometheus_block(self.api.cluster_metrics(), prefix,
                                  seen=seen)
        # query cost plane (docs/OBSERVABILITY.md): per-tenant usage
        # accounting, per-shard heat, and SLO burn-rate gauges — tagged
        # series are cardinality-capped (full tables live on their
        # /debug endpoints)
        from pilosa_tpu.storage.heat import global_heat

        text += self.api.cost.prometheus_lines(prefix, seen=seen)
        text += global_heat().prometheus_lines(prefix, seen=seen)
        text += self.api.slo.prometheus_lines(prefix, seen=seen)
        self._text(text, "text/plain; version=0.0.4")

    def get_faults(self, query=None):
        """Installed fault-injection rules + hit counters
        (testing/faults.py — docs/OPERATIONS.md failure model)."""
        from pilosa_tpu.testing import faults

        plane = faults.active()
        if plane is None:
            self._json({"enabled": False, "rules": []})
            return
        self._json({"enabled": True, **plane.snapshot()})

    def post_faults(self, query=None):
        """Program the fault plane over HTTP: ``{"rules": [{action, src,
        dst, route, delayMs, status, count}, ...]}`` installs rules
        (creating the plane on first use), ``{"heal": true}`` removes
        every drop rule, ``{"clear": true}`` removes all rules. The
        serving node registers its own name→endpoint mapping when the
        plane appears, so rules can target node names."""
        from pilosa_tpu.testing import faults

        body = self._json_body()
        plane = faults.active()
        if plane is None:
            plane = faults.install()
        if self.api.cluster is not None:
            # register EVERY known member's name→endpoint (from the
            # advertised URIs peers actually dial): rules written
            # against node names must match traffic toward REMOTE
            # nodes too, not only the serving node — a dst="n1" rule
            # posted to n0 is otherwise a silent no-op
            for node in self.api.cluster.sorted_nodes():
                plane.name_endpoint(node.id,
                                    node.uri.split("://", 1)[-1])
        if body.get("clear"):
            plane.clear_rules()
        if body.get("heal"):
            plane.heal()
        installed = []
        for spec in body.get("rules", []):
            try:
                rule = plane.add(
                    spec.get("action", ""),
                    src=spec.get("src", "*"),
                    dst=spec.get("dst", "*"),
                    route=spec.get("route", "*"),
                    delay_ms=float(spec.get("delayMs", 0.0)),
                    status=int(spec.get("status", 503)),
                    count=(int(spec["count"])
                           if spec.get("count") is not None else None),
                )
            except (ValueError, TypeError) as e:
                raise ApiError(f"invalid fault rule {spec!r}: {e}") from e
            installed.append(rule.id)
        self._json({"installed": installed, **plane.snapshot()})

    def delete_faults(self, query=None):
        """Clear every rule and uninstall the plane — the wire is
        guaranteed clean afterwards (the zero-overhead off state)."""
        from pilosa_tpu.testing import faults

        faults.clear()
        self._json({"enabled": False})

    def get_traces(self, query=None):
        from pilosa_tpu.utils.tracing import global_tracer

        tracer = global_tracer()
        self._json({"enabled": tracer.enabled,
                    "sampleRate": tracer.sample_rate,
                    "traces": tracer.recent()})

    def get_tenants(self, query=None):
        """Per-(tenant, index) usage accounting + top-K offender view
        (``?k=10&by=device_ms`` — docs/OBSERVABILITY.md)."""
        k = _int_param((query.get("k") or ["10"])[0], "k") if query else 10
        if k <= 0:
            # a negative k flows into a Python slice and would return
            # the table MINUS its top offenders — the inverse view
            raise ApiError(f"k must be positive, got {k}")
        by = (query.get("by") or ["device_ms"])[0] if query else "device_ms"
        try:
            self._json(self.api.tenants_json(k=k, by=by))
        except ValueError as e:
            raise ApiError(str(e)) from e

    def get_heatmap(self, query=None):
        """Decayed per-(index, field, shard) access/write heat with the
        HBM-residency overlay (``?k=100`` caps rows) — the promote/
        demote signal for residency tiering (docs/OBSERVABILITY.md).

        ``?tier=true`` adds the tiering manager's world view beside the
        raw heat: each row gains its current tier (resident /
        compressed / host / cold), per-tier bytes, and the last pass's
        decision (promoted / demoted / hold / ...) — so an operator can
        see WHY a shard was demoted, not just that it is cold."""
        from pilosa_tpu.storage.heat import global_heat

        k = _int_param((query.get("k") or ["100"])[0], "k") if query else 100
        if k < 0:
            raise ApiError(f"k must be non-negative, got {k}")
        # k=0 = the FULL table (snapshot's own convention): the autopilot
        # coordinator's peer fetch (client.heatmap) needs every row — a
        # capped view would hide heat and silently blank the plan
        snap = global_heat().snapshot(k=k)
        if query and query.get("tier", ["false"])[0] == "true":
            from pilosa_tpu.storage.residency import global_row_cache

            per_frag, per_stack = global_row_cache().tier_overlay()
            tierer = self.api.tierer
            decisions = (tierer.last_decisions()
                         if tierer is not None else {})

            def label(tiers):
                if tiers["dense"] + tiers["compressed"] > 0:
                    return "resident" if tiers["dense"] else "compressed"
                return "host"

            for r in snap["shards"]:
                fkey = (r.get("scope", ""), r["index"], r["field"],
                        r["shard"])
                tiers = per_frag.get(fkey)
                stiers = per_stack.get(fkey[:3])
                if tiers is not None:
                    r["tier"] = label(tiers)
                    r["tierBytes"] = tiers
                elif stiers is not None:
                    # stacked leaves tier at field granularity: every
                    # shard of the field shows the leaf's tier
                    r["tier"] = label(stiers)
                    r["stackTierBytes"] = stiers
                else:
                    r["tier"] = "cold"
                d = decisions.get(fkey, decisions.get(fkey[:3]))
                if d is not None:
                    r["tierDecision"] = d
            snap["tiering"] = (tierer.to_json() if tierer is not None
                               else {"enabled": False})
        self._json(snap)

    def get_rescache(self, query=None):
        """Result-cache inspector (``?k=100`` caps entries): the entry
        table hottest-first with per-entry decayed score, hits, bytes,
        and dependency fields, plus totals — docs/OPERATIONS.md skewed-
        traffic runbook, step one for a hot-tenant p99 regression."""
        k = _int_param((query.get("k") or ["100"])[0], "k") if query else 100
        if k <= 0:
            raise ApiError(f"k must be positive, got {k}")
        self._json(self.api.rescache_json(k=k))

    def get_autopilot(self, query=None):
        """Autopilot inspector (docs/OPERATIONS.md autopilot runbook):
        planner config + pass counters, the live placement-override
        table, and the recent decision log — or just the adopted table
        when the planner is off on this node (kill switch gates the
        ticker, not table adoption)."""
        autopilot = self.api.autopilot
        if autopilot is not None:
            self._json(autopilot.to_json())
            return
        placement = getattr(self.api.cluster, "placement", None)
        self._json({
            "enabled": False,
            "placement": (placement.to_json() if placement is not None
                          else {"epoch": 0, "overrides": []}),
        })

    def get_elastic(self, query=None):
        """Elastic-plane inspector (docs/OPERATIONS.md elastic
        operations): the drain state machine record, join warm-up
        counters, and the range-keyed placement table — readable on
        every node because the drain record gossips with the epoch."""
        self._json(self.api.elastic_json())

    def post_drain(self, node, query=None):
        """Start a coordinator-driven graceful drain of ``node``:
        mints an epoch, moves every shard group the target owns, hands
        off its CDC cursors, then removes it from the ring."""
        self._body()  # drain unread bytes: keep-alive reuse
        self._json(self.api.drain_start(node))

    def delete_drain(self, query=None):
        """Abort the in-flight drain (coordinator only): stamps the
        record aborted so the worker stops at its next state check."""
        self._body()
        self._json(self.api.drain_abort())

    def get_drain(self, query=None):
        """Drain state machine record plus active/draining flags."""
        self._json(self.api.drain_status())

    def get_slo(self, query=None):
        """Declared objectives with per-window burn rates and breach
        flags (docs/OBSERVABILITY.md)."""
        self._json(self.api.slo.to_json())

    def get_workers(self, query=None):
        """Multi-process serving worker table (docs/OPERATIONS.md
        deployment shapes): one row per SO_REUSEPORT worker with
        generation, pid, liveness, ring depth, and the worker-reported
        ring round-trip quantiles."""
        self._json(self.api.workers_json())

    def get_inflight_queries(self, query=None):
        """Live queries on this node (upstream's long-running-query
        view): trace id, PQL, index, age, current stage, shards
        outstanding — see docs/OBSERVABILITY.md."""
        from pilosa_tpu.utils.tracing import global_query_tracker

        tracker = global_query_tracker()
        self._json({"queries": tracker.snapshot(),
                    "trackedTotal": tracker.started_total})

    def get_long_queries(self, query=None):
        self._json({"threshold": self.api.long_query_time,
                    "total": self.api.slow_queries_total,
                    "queries": list(self.api.long_queries)})

    def post_trace_device(self, query=None):
        """Live JAX profiler capture around real traffic:
        ``POST /debug/trace-device?secs=N`` writes an xprof/tensorboard
        trace into the configured log dir (trace-log-dir knob)."""
        self._body()  # drain: unread bytes would corrupt keep-alive reuse
        raw = (query.get("secs") or ["1"])[0] if query else "1"
        try:
            secs = float(raw)
        except ValueError as e:
            raise ApiError(f"invalid secs parameter {raw!r}") from e
        self._json(self.api.start_device_trace(secs))

    def get_debug_vars(self, query=None):
        from pilosa_tpu.storage.residency import global_row_cache
        from pilosa_tpu.utils.stats import global_stats

        snap = global_stats().snapshot()
        snap["residency"] = global_row_cache().metrics()
        snap["serving_pipeline"] = self.api.pipeline_metrics()
        snap["qos"] = self.api.qos.metrics()
        fastlane = self.api.fastlane_metrics()
        lock = getattr(self.server, "metrics_lock", None)
        if lock is not None:
            with lock:
                fastlane["http_connections_total"] = \
                    self.server.connections_opened
                fastlane["http_requests_total"] = self.server.requests_served
        snap["serving_fastlane"] = fastlane
        snap["serving_mp"] = self.api.mp_metrics()
        snap["result_cache"] = self.api.rescache_metrics()
        snap["residency_tiering"] = self.api.tiering_metrics()
        snap["autopilot"] = self.api.autopilot_metrics()
        snap["elastic"] = self.api.elastic_metrics()
        snap["durability"] = self.api.durability_metrics()
        snap["cdc"] = self.api.cdc_metrics()
        snap["integrity"] = self.api.integrity_metrics()
        snap["observability"] = self.api.observability_metrics()
        from pilosa_tpu.parallel.reduction import global_reduce_stats

        snap["dist_reduce"] = global_reduce_stats().snapshot()
        from pilosa_tpu.storage.heat import global_heat

        snap["tenants"] = self.api.cost.metrics()
        snap["heat"] = global_heat().metrics()
        snap["slo"] = self.api.slo.metrics()
        snap["cluster"] = self.api.cluster_metrics()
        self._json(snap)

    def get_pprof(self, query=None):
        """Thread stack dump (the /debug/pprof role for a python server)."""
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append(f"--- thread {names.get(ident, ident)} ---")
            out.extend(line.rstrip() for line in traceback.format_stack(frame))
        self._text("\n".join(out))

    def get_export(self, query=None):
        index = (query.get("index") or [""])[0]
        field = (query.get("field") or [""])[0]
        if not index or not field:
            raise ApiError("export requires index= and field=")
        self._text(self.api.export_csv(index, field), "text/csv")

    def get_shards_max(self, query=None):
        self._json(self.api.max_shards())

    def get_fragment_blocks(self, query=None):
        index = (query.get("index") or [""])[0]
        field = (query.get("field") or [""])[0]
        view = (query.get("view") or ["standard"])[0]
        shard = _int_param((query.get("shard") or ["0"])[0], "shard")
        idx = self.api._index(index)
        fld = self.api._field(idx, field)
        v = fld.view(view)
        frag = v.fragment(shard) if v else None
        blocks = frag.blocks() if frag else []
        self._json({"blocks": [{"block": b, "checksum": c} for b, c in blocks]})

    def get_fragment_nodes(self, query=None):
        """Which nodes own a shard (reference /internal/fragment/nodes —
        clients use it to route imports/queries directly to owners)."""
        index = (query.get("index") or [""])[0]
        shard_param = (query.get("shard") or [None])[0]
        if shard_param is None:
            raise ApiError("shard param required", 400)
        col_param = (query.get("col") or [None])[0]
        self._json(self.api.shard_nodes(
            index, _int_param(shard_param, "shard"),
            col=(_int_param(col_param, "col")
                 if col_param is not None else None)))

    def get_fragment_data(self, query=None):
        index = (query.get("index") or [""])[0]
        field = (query.get("field") or [""])[0]
        view = (query.get("view") or ["standard"])[0]
        shard = _int_param((query.get("shard") or ["0"])[0], "shard")
        idx = self.api._index(index)
        fld = self.api._field(idx, field)
        v = fld.view(view)
        frag = v.fragment(shard) if v else None
        data = frag.serialize_snapshot() if frag else b""
        # whole-fragment resize payloads honor Accept-Encoding: deflate
        # (the repair client's repair-compression knob)
        self._bytes_negotiated(data)

    def get_sync_manifest(self, query=None):
        """Batched anti-entropy manifest: every (field, view, shard) →
        checksum-block list of one index in ONE response, so a repair
        pass diffs the whole index against this node in one RTT instead
        of one /internal/fragment/blocks GET per fragment. Protobuf by
        Accept negotiation, JSON fallback (the 406 dance the query path
        uses)."""
        from pilosa_tpu.storage.fragment import build_index_manifest
        from pilosa_tpu.utils.stats import global_stats
        from pilosa_tpu.utils.tracing import TRACE_HEADER, global_tracer

        index = (query.get("index") or [""])[0]
        # a traced repair pass stitches the serving-side cost into the
        # coordinator's tree via this node's local /debug/traces (the
        # subtree stays here — manifest responses are binary/protobuf)
        trace_cm = global_tracer().remote_root(
            self.headers.get(TRACE_HEADER), "rpc.sync-manifest",
            node=self.api.node_id(), index=index,
        )
        with trace_cm:
            # An unknown index answers an EMPTY manifest, not 404:
            # sync-wise this node simply holds nothing for it (a schema
            # broadcast may not have landed yet), and a 404 here would be
            # misread by peers as "route missing" — permanently demoting
            # this node to the per-fragment legacy path. The legacy
            # catalog walk treated the same condition as "no fragments"
            # too (ClientError → []).
            idx = self.api.holder.index(index)
            entries = build_index_manifest(idx) if idx is not None else []
            global_stats().count("sync_manifest_served", 1)
            if "application/x-protobuf" in (self.headers.get("Accept")
                                            or ""):
                from pilosa_tpu import wire

                if not wire.available():
                    raise ApiError("protobuf wire format unavailable", 406)
                from pilosa_tpu.wire.serializer import encode_sync_manifest

                self._raw(encode_sync_manifest(entries),
                          "application/x-protobuf")
                return
            self._json({"fragments": [
                {"field": f, "view": v, "shard": s,
                 "blocks": [{"block": b, "checksum": c}
                            for b, c in blocks]}
                for f, v, s, blocks in entries
            ]})

    def post_sync_blocks(self, query=None):
        """Multi-block delta fetch: the body lists every wanted checksum
        block per fragment (protobuf SyncBlocksRequest or JSON); the
        response streams the blocks back as length-prefixed roaring
        payloads in request order — one POST replaces one
        /internal/fragment/block/data GET per differing block. The data
        plane stays raw roaring bytes whichever control encoding was
        negotiated; Accept-Encoding: deflate compresses the framed
        stream."""
        from pilosa_tpu.roaring import RoaringBitmap
        from pilosa_tpu.roaring.format import serialize
        from pilosa_tpu.utils.stats import global_stats
        from pilosa_tpu.utils.tracing import TRACE_HEADER, global_tracer
        from pilosa_tpu.wire.serializer import encode_block_frames

        trace_cm = global_tracer().remote_root(
            self.headers.get(TRACE_HEADER), "rpc.sync-blocks",
            node=self.api.node_id(),
        )
        raw = self._body()
        if "application/x-protobuf" in (
                self.headers.get("Content-Type") or ""):
            from pilosa_tpu import wire

            if not wire.available():
                raise ApiError("protobuf wire format unavailable", 406)
            from pilosa_tpu.wire.serializer import (
                decode_sync_blocks_request,
            )

            index, fragments = decode_sync_blocks_request(raw)
        else:
            try:
                body = json.loads(raw or b"{}")
            except json.JSONDecodeError as e:
                raise ApiError(f"invalid JSON body: {e}") from e
            index = body.get("index", "")
            fragments = [
                (e.get("field", ""), e.get("view", "standard"),
                 _int_param(str(e.get("shard", 0)), "shard"),
                 [_int_param(str(b), "block")
                  for b in e.get("blocks", [])])
                for e in body.get("fragments", [])
            ]
        # unknown index/field answer empty bitmaps, not 404, for the
        # same reason as the manifest route: a domain 404 would be
        # misread as "route missing" and demote the peer to the legacy
        # path for the process lifetime — and an empty payload is the
        # correct sync answer for data this node doesn't hold
        with trace_cm:
            idx = self.api.holder.index(index)
            payloads = []
            for fname, vname, shard, blocks in fragments:
                fld = idx.field(fname) if idx is not None else None
                v = fld.view(vname) if fld is not None else None
                frag = v.fragment(shard) if v else None
                if frag is None:
                    payloads.extend(
                        serialize(RoaringBitmap.from_ids([]))
                        for _ in blocks)
                    continue
                # one flatten + one id kernel + one boundary search for
                # ALL requested blocks (fragment.blocks_ids) — the old
                # loop re-materialized the whole fragment per block
                by_block = frag.blocks_ids(blocks)
                payloads.extend(
                    serialize(RoaringBitmap.from_ids(by_block[int(b)]))
                    for b in blocks)
            global_stats().count("sync_delta_blocks_served", len(payloads))
            self._bytes_negotiated(encode_block_frames(payloads))

    def get_wal_tail(self, query=None):
        """Resumable CDC tail over the committed WAL (docs/OPERATIONS.md
        Replication & CDC): ``?since=N`` streams seq-framed WAL records
        with seq > N in commit order (cdc/feed.py frame layout);
        ``since`` absent is the attach handshake — registers the named
        ``cursor`` at the durable seq, empty body. ``max-bytes`` caps
        one response (the producer stops at a group boundary and the
        Next-Seq header tells the consumer where to resume). A cursor
        behind the retained tail answers 410 ``{"restartFrom",
        "floor"}`` — restart from a snapshot. Frames honor
        Accept-Encoding: deflate like the sync routes; positions ride
        response headers so the body stays a pure frame stream."""
        from pilosa_tpu.cdc.feed import (
            DURABLE_SEQ_HEADER,
            NEXT_SEQ_HEADER,
            TailGone,
            encode_events,
        )

        since_raw = (query.get("since") or [None])[0] if query else None
        since = (_int_param(since_raw, "since")
                 if since_raw is not None else None)
        mb_raw = (query.get("max-bytes") or [None])[0] if query else None
        max_bytes = (_int_param(mb_raw, "max-bytes")
                     if mb_raw is not None else 1 << 20)
        if max_bytes <= 0:
            raise ApiError(f"max-bytes must be positive, got {max_bytes}")
        cursor = (query.get("cursor") or [""])[0] if query else ""
        try:
            events, next_seq, durable = self.api.wal_tail(
                since, max_bytes=max_bytes, cursor=cursor or None)
        except TailGone as e:
            # 410 Gone, the resumability contract's hard edge: the JSON
            # body carries where to restart so a consumer needn't parse
            # the floor out of the error string
            self._json({"error": str(e), "restartFrom": e.restart_from,
                        "floor": e.floor}, status=410)
            return
        self._bytes_negotiated(encode_events(events), {
            NEXT_SEQ_HEADER: str(next_seq),
            DURABLE_SEQ_HEADER: str(durable),
        })

    def get_shards_list(self, query=None):
        index = (query.get("index") or [""])[0]
        idx = self.api._index(index)
        self._json({"shards": idx.available_shards()})

    def get_fragment_block_data(self, query=None):
        """One checksum block's bits as a roaring-serialized octet-stream.
        The reference moves block data as protobuf bodies (SURVEY.md §2
        #16-17); JSON int lists here cost ~20 bytes/bit, which makes
        dense-block repair two orders of magnitude larger than the data."""
        from pilosa_tpu.roaring import RoaringBitmap
        from pilosa_tpu.roaring.format import serialize

        index = (query.get("index") or [""])[0]
        field = (query.get("field") or [""])[0]
        view = (query.get("view") or ["standard"])[0]
        shard = _int_param((query.get("shard") or ["0"])[0], "shard")
        block = _int_param((query.get("block") or ["0"])[0], "block")
        idx = self.api._index(index)
        fld = self.api._field(idx, field)
        v = fld.view(view)
        frag = v.fragment(shard) if v else None
        ids = frag.block_ids(block) if frag is not None else []
        data = serialize(RoaringBitmap.from_ids(ids))
        self._bytes(data)

    def get_fragments_catalog(self, query=None):
        """Every (field, view, shard) fragment of an index — drives resize
        fetches and anti-entropy enumeration."""
        index = (query.get("index") or [""])[0]
        idx = self.api._index(index)
        out = []
        for fname, fld in sorted(idx.fields.items()):
            for vname, view in sorted(fld.views.items()):
                for shard in sorted(view.fragments):
                    out.append({"field": fname, "view": vname, "shard": shard})
        self._json({"fragments": out})

    def _attr_store(self, query):
        index = (query.get("index") or [""])[0]
        field = (query.get("field") or [""])[0]
        idx = self.api._index(index)
        if not field:
            return idx.column_attrs
        return self.api._field(idx, field).row_attrs

    def get_attr_blocks(self, query=None):
        store = self._attr_store(query)
        self._json({"blocks": [
            {"block": b, "checksum": c} for b, c in (store.blocks() if store else [])
        ]})

    def get_attr_block_data(self, query=None):
        store = self._attr_store(query)
        block = _int_param((query.get("block") or ["0"])[0], "block")
        self._json({"attrs": store.block_data(block) if store else {}})

    def post_translate_keys(self, query=None):
        body = self._json_body()
        ids = self.api.holder.translate.translate(
            body.get("namespace", ""), body.get("keys", []),
            create=bool(body.get("create", False)),
        )
        self._json({"ids": ids})

    def get_translate_data(self, query=None):
        offset = _int_param((query.get("offset") or ["0"])[0], "offset")
        data = self.api.holder.translate.read_log(offset)
        self._bytes(data)

    def post_cluster_message(self, query=None):
        body = self._json_body()
        if self.api.cluster is None:
            self._json({})
            return
        self._json(self.api.cluster.handle_message(body))


def _int_param(value: str, name: str) -> int:
    try:
        return int(value)
    except ValueError as e:
        raise ApiError(f"invalid {name} parameter {value!r}") from e


class PilosaHTTPServer(ThreadingHTTPServer):
    # socketserver's default listen backlog (5) resets connections under
    # a concurrent client wave — exactly the traffic shape the coalescing
    # query pipeline exists to serve (server/pipeline.py).
    request_queue_size = 128
    # disable_nagle_algorithm: responses go out as a header write + a
    # body write; without TCP_NODELAY the second small packet can sit
    # behind Nagle/delayed-ACK interplay on real networks
    disable_nagle_algorithm = True

    def __init__(self, *args, **kwargs):
        # counters/registry exist BEFORE bind: TCPServer.__init__ calls
        # server_close on a bind failure (port in use), which walks the
        # registry — post-construction assignment would turn that into
        # an AttributeError masking the real bind error
        self.metrics_lock = threading.Lock()
        self.connections_opened = 0
        self.requests_served = 0
        self.open_connections = set()
        super().__init__(*args, **kwargs)

    def server_close(self):
        super().server_close()
        # Hard-close ESTABLISHED keep-alive connections too: closing only
        # the listener leaves handler threads serving old peers' pooled
        # connections indefinitely — a closed node must look DEAD to the
        # cluster (peers' pools see EOF, reconnect, get refused, degrade),
        # exactly like a crashed process whose sockets the kernel reset.
        import socket as _socket

        with self.metrics_lock:
            conns = list(self.open_connections)
            self.open_connections.clear()
        for sock in conns:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


def make_http_server(api: API, bind: str = "localhost", port: int = 10101):
    handler = type("BoundHandler", (HTTPHandler,), {"api": api})
    return PilosaHTTPServer((bind, port), handler)


def serve_in_thread(api: API, bind: str = "localhost", port: int = 0):
    """Start a server on an ephemeral port; returns (server, port, thread).
    The in-process equivalent of the reference's test.MustRunCluster node."""
    server = make_http_server(api, bind, port)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1], thread
