"""Autopilot placement plane: heat-weighted shard rebalancing.

ROADMAP item 4's closing move: PRs 8-12 built the sensors (decayed
per-shard heat, SLO burn rates) and the safe actuators (epoch-fenced
quorum-gated resize, paced transfers), but placement stayed pure
``hash(index, shard) % 256`` — a Zipf-skewed tenant pins its hot shards
on whichever node the hash picked, and that node's queue becomes the
cluster's p99 while its peers idle. This module closes the loop:

- :func:`plan_moves` is the PURE planner — given per-(index, shard)
  heat, the current owner map, and the live membership, it greedily
  moves the hottest shard groups off nodes above a per-node heat budget
  until every node fits (or the per-pass move budget runs out). The
  budget is a *multiple of the mean node load* (``heat_budget = 1.5`` ⇒
  a node may run 50% hotter than average before the planner acts), with
  a hysteresis dead band: rebalancing starts only above the high
  watermark but drains the node down to the midpoint between mean and
  budget, so a node hovering AT the budget doesn't flap every pass.
  Properties the tests pin: uniform heat ⇒ zero moves, and re-planning
  after applying a plan ⇒ zero moves (idempotent fixpoint).

- :class:`Autopilot` is the ticker (same lifecycle as the residency
  tierer): every node runs one, but a pass acts only on the acting
  coordinator with quorum — so the planner fails over with coordination
  itself. A pass gathers cluster-wide heat (each node records heat
  where shards EXECUTE, so the coordinator polls every member's
  /debug/heatmap and max-merges), reads the SLO burn rate to size the
  move budget (an actively-burning latency objective unlocks the full
  budget; otherwise rebalancing is background maintenance at half
  rate), shapes that budget by the RepairPacer's byte rate (moves ride
  the same paced repair wire, so the planner never schedules more
  transfer than the pacer would admit per interval), and executes via
  the EXISTING machinery: install the override table with
  ``Cluster.apply_placement`` (quorum-gated, epoch-minted, gossiped),
  then ``coordinate_resize`` moves the data and the post-resize cleanup
  drops the old copies — which is exactly why the chaos oracles (zero
  lost acked writes, byte-identical replicas, no non-quorum deletion)
  gate the autopilot itself.

- **Dwell**: a shard moved by a pass is immune from further moves for
  ``min_dwell_s`` (default two intervals) — heat redistributes slowly
  after a move (decayed counters), and without dwell the planner would
  chase its own tail, bouncing the same hot shard between nodes.
"""

from __future__ import annotations

import collections
import threading
import time

DEFAULT_HEAT_BUDGET = 1.5
DEFAULT_MAX_MOVES = 4

# Nominal per-move transfer estimate for pacer shaping: the planner
# runs BEFORE fragments move, so exact sizes are unknowable — one
# roaring fragment of serving-shaped data lands around a MiB (Chambi
# et al. 1402.6407 compression on the delta wire), and the estimate
# only needs to be right within an order of magnitude to keep a
# tightly-paced cluster from scheduling transfers it cannot absorb.
NOMINAL_MOVE_BYTES = 1 << 20


def plan_moves(shard_heat: dict, owners_of, node_ids, *,
               heat_budget: float = DEFAULT_HEAT_BUDGET,
               max_moves: int = DEFAULT_MAX_MOVES,
               frozen=()) -> list[dict]:
    """Greedy heat rebalance. Pure: no clocks, no cluster handles.

    ``shard_heat``: {(index, shard): heat ≥ 0} — the unit of movement.
    ``owners_of``: callable (index, shard) → ordered owner node-id list
    (the live placement, overrides included).
    ``node_ids``: live members eligible to receive shards.
    ``frozen``: (index, shard) keys under dwell — immune this pass.

    Returns moves ``{"index", "shard", "from", "to", "heat",
    "owners"}`` hottest-first, where ``owners`` is the full new owner
    list for the override table (source replaced by target, order
    preserved — order is the query-routing preference)."""
    node_ids = sorted(set(node_ids))
    if len(node_ids) < 2 or max_moves <= 0:
        return []
    frozen = set(frozen)

    # Attribute each group's heat evenly across its owners (replicas
    # share the serving load), building per-node load + the owner map.
    loads = dict.fromkeys(node_ids, 0.0)
    owners: dict[tuple, list[str]] = {}
    shares: dict[tuple, float] = {}
    for key, heat in shard_heat.items():
        own = [i for i in (owners_of(*key) or []) if i in loads]
        if not own or heat <= 0:
            continue
        owners[key] = list(own)
        share = float(heat) / len(own)
        shares[key] = share
        for node_id in own:
            loads[node_id] += share

    mean = sum(loads.values()) / len(node_ids)
    if mean <= 0:
        return []
    high = heat_budget * mean
    # hysteresis dead band: act above ``high``, stop draining at the
    # midpoint — a node sitting exactly at budget neither starts nor
    # endlessly continues a rebalance
    low = mean + (high - mean) / 2.0

    moves: list[dict] = []
    moved: set[tuple] = set()
    while len(moves) < max_moves:
        src = max(loads, key=loads.get)
        if loads[src] <= high:
            break
        # hottest movable groups on the overloaded node first: fewest
        # moves to drain the most heat
        candidates = sorted(
            (key for key, own in owners.items()
             if src in own and key not in frozen and key not in moved),
            key=lambda k: shares[k], reverse=True,
        )
        applied = False
        for key in candidates:
            share = shares[key]
            own = owners[key]
            # least-loaded node not already replicating this group
            targets = [i for i in node_ids if i not in own]
            if not targets:
                continue
            dst = min(targets, key=loads.get)
            # accept only a strict improvement that keeps the target
            # under the source's new load (otherwise the "rebalance"
            # just relocates the hot spot) and never drains below the
            # low watermark's need
            if loads[dst] + share >= loads[src]:
                continue
            loads[src] -= share
            loads[dst] += share
            own[own.index(src)] = dst
            moved.add(key)
            moves.append({
                "index": key[0], "shard": key[1], "from": src,
                "to": dst, "heat": round(shares[key] * len(own), 3),
                "owners": list(own),
            })
            applied = True
            if loads[src] <= low:
                break  # drained into the dead band: next hottest node
            if len(moves) >= max_moves:
                break
        if not applied:
            break  # nothing movable improves the worst node: stop
    return moves


def plan_splits(shard_heat: dict, owners_of, node_ids, current_ranges,
                *, split_threshold: float, split_ways: int = 2,
                shard_width: int | None = None,
                replica_n: int = 1) -> tuple[list[dict], list]:
    """Sub-shard range planning (elastic plane). Pure, like plan_moves.

    Placement moves cannot help ONE pathologically hot (index, shard):
    wherever it lands, that node is the tail. A split spreads it by
    keying sub-shard COLUMN ranges to distinct owners, while the
    whole-shard override is widened to the UNION of range owners — so
    every range owner holds the full fragment (durability unchanged,
    replica routing spreads the load) and range-unaware peers compute
    identical data placement from the override alone.

    ``split_threshold``: a shard whose heat alone exceeds
    ``split_threshold × mean node load`` is split; ≤ 0 disables.
    ``current_ranges``: {(index, shard): spans} already split — a split
    shard whose heat cools below HALF the threshold (hysteresis) is
    merged back (returned in ``merges``); still-hot ones are left
    alone.

    Returns ``(splits, merges)``: splits are ``{"index", "shard",
    "heat", "spans": [(lo, hi, (owner, ...)), ...], "owners": [union]}``
    hottest-first (each span carrying ``replica_n`` owners, so
    range-narrowed writes keep full replica durability), merges are
    (index, shard) keys to un-split."""
    if shard_width is None:
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        shard_width = SHARD_WIDTH
    node_ids = sorted(set(node_ids))
    current_ranges = dict(current_ranges or {})
    if split_threshold <= 0 or len(node_ids) < 2:
        return [], sorted(current_ranges)

    # per-node loads, same attribution as plan_moves (replicas share)
    loads = dict.fromkeys(node_ids, 0.0)
    for key, heat in shard_heat.items():
        own = [i for i in (owners_of(*key) or []) if i in loads]
        if not own or heat <= 0:
            continue
        for node_id in own:
            loads[node_id] += float(heat) / len(own)
    mean = sum(loads.values()) / len(node_ids)
    if mean <= 0:
        return [], sorted(current_ranges)
    hot_cut = split_threshold * mean

    splits: list[dict] = []
    for key, heat in sorted(shard_heat.items(),
                            key=lambda kv: kv[1], reverse=True):
        if key in current_ranges or heat <= hot_cut:
            continue
        own = [i for i in (owners_of(*key) or []) if i in node_ids]
        if not own:
            continue
        ways = max(2, min(int(split_ways), len(node_ids)))
        # spread order: current owners keep their ranges first (no data
        # movement for them), then least-loaded non-owners fill out the
        # fan — the union grows, it NEVER shrinks below current owners
        extra = sorted((i for i in node_ids if i not in own),
                       key=lambda i: loads[i])
        spread = (own + extra)[:ways]
        if len(spread) < 2:
            continue  # cannot spread: every node already an owner of 1
        step = shard_width // len(spread)
        # each span gets replica_n owners (cycling through the spread)
        # so range-aware WRITE routing keeps full replica durability:
        # a narrowed set reaches as many nodes as hash placement would.
        # replica_n=1 degenerates to the original one-owner spans.
        width = max(1, min(int(replica_n), len(spread)))
        spans = [
            (i * step,
             shard_width if i == len(spread) - 1 else (i + 1) * step,
             tuple(spread[(i + j) % len(spread)] for j in range(width)))
            for i in range(len(spread))
        ]
        union = own + [i for i in spread if i not in own]
        splits.append({"index": key[0], "shard": key[1],
                       "heat": round(float(heat), 3),
                       "spans": spans, "owners": union})

    # hysteresis merge: a split shard that cooled below half the cut
    merges = sorted(
        key for key in current_ranges
        if shard_heat.get(key, 0.0) < hot_cut / 2.0
    )
    return splits, merges


def shaped_move_budget(max_moves: int, pacer, interval_s: float,
                       est_move_bytes: int = NOMINAL_MOVE_BYTES) -> int:
    """Per-pass move budget shaped by the RepairPacer: never schedule
    more transfer than the pacer admits in one interval (the moves ride
    the same paced repair wire — scheduling past the rate just queues
    paced sleeps into the resize window and starves serving of exactly
    the bandwidth the pacer protects). Unpaced clusters keep the
    configured budget."""
    max_moves = max(0, int(max_moves))
    rate = float(getattr(pacer, "rate", 0) or 0)
    if rate <= 0 or interval_s <= 0:
        return max_moves
    cap = int((rate * interval_s) / max(int(est_move_bytes), 1))
    return max(1, min(max_moves, cap)) if max_moves else 0


class Autopilot:
    """Planner ticker: heat in, epoch-fenced placement changes out."""

    MAX_DECISIONS = 256
    # dwell stamps are an observability/thrash ring, not history
    MAX_TRACKED = 65536

    def __init__(self, cluster, heat=None, slo=None, *,
                 interval_s: float = 0.0,
                 heat_budget: float = DEFAULT_HEAT_BUDGET,
                 max_moves: int = DEFAULT_MAX_MOVES,
                 min_dwell_s: float | None = None,
                 split_threshold: float = 0.0,
                 split_ways: int = 2,
                 pacer=None, logger=None):
        if heat is None:
            from pilosa_tpu.storage.heat import global_heat

            heat = global_heat()
        self.cluster = cluster
        self.heat = heat
        self.slo = slo
        self.interval_s = float(interval_s)
        self.heat_budget = float(heat_budget)
        self.max_moves = int(max_moves)
        # dwell immunity defaults to two intervals, like the residency
        # tierer: one pass of post-move heat noise cannot bounce the
        # shard straight back
        self.min_dwell_s = (float(min_dwell_s)
                            if min_dwell_s is not None and min_dwell_s > 0
                            else max(2 * self.interval_s, 1.0))
        # sub-shard split/merge (elastic plane): 0 keeps splits off
        self.split_threshold = float(split_threshold)
        self.split_ways = int(split_ways)
        self.pacer = pacer
        self.logger = logger
        self._lock = threading.Lock()
        self._moved_at: dict[tuple, float] = {}
        self._decisions = collections.deque(maxlen=self.MAX_DECISIONS)
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        self.passes = 0
        self.plans = 0
        self.moves_planned = 0
        self.moves_executed = 0
        self.splits_executed = 0
        self.merges_executed = 0
        self.prunes = 0
        self.skips: dict[str, int] = {}
        self.last_pass_s = 0.0
        self.last_burn = 0.0

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "Autopilot":
        if self.interval_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="autopilot"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._closed.wait(self.interval_s):
            try:
                self.run_pass()
            except Exception as e:  # noqa: BLE001 — ticker must not die
                if self.logger is not None:
                    self.logger.warning("autopilot pass failed: %s", e)

    def close(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # ---------------------------------------------------------------- pass

    def _skip(self, reason: str) -> dict:
        self.skips[reason] = self.skips.get(reason, 0) + 1
        return {"acted": False, "reason": reason}

    def _gather_heat(self, peers) -> dict:
        """Cluster-wide (index, shard) → heat: local snapshot plus every
        reachable peer's /debug/heatmap, max-merged by full row key (an
        unreachable peer contributes nothing — its shards read as cold
        this pass, and moving TOWARD a node we cannot see is what the
        live-membership check in plan_moves prevents)."""
        from pilosa_tpu.storage.heat import merge_shard_heat
        from pilosa_tpu.utils.pool import concurrent_map

        row_lists = [
            self.heat.snapshot(residency_overlay=False)["shards"]
        ]

        def one(node):
            try:
                return self.cluster.client.heatmap(
                    node.uri, timeout=self.cluster.heartbeat_timeout,
                )["shards"]
            except Exception:  # noqa: BLE001 — cold this pass
                return []

        if peers:
            row_lists.extend(concurrent_map(one, peers))
        return merge_shard_heat(row_lists)

    def run_pass(self) -> dict:
        """One plan/execute pass. Acts only as the acting coordinator
        with quorum and a NORMAL, non-degraded cluster — every other
        node's ticker idles (and takes over when coordination fails
        over to it). Returns the pass record (tests, /debug)."""
        from pilosa_tpu.parallel.cluster import STATE_NORMAL

        t0 = time.monotonic()
        self.passes += 1
        c = self.cluster
        record: dict
        try:
            if not c.is_acting_coordinator:
                return self._skip("not-coordinator")
            if c.degraded:
                return self._skip("degraded")
            if c.state != STATE_NORMAL:
                return self._skip("not-normal")
            if getattr(c, "drain_active", False):
                # one coordinated actuator per epoch: a drain owns the
                # placement table until it terminates — planning now
                # would mint dueling resizes (and vice versa: a drain
                # refuses to start while a resize is in flight). The
                # skip reason is visible on /debug/autopilot.
                return self._skip("drain-in-flight")
            with c._lock:
                node_ids = sorted(c.nodes)
                peers = [n for n in c.nodes.values()
                         if n.id != c.local.id]
            if len(node_ids) < 2:
                return self._skip("single-node")

            shard_heat = self._gather_heat(peers)
            holder = c.holder
            if holder is not None:
                # deleted indexes' heat decays but may linger — never
                # mint overrides for shards that no longer exist
                shard_heat = {k: v for k, v in shard_heat.items()
                              if k[0] in holder.indexes}

            burn = 0.0
            if self.slo is not None:
                try:
                    burn = float(self.slo.max_burn_rate())
                except Exception:  # noqa: BLE001 — plan without SLO
                    burn = 0.0
            self.last_burn = burn
            budget = shaped_move_budget(self.max_moves, self.pacer,
                                        self.interval_s)
            if burn < 1.0:
                # no error budget burning: rebalance is background
                # maintenance, run at half throttle
                budget = max(1, budget // 2) if budget else 0

            now = time.monotonic()
            current_ranges = c.placement.ranges_snapshot()
            with self._lock:
                if len(self._moved_at) > self.MAX_TRACKED:
                    self._moved_at.clear()
                frozen = {k for k, t in self._moved_at.items()
                          if now - t < self.min_dwell_s}
            # a range-split shard never MOVES: relocating one owner of
            # a split would desync the range map from the override
            frozen |= set(current_ranges)
            moves = plan_moves(
                shard_heat,
                owners_of=lambda i, s: [n.id
                                        for n in c.shard_nodes(i, s)],
                node_ids=node_ids,
                heat_budget=self.heat_budget,
                max_moves=budget,
                frozen=frozen,
            )
            splits, merges = [], []
            if self.split_threshold > 0:
                splits, merges = plan_splits(
                    shard_heat,
                    owners_of=lambda i, s: [n.id
                                            for n in c.shard_nodes(i, s)],
                    node_ids=node_ids,
                    current_ranges=current_ranges,
                    split_threshold=self.split_threshold,
                    split_ways=self.split_ways,
                    replica_n=c.replica_n,
                )
                splits = [s for s in splits
                          if (s["index"], s["shard"]) not in frozen][:1]
                # one split per pass: each rides its own resize, and
                # the hysteresis merge needs settled heat to judge
            self.plans += 1
            self.moves_planned += len(moves)

            # assemble the new table: current overrides, minus entries
            # gone stale (departed owners — hash placement already
            # resumed for them, materialize it) or redundant (equal to
            # the hash walk), plus this pass's moves and splits
            live = set(node_ids)
            table = {}
            pruned = 0
            for key, ids in c.placement.snapshot().items():
                hash_ids = tuple(
                    n.id for n in c.partition_nodes(c.partition(*key)))
                if not set(ids) <= live or tuple(ids) == hash_ids:
                    pruned += 1
                    continue
                table[key] = ids
            for m in moves:
                key = (m["index"], m["shard"])
                hash_ids = tuple(
                    n.id for n in c.partition_nodes(c.partition(*key)))
                if tuple(m["owners"]) == hash_ids:
                    table.pop(key, None)  # moved back home: no entry
                else:
                    table[key] = tuple(m["owners"])

            # ranges: keep live splits, drop merged/stale ones, add new
            ranges = {}
            range_prunes = 0
            for key, spans in current_ranges.items():
                if key in merges:
                    range_prunes += 1
                    continue
                span_owners = {i for _, _, ids in spans for i in ids}
                if not span_owners <= live:
                    # a range owner departed: un-split (union routing
                    # already resumed via shard_nodes' fallback)
                    range_prunes += 1
                    table.pop(key, None)
                    continue
                ranges[key] = spans
            for s in splits:
                key = (s["index"], s["shard"])
                ranges[key] = tuple(s["spans"])
                # the mixed-version contract: a split ALWAYS installs
                # its union owners as the whole-shard override
                table[key] = tuple(s["owners"])
            if merges:
                for key in merges:
                    table.pop(key, None)  # back to hash/override home

            if not moves and not pruned and not splits \
                    and not range_prunes:
                return self._skip("in-budget")

            epoch = c.apply_placement(table, ranges=ranges)
            if not epoch:
                return self._skip("no-quorum")
            with self._lock:
                for m in moves:
                    self._moved_at[(m["index"], m["shard"])] = now
                for s in splits:
                    self._moved_at[(s["index"], s["shard"])] = now
            self.moves_executed += len(moves)
            self.splits_executed += len(splits)
            self.merges_executed += len(merges)
            self.prunes += pruned + range_prunes
            if self.logger is not None:
                self.logger.info(
                    "autopilot epoch %d: %d move(s), %d split(s), "
                    "%d merge(s), %d pruned, burn %.2f, budget %d: %s",
                    epoch, len(moves), len(splits), len(merges),
                    pruned + range_prunes, burn, budget,
                    [f"{m['index']}/{m['shard']} {m['from']}→{m['to']}"
                     for m in moves]
                    + [f"split {s['index']}/{s['shard']} "
                       f"×{len(s['spans'])}" for s in splits],
                )
            record = {
                "acted": True, "epoch": epoch, "moves": moves,
                "splits": [{"index": s["index"], "shard": s["shard"],
                            "heat": s["heat"],
                            "spans": [[lo, hi, list(ids)]
                                      for lo, hi, ids in s["spans"]]}
                           for s in splits],
                "merges": [list(k) for k in merges],
                "pruned": pruned + range_prunes, "burn": round(burn, 3),
                "budget": budget,
                "heatGroups": len(shard_heat),
            }
            self._decisions.append({"at": time.time(), **record})
            if moves or splits:
                # the actuator: new owners pull their fragments through
                # the epoch-fenced resize, cleanup drops the old copies
                # (a split's new union owners fetch the whole fragment)
                c.coordinate_resize()
            return record
        finally:
            self.last_pass_s = time.monotonic() - t0

    # -------------------------------------------------------- observability

    def last_decisions(self, k: int = 32) -> list[dict]:
        with self._lock:
            return list(self._decisions)[-k:]

    def metrics(self) -> dict:
        """autopilot_* series for /metrics and /debug/vars — every key
        present from scrape one (api.autopilot_metrics zero-fills when
        the ticker is off)."""
        skipped = sum(self.skips.values())
        return {
            "autopilot_passes_total": self.passes,
            "autopilot_plans_total": self.plans,
            "autopilot_moves_planned_total": self.moves_planned,
            "autopilot_moves_executed_total": self.moves_executed,
            "autopilot_splits_total": self.splits_executed,
            "autopilot_merges_total": self.merges_executed,
            "autopilot_overrides_pruned_total": self.prunes,
            "autopilot_passes_skipped_total": skipped,
            "autopilot_placement_overrides": len(self.cluster.placement),
            "autopilot_placement_epoch": self.cluster.placement.epoch,
            "autopilot_last_pass_seconds": round(self.last_pass_s, 6),
            "autopilot_slo_burn_rate": round(self.last_burn, 4),
        }

    def to_json(self) -> dict:
        """GET /debug/autopilot: knobs, planner state, the decision log,
        and the live override table."""
        return {
            "enabled": True,
            "intervalS": self.interval_s,
            "heatBudget": self.heat_budget,
            "maxMoves": self.max_moves,
            "minDwellS": self.min_dwell_s,
            "splitThreshold": self.split_threshold,
            "splitWays": self.split_ways,
            "actingCoordinator": self.cluster.is_acting_coordinator,
            "skips": dict(self.skips),
            "metrics": self.metrics(),
            "placement": self.cluster.placement.to_json(),
            "decisions": self.last_decisions(),
        }
