"""Elastic membership plane: coordinator-driven graceful drain.

ROADMAP open item 2's closing move: the cluster could *rebalance*
(autopilot moves) but not change SIZE cleanly — a departing node just
broadcast node-leave and relied on replicas, which at replica_n == 1
loses data, and at any replica count leaves the tail to anti-entropy.
:class:`ElasticManager` drives the missing half as a resumable state
machine built ENTIRELY from the existing epoch-fenced, quorum-gated
primitives:

``pending → moving → handoff → leaving → done`` (or ``aborted`` /
``failed``), where

- **pending**: the drain record is epoch-stamped (one minted epoch per
  drain, rev-bumped per state change) and broadcast; adopting it flips
  the TARGET's ``draining`` latch, so writes shed BEFORE any data
  moves — the window where an acked write could land on a fragment
  mid-departure is closed first;
- **moving**: every (index, shard) group the target owns is rewritten
  in the placement table to a least-loaded live replacement
  (``apply_placement`` — quorum-gated, epoch-minted, gossiped), then
  ``coordinate_resize`` makes the new owners pull their copies and the
  post-resize cleanup drops the target's;
- **handoff**: the target's CDC cursors on the coordinator's WAL are
  dropped (every other member drops theirs on the node-leave they
  receive next — the same departed-member drop that covers
  declared-dead nodes), releasing the WAL retention those cursors
  pinned;
- **leaving**: the coordinator sends ``drain-leave``; the target calls
  ``Cluster.leave()`` and departs. An unreachable target is declared
  dead instead (quorum-gated) so the drain still terminates.

The record gossips via /status and drain-update messages, so when the
drain COORDINATOR dies mid-drain, the failover coordinator's
``maybe_resume`` (driven from the heartbeat tick) adopts the record
and re-enters the machine at the recorded state — every step is
idempotent against the epoch-fenced actuators, so re-running a
half-finished step is safe. One drain at a time, and never while a
resize is in flight or the autopilot is mid-action (the planner
symmetrically skips while a drain is active): one coordinated actuator
per epoch.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.parallel.client import ClientError
from pilosa_tpu.parallel.cluster import (
    DRAIN_ACTIVE_STATES,
    STATE_DEGRADED,
    STATE_NORMAL,
)


class ElasticError(Exception):
    """A drain request the coordinator refuses (or cannot take). Maps
    to the carried HTTP status at the API edge."""

    def __init__(self, message: str, status: int = 409):
        super().__init__(message)
        self.status = int(status)


class _DrainInterrupted(Exception):
    """The running drain thread lost ownership of the record (aborted
    by the operator, or superseded by a newer drain epoch): unwind
    without stamping a terminal state."""


class ElasticManager:
    """Drain state machine + elastic observability, wired as
    ``api.elastic`` on every server (drain must work with the autopilot
    ticker off)."""

    # how long the leaving step waits for the target to depart the
    # member list before declaring it dead instead (tests shrink this)
    LEAVE_TIMEOUT = 10.0

    def __init__(self, cluster, logger=None):
        self.cluster = cluster
        self.logger = logger
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._closed = threading.Event()
        self.drains_started = 0
        self.drains_completed = 0
        self.drains_failed = 0
        self.drains_aborted = 0
        self.drains_resumed = 0
        self.cursor_handoffs = 0

    def close(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    # --------------------------------------------------------- operator API

    def start_drain(self, target: str) -> dict:
        """Begin draining ``target`` (acting coordinator only). Refuses
        — with the reason in the raised ElasticError — whenever a
        second coordinated actuator could mint dueling resizes."""
        c = self.cluster
        if not c.is_acting_coordinator:
            raise ElasticError(
                "not the acting coordinator: start the drain there", 409)
        with c._lock:
            nodes = dict(c.nodes)
        if target not in nodes:
            raise ElasticError(f"unknown node {target!r}", 404)
        if target == c.local.id:
            raise ElasticError(
                "refusing to drain the acting coordinator: drain the "
                "other nodes first (coordination fails over only after "
                "this node actually leaves)", 409)
        if len(nodes) < 2:
            raise ElasticError("nothing to drain to: single-node", 409)
        if c.drain_active:
            raise ElasticError(
                f"a drain of {c.drain_record.get('target')!r} is "
                "already in flight", 409)
        if c.state != STATE_NORMAL:
            raise ElasticError(
                "cluster is resizing: one coordinated action at a time",
                409)
        if c.degraded or not c.check_quorum():
            raise ElasticError("no member quorum: drain refused", 503)
        epoch = c._bump_epoch()
        c._note_acted(epoch, f"drain:{target}")
        record = {
            "epoch": epoch, "rev": 1, "target": target,
            "state": "pending", "coordinator": c.local.id,
            "groups": 0, "moved": 0, "error": "",
        }
        # the broadcast flips the target's draining latch NOW — writes
        # shed before the first byte moves
        c.set_drain(record)
        self.drains_started += 1
        if self.logger is not None:
            self.logger.info("drain of %s started (epoch %d)",
                             target, epoch)
        self._spawn(record)
        return dict(record)

    def abort_drain(self) -> dict:
        """Stamp the in-flight drain aborted: the target un-sheds, its
        remaining groups stay where the machine left them (already-
        moved overrides remain valid placement). Acting-coordinator
        only — the abort must gossip from the authority peers obey."""
        c = self.cluster
        if not c.is_acting_coordinator:
            raise ElasticError(
                "not the acting coordinator: abort the drain there", 409)
        with c._lock:
            record = dict(c.drain_record)
        if record.get("state") not in DRAIN_ACTIVE_STATES:
            raise ElasticError("no drain in flight", 409)
        record["rev"] = int(record.get("rev", 1)) + 1
        record["state"] = "aborted"
        c.set_drain(record)
        self.drains_aborted += 1
        if self.logger is not None:
            self.logger.info("drain of %s aborted",
                             record.get("target"))
        return record

    def status(self) -> dict:
        c = self.cluster
        with c._lock:
            record = dict(c.drain_record)
        return {
            "drain": record,
            "active": c.drain_active,
            "draining": c.draining,
        }

    def maybe_resume(self) -> bool:
        """Heartbeat-tick hook on every node: when the drain record is
        ACTIVE, this node is the acting coordinator, and no local drain
        thread is running, take the state machine over (coordinator
        failover mid-drain, or a restart of the original coordinator).
        A record whose target already departed the membership is simply
        stamped done — the drain's goal state was reached."""
        if self._closed.is_set():
            return False
        c = self.cluster
        with c._lock:
            record = dict(c.drain_record)
        if record.get("state") not in DRAIN_ACTIVE_STATES:
            return False
        if not c.is_acting_coordinator:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False  # the machine is already running here
        target = record.get("target")
        with c._lock:
            present = target in c.nodes
        if not present:
            record["rev"] = int(record.get("rev", 1)) + 1
            record["state"] = "done"
            c.set_drain(record)
            self.drains_completed += 1
            return True
        if c.degraded:
            return False  # resume only with a healthy majority
        record["rev"] = int(record.get("rev", 1)) + 1
        record["coordinator"] = c.local.id
        c.set_drain(record)
        self.drains_resumed += 1
        if self.logger is not None:
            self.logger.info(
                "resuming drain of %s from state %s on %s",
                target, record.get("state"), c.local.id,
            )
        self._spawn(record)
        return True

    def metrics(self) -> dict:
        c = self.cluster
        return {
            "elastic_drains_started_total": self.drains_started,
            "elastic_drains_completed_total": self.drains_completed,
            "elastic_drains_failed_total": self.drains_failed,
            "elastic_drains_aborted_total": self.drains_aborted,
            "elastic_drains_resumed_total": self.drains_resumed,
            "elastic_cursor_handoffs_total": self.cursor_handoffs,
            "elastic_drain_active": 1 if c.drain_active else 0,
            "elastic_drain_epoch":
                int(c.drain_record.get("epoch", 0) or 0),
        }

    def to_json(self) -> dict:
        """GET /debug/elastic: the drain state machine, counters, and
        the range-split placement view."""
        c = self.cluster
        out = self.status()
        out["metrics"] = self.metrics()
        out["placement"] = c.placement.to_json()
        return out

    # -------------------------------------------------------- state machine

    def _spawn(self, record: dict) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, args=(dict(record),),
                daemon=True, name="drain",
            )
            self._thread.start()

    def _advance(self, record: dict, state: str) -> None:
        """Move the record to ``state`` — but only while this thread
        still OWNS it: an operator abort or a newer drain epoch landed
        in the cluster record means this machine must unwind without
        stamping anything."""
        c = self.cluster
        with c._lock:
            cur = c.drain_record
            if int(cur.get("epoch", 0) or 0) != int(record["epoch"]):
                raise _DrainInterrupted("superseded by a newer drain")
            if cur.get("state") in ("aborted", "failed"):
                raise _DrainInterrupted(f"drain {cur.get('state')}")
        record["rev"] = int(record.get("rev", 1)) + 1
        record["state"] = state
        self.cluster.set_drain(record)

    def _run(self, record: dict) -> None:
        c = self.cluster
        target = record["target"]
        try:
            if record["state"] == "pending":
                self._advance(record, "moving")
            if record["state"] == "moving":
                if self._closed.is_set():
                    return
                table, ranges, groups = self._drain_overrides(target)
                record["groups"] = groups
                epoch = c.apply_placement(table, ranges=ranges)
                if not epoch:
                    raise RuntimeError(
                        "placement refused (lost coordination or quorum "
                        "mid-drain)")
                c.coordinate_resize()
                record["moved"] = groups
                self._advance(record, "handoff")
            if record["state"] == "handoff":
                # the target's tail cursors on THIS node's WAL go now;
                # every other member drops its own on the node-leave
                # broadcast the leaving step triggers
                self.cursor_handoffs += c.drop_departed_cursors(target)
                self._advance(record, "leaving")
            if record["state"] == "leaving":
                self._leave_target(record, target)
                self._advance(record, "done")
            self.drains_completed += 1
            if self.logger is not None:
                self.logger.info(
                    "drain of %s complete: %d group(s) moved",
                    target, record.get("moved", 0),
                )
        except _DrainInterrupted as e:
            if self.logger is not None:
                self.logger.info("drain of %s interrupted: %s", target, e)
        except Exception as e:  # noqa: BLE001 — stamp failed, never die
            record["error"] = repr(e)
            try:
                self._advance(record, "failed")
            except _DrainInterrupted:
                pass
            self.drains_failed += 1
            if self.logger is not None:
                self.logger.error("drain of %s failed: %r", target, e)

    def _leave_target(self, record: dict, target: str) -> None:
        """Tell the target to leave; wait for the membership to reflect
        it. An unreachable target (it died mid-drain) is declared dead
        instead — its groups are already moved, so the declaration's
        resize finds nothing left to do but the record still reaches
        ``done``."""
        c = self.cluster
        with c._lock:
            node = c.nodes.get(target)
        if node is None:
            return  # already departed
        try:
            # current cluster epoch, not the record's minted-at-start
            # one: the moving step's resize bumped the epoch past it
            # and the target would fence the leave as stale
            c.client.send_message(node.uri, {
                "type": "drain-leave", "node": target,
                "epoch": int(c.epoch),
            })
        except ClientError:
            pass  # fall through to the departure wait + dead fallback
        deadline = time.monotonic() + self.LEAVE_TIMEOUT
        while time.monotonic() < deadline:
            with c._lock:
                if target not in c.nodes:
                    return
            if self._closed.is_set():
                return
            time.sleep(0.05)
        if self.logger is not None:
            self.logger.info(
                "drain target %s did not leave in %.1fs: declaring dead",
                target, self.LEAVE_TIMEOUT,
            )
        c.declare_dead(target)

    def _drain_overrides(self, target: str) -> tuple[dict, dict, int]:
        """The moving step's plan: every (index, shard) group the
        target owns gets an override with the target replaced by the
        least-loaded live node not already an owner (or simply removed
        when every live node already replicates it). Existing overrides
        and splits are preserved minus the target; a split whose ranges
        named the target is un-split (union routing resumes). Returns
        (override table, ranges table, groups moved off)."""
        c = self.cluster
        with c._lock:
            live = sorted(
                i for i, n in c.nodes.items()
                if i != target and n.state != STATE_DEGRADED
            )
        if not live:
            raise RuntimeError("no live node to receive the drain")

        # group universe: local fragments ∪ announced shards ∪ peer
        # catalogs — the same union the resize planner sees
        shards_by_index: dict[str, set[int]] = {}
        holder = c.holder
        if holder is not None:
            for index_name, idx in list(holder.indexes.items()):
                shards: set[int] = set()
                for field in list(idx.fields.values()):
                    for view in list(field.views.values()):
                        shards.update(int(s) for s in view.fragments)
                shards.update(c.get_known_shards(index_name))
                for _f, _v, s, _node in c._peer_fragment_entries(
                        index_name):
                    shards.add(int(s))
                shards_by_index[index_name] = shards

        table = dict(c.placement.snapshot())
        ranges = dict(c.placement.ranges_snapshot())

        # seed receiver balance with current ownership so the drain
        # doesn't pile every group onto one node
        load = dict.fromkeys(live, 0)
        for index_name, shards in shards_by_index.items():
            for shard in shards:
                for n in c.shard_nodes(index_name, shard):
                    if n.id in load:
                        load[n.id] += 1

        groups = 0
        for index_name, shards in sorted(shards_by_index.items()):
            for shard in sorted(shards):
                owners = [n.id for n in c.shard_nodes(index_name, shard)]
                if target not in owners:
                    continue
                groups += 1
                candidates = [i for i in live if i not in owners]
                if candidates:
                    repl = min(candidates, key=lambda i: load[i])
                    load[repl] += 1
                    new_owners = tuple(
                        repl if i == target else i for i in owners)
                else:  # every live node already replicates this group
                    new_owners = tuple(
                        i for i in owners if i != target)
                if new_owners:
                    table[(index_name, int(shard))] = new_owners

        # scrub the target from anything the walk above didn't touch
        for key, ids in list(table.items()):
            if target in ids:
                remaining = tuple(i for i in ids if i != target)
                if remaining:
                    table[key] = remaining
                else:
                    del table[key]
        for key, spans in list(ranges.items()):
            if any(target in ids for _lo, _hi, ids in spans):
                del ranges[key]  # un-split: union/hash routing resumes
        return table, ranges, groups
