"""Autopilot placement plane: heat-weighted shard rebalancing that
recovers hot-spot p99 without operator action (ROADMAP item 4), plus
the elastic membership plane (graceful drain, sub-shard split/merge —
ROADMAP item 2). The pure planners and the ticker live in ``planner``,
the drain state machine in ``elastic``; the actuator surface (the
epoch-stamped placement-override + range table) lives beside the hash
ring in ``pilosa_tpu.parallel.cluster``."""

from pilosa_tpu.autopilot.elastic import ElasticError, ElasticManager
from pilosa_tpu.autopilot.planner import (
    DEFAULT_HEAT_BUDGET,
    DEFAULT_MAX_MOVES,
    Autopilot,
    plan_moves,
    plan_splits,
    shaped_move_budget,
)

__all__ = [
    "Autopilot",
    "ElasticError",
    "ElasticManager",
    "plan_moves",
    "plan_splits",
    "shaped_move_budget",
    "DEFAULT_HEAT_BUDGET",
    "DEFAULT_MAX_MOVES",
]
