"""Autopilot placement plane: heat-weighted shard rebalancing that
recovers hot-spot p99 without operator action (ROADMAP item 4). The
pure planner and the ticker live in ``planner``; the actuator surface
(the epoch-stamped placement-override table) lives beside the hash
ring in ``pilosa_tpu.parallel.cluster``."""

from pilosa_tpu.autopilot.planner import (
    DEFAULT_HEAT_BUDGET,
    DEFAULT_MAX_MOVES,
    Autopilot,
    plan_moves,
    shaped_move_budget,
)

__all__ = [
    "Autopilot",
    "plan_moves",
    "shaped_move_budget",
    "DEFAULT_HEAT_BUDGET",
    "DEFAULT_MAX_MOVES",
]
