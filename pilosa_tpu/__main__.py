import sys

from pilosa_tpu.cli import main

sys.exit(main())
