"""The query executor: dispatch, shard mapReduce, result reduction.

Reference: executor.go (SURVEY.md §2 #12, §3.2–3.4). Shape preserved:
``execute<CallName>`` dispatch, a map phase over shards and a reduce phase
merging partials (rows union, counts add, TopN pair-merge + exact recount,
GroupBy group-merge). TPU re-design: the map phase evaluates ONE fused
compiled kernel per query shape per shard (expr.py) against HBM-resident
rows; the single-chip path loops shards on the host, and the mesh path
(pilosa_tpu.parallel) shard_maps the same kernels with psum reduces.

BSI semantics (Sum/Min/Max/Range): values are offset-encoded against the
field base (storage.field); kernels work on stored magnitudes and the
host adds ``base·count`` back (Sum) or ``base`` (Min/Max). Predicates are
base-shifted and range-clamped at compile time so out-of-range compares
reduce to const-empty / all-existing without touching the device.
"""

from __future__ import annotations

import collections
import datetime as dt
import math
import threading
import time
import weakref
from typing import Callable

import numpy as np

from pilosa_tpu.executor import batch, expr
from pilosa_tpu.executor.result import GroupCount, Pair, RowResult, ValCount
from pilosa_tpu.pql import Call, Condition, parse
from pilosa_tpu.pql.ast import Query
from pilosa_tpu.shardwidth import WORDS_PER_SHARD, next_pow2, position, shard_of
from pilosa_tpu.storage import residency
from pilosa_tpu.storage.heat import global_heat
from pilosa_tpu.utils.cost import current_cost, use_node
from pilosa_tpu.storage.field import (
    BSI_EXISTS_ROW,
    TYPE_INT,
    TYPE_TIME,
)
from pilosa_tpu.storage.index import EXISTENCE_FIELD, Index
from pilosa_tpu.storage.view import VIEW_STANDARD, views_by_time_range

# TopN phase-1 candidate overfetch per shard (reference uses a similar
# superset factor before the exact recount — SURVEY.md §3.4; exact upstream
# value unverifiable, Appendix B).
TOPN_CANDIDATE_FACTOR = 4

# HBM budget (per device) for one TopN phase-2 candidate matrix chunk. A
# candidate row costs shards×2^15 words ≈ 128 MiB/candidate at 1024
# shards, so an unchunked 64-candidate matrix would be 8 GiB — larger
# than the residency budget. Chunks are power-of-two candidate counts so
# a pipelined TopN stream still buckets into shared program shapes.
TOPN_MATRIX_BUDGET_BYTES = 1 << 30

# GroupBy cross-products at or below this size are evaluated in a single
# level (one device sync); larger ones use per-dimension prefix pruning
# (one sync per dimension). Memory is bounded separately, by
# batch.GROUPBY_MASK_BUDGET_BYTES-based chunking, at any size.
GROUPBY_DENSE_MAX_GROUPS = 4096

_RESERVED_ARGS = {"_field", "_col", "from", "to", "n", "limit", "offset",
                  "previous", "column", "filter", "field", "ids", "timestamp",
                  "excludeColumns", "shards", "aggregate", "columnAttrs",
                  "attrName", "attrValue", "like", "threshold", "having"}


class PQLError(ValueError):
    pass


# --------------------------------------------------------------- leaf specs


class _RowSpec:
    """Device leaf: OR of one row across a set of views (time ranges span
    multiple views; missing fragments contribute zeros)."""

    __slots__ = ("field", "views", "row")

    def __init__(self, field: str, views: tuple[str, ...], row: int):
        self.field = field
        self.views = views
        self.row = row

    def resolve(self, idx: Index, shard: int):
        field = idx.field(self.field)
        acc = None
        for vname in self.views:
            view = field.view(vname) if field else None
            frag = view.fragment(shard) if view else None
            if frag is None:
                continue
            row = frag.device_row(self.row)
            acc = row if acc is None else acc | row
        return acc if acc is not None else _zeros_words()


class _PlanesSpec:
    """Device leaf: the stacked BSI plane matrix uint32[2+depth, words].
    ``depth`` is captured at compile time so a delete_field racing the
    query resolves to correctly-shaped zeros, not a dead dereference."""

    __slots__ = ("field", "depth")

    def __init__(self, field: str, depth: int):
        self.field = field
        self.depth = depth

    def resolve(self, idx: Index, shard: int):
        # compile-time depth throughout: the node's clamped scalars were
        # built for it, so a racing delete+recreate with a different
        # range must not change the leaf shape mid-plan (the schema epoch
        # invalidates the plan for the NEXT query)
        depth = self.depth
        field = idx.field(self.field)
        view = field.view(field.bsi_view_name()) if field is not None else None
        frag = view.fragment(shard) if view else None
        if frag is None:
            return _zeros_planes(2 + depth)

        def decode():
            rows = [frag.row_words(r) for r in range(2 + depth)]
            return np.stack(rows)

        return residency.global_row_cache().get_row(
            frag.frag_id + ("__planes__", 2 + depth), decode
        )


class _ZeroSpec:
    __slots__ = ()

    def resolve(self, idx: Index, shard: int):
        return _zeros_words()


_zeros = {}


def _zeros_words():
    z = _zeros.get(WORDS_PER_SHARD)
    if z is None:
        import jax

        z = jax.device_put(np.zeros(WORDS_PER_SHARD, np.uint32))
        _zeros[WORDS_PER_SHARD] = z
    return z


def _zeros_planes(rows: int):
    key = ("planes", rows)
    z = _zeros.get(key)
    if z is None:
        import jax

        z = jax.device_put(np.zeros((rows, WORDS_PER_SHARD), np.uint32))
        _zeros[key] = z
    return z


class _Compiled:
    """A bitmap call compiled to (structure, leaf specs, scalars).

    ``memoizable`` is set by _compile_cached exactly when the plan was
    placed in the plan cache: only those objects have a stable identity
    across repeat queries, so only their operand assemblies are worth
    (and safe to bound) memoizing — per-call plans (TopN phase 2,
    const0-degenerate trees) would fill the operand memo with
    dead-on-arrival entries."""

    def __init__(self, node, specs, scalars):
        self.node = node
        self.specs = specs
        self.scalars = scalars
        self.memoizable = False


    def eval(self, idx: Index, shard: int):
        """Single-shard evaluation (IncludesColumn); batched queries go
        through Executor._batched_eval instead."""
        leaves = [s.resolve(idx, shard) for s in self.specs]
        if not leaves:
            leaves = [_zeros_words()]
        return expr.evaluate(self.node, leaves, self.scalars)


def _node_has_const0(node) -> bool:
    """True when a compiled tree contains a const0 leaf — compiled from
    an unknown row key (or a degenerate range), whose meaning can change
    with later writes; such plans are not memoized."""
    if not isinstance(node, tuple):
        return False
    if node and node[0] == "const0":
        return True
    return any(_node_has_const0(c) for c in node[1:])


class Deferred:
    """Handle for a pipelined query result (Executor.submit).

    For most pipelined calls the device program is already enqueued and
    ``result()`` performs only the blocking host readback (plus host
    finalization); because a single device's stream is ordered,
    resolving the LAST such Deferred implies every earlier program has
    completed. Exception: calls whose evaluation needs intermediate
    readbacks (pruned multi-level GroupBy) defer their dispatch into
    ``result()`` too — see Executor.submit's per-call contract.
    """

    __slots__ = ("_finalize", "_value")

    def __init__(self, finalize=None, value=None):
        self._finalize = finalize
        self._value = value

    def result(self):
        if self._finalize is not None:
            self._value = self._finalize()
            self._finalize = None
        return self._value


# ----------------------------------------------------------------- executor


def instrument_calls(index_name: str, calls, run_one) -> list:
    """Stats/trace/cost envelope around a query's calls: one
    ``executor.Execute`` span per query, per-call ``execute<Name>`` spans
    and ``query``/``queries`` stats. Shared by eager execution and the
    serving pipeline's resolve loop (server/api.py) so span and stat
    names cannot drift between the two paths. With a PROFILE active
    (utils/cost.py) each call additionally runs under its ProfileNode —
    wall time and result cardinality land per AST node, matching the
    span tree's per-call attribution so the two reconcile."""
    from pilosa_tpu.utils.stats import global_stats
    from pilosa_tpu.utils.tracing import global_tracer

    stats = global_stats()
    cost = current_cost()
    profile = cost.profile if cost is not None else None
    out = []
    # root_span: joins the request's trace under the HTTP root, or roots
    # its own tree for direct in-process callers (tests, CLI)
    with global_tracer().root_span("executor.Execute", index=index_name):
        for i, call in enumerate(calls):
            if profile is None:  # accounting-only path: no node scoping
                with global_tracer().span(f"execute{call.name}"), \
                        stats.timer("query", {"call": call.name}):
                    out.append(run_one(call))
                stats.count("queries", 1, {"call": call.name})
                continue
            node = profile.node_for(i, call)
            t0 = time.perf_counter()
            with use_node(cost, node):
                with global_tracer().span(f"execute{call.name}"), \
                        stats.timer("query", {"call": call.name}):
                    res = run_one(call)
                node.wall_s += time.perf_counter() - t0
                cost.note_rows(_result_cardinality(res))
            out.append(res)
            stats.count("queries", 1, {"call": call.name})
    return out


def _result_cardinality(res) -> int:
    """Rows materialized by one call's result (PROFILE accounting):
    result-set cardinality for bitmap calls, element counts for
    TopN/GroupBy/Rows lists. Computed only when profiling — the RowResult
    popcount is not free."""
    if isinstance(res, RowResult):
        return int(res.count())
    if isinstance(res, list):
        return len(res)
    return 0


class Executor:
    # Queries per micro-batched dispatch (see _microbatch_enqueue).
    MICROBATCH_MAX = 16
    # XLA accounts every parameter of a compiled program as distinct HBM
    # storage even when parameters alias one buffer (measured on v5e: a
    # 64-query batch of 2×128MiB leaves fails compile with "arguments
    # 16.00G"), so a micro-batch of wide queries (many leaves) must cap
    # its TOTAL argument bytes, not just its query count — 4-way
    # intersects over 1B columns would otherwise OOM at MICROBATCH_MAX.
    MICROBATCH_ARG_BUDGET = 4 << 30
    # Plan-memo bound; cleared wholesale when full (see _compile_cached).
    PLAN_CACHE_MAX = 4096

    def __init__(self, holder):
        self.holder = holder
        # cluster hooks (set by ClusterExecutor): key_resolver translates
        # unknown keys via the coordinator; key_backfill pulls the
        # coordinator's translate log before reverse lookups
        self.key_resolver = None
        self.key_backfill = None
        self.microbatch_max = self.MICROBATCH_MAX
        self.microbatch_arg_budget = self.MICROBATCH_ARG_BUDGET
        # divisor for per-DEVICE argument accounting: mesh-sharded leaves
        # occupy nbytes/n_devices per chip (DistExecutor sets mesh.size)
        self.arg_shard_factor = 1
        self._pending: dict = {}
        self._mb_lock = threading.Lock()
        # (index, call identity, wrap) -> validated plan; see _compile_cached
        self._plan_cache: dict = {}
        # shard-list identity -> ShardBlock (LRU); see _shard_block
        self._block_memo: collections.OrderedDict = collections.OrderedDict()
        # (plan identity, block identity) -> assembled device operands,
        # valid for ONE residency generation; see _eval_operands. A
        # listener on the row cache drops entries (and their
        # device-array references) EAGERLY on every generation bump so
        # a residency eviction actually frees HBM instead of waiting
        # for the next query's validity check; it is (re-)registered
        # lazily against whatever cache is globally live, because
        # set_global_row_cache can swap the cache after this executor
        # was built (Server.open's budget-sized cache).
        self._operand_memo: dict = {}
        self._operand_memo_gen = -1
        self._listened_cache = None
        # guards the re-home check-then-register below: two serving
        # threads racing it would both register the clear listener
        self._rehome_lock = threading.Lock()

    def _clear_operand_memo(self) -> None:
        """Generation listener (called under the residency lock — must
        stay lock-free and cheap)."""
        self._operand_memo.clear()

    # ------------------------------------------------------------ top level

    def execute(self, index_name: str, query, shards=None, deadline=None):
        if deadline is not None:
            # the local map is fast (per-shard work is cheap, the paper's
            # tail math is all coordination) — enforcing at the dispatch
            # boundary is what keeps an expired sub-query from occupying
            # a device dispatch slot at all
            deadline.check("local execute")
        idx = self.holder.index(index_name)
        if idx is None:
            raise PQLError(f"index {index_name!r} not found")
        if isinstance(query, str):
            query = parse(query)
        elif isinstance(query, Call):
            query = Query([query])
        return instrument_calls(
            index_name, query.calls,
            lambda call: self._execute_call(idx, call, shards),
        )

    def submit(self, index_name: str, query, shards=None, deadline=None):
        """Pipelined execution: parse, compile, and ENQUEUE each call's
        device program without blocking on the result readback; returns
        one ``Deferred`` per call, resolved on ``.result()``.

        Device streams are ordered, so a serving loop can enqueue a stream
        of queries and resolve them in order — the host↔device round trip
        (the latency floor on tunneled/remote backends) overlaps with
        device compute instead of serializing after it. Pipelined
        reductions sharing a program shape — Count, the BSI aggregates
        Sum/Min/Max, AND TopN's phase-2 recount (candidate lists pad to
        power-of-two buckets so same-field TopN streams share shapes) —
        are additionally coalesced into micro-batched dispatches (see
        _microbatch_enqueue) and stay in flight until resolved. Dense
        single-level GroupBys and row-materializing bitmap calls enqueue
        their programs at submit time with the readback deferred to
        result(); pruned (multi-level) GroupBys defer ALL dispatch to
        result() (each level's readback gates the next level's
        candidates). Remaining call types (writes, host-only reads)
        evaluate eagerly at submit time and return an already-resolved
        Deferred.

        ``deadline`` (qos.Deadline) is enforced at the dispatch boundary:
        an already-expired request raises before any device program is
        enqueued, so a backlogged wave sheds its dead requests instead of
        spending dispatches on answers nobody is waiting for.
        """
        if deadline is not None:
            deadline.check("local submit")
        idx = self.holder.index(index_name)
        if idx is None:
            raise PQLError(f"index {index_name!r} not found")
        if isinstance(query, str):
            query = parse(query)
        elif isinstance(query, Call):
            query = Query([query])
        cost = current_cost()
        if cost is not None and cost.profile is not None:
            # submit-phase work (operand assembly, device enqueue) must
            # land on the SAME ProfileNode the resolve phase uses —
            # node_for is positional, so both phases address one node
            out = []
            for i, call in enumerate(query.calls):
                with use_node(cost, cost.profile.node_for(i, call)):
                    out.append(self._submit_one(idx, call, shards))
            return out
        return [self._submit_one(idx, call, shards) for call in query.calls]

    def _submit_one(self, idx: Index, call: Call, shards=None) -> "Deferred":
        if call.name == "Count":
            return self._submit_count(idx, call, shards, pipeline=True)
        if call.name in ("Sum", "Min", "Max"):
            return self._submit_bsi_aggregate(idx, call, shards,
                                              pipeline=True)
        if call.name == "TopN":
            return self._submit_topn(idx, call, shards, pipeline=True)
        if call.name == "GroupBy":
            return self._submit_groupby(idx, call, shards, pipeline=True)
        if call.name in _BITMAP_CALLS:
            return self._submit_bitmap(idx, call, shards, pipeline=True)
        if call.name == "Options" and call.children:
            # unwrap so the CHILD pipelines (a serving wave of
            # Options-wrapped Counts must coalesce, not evaluate eagerly
            # on the dispatcher); result options apply at resolve time
            inner = self._submit_one(
                idx, options_child(call),
                options_restrict_shards(call, shards),
            )
            return Deferred(
                lambda: apply_options_result(idx, call, inner.result())
            )
        return Deferred(value=self._execute_call(idx, call, shards))

    def _execute_call(self, idx: Index, call: Call, shards=None):
        name = call.name
        if name == "Options":
            return self._execute_options(idx, call, shards)
        if name in ("Set",):
            return self._execute_set(idx, call)
        if name == "Clear":
            return self._execute_clear(idx, call)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call, shards)
        if name == "Store":
            return self._execute_store(idx, call, shards)
        if name == "Count":
            return self._execute_count(idx, call, shards)
        if name == "TopN":
            return self._execute_topn(idx, call, shards)
        if name in ("Sum", "Min", "Max"):
            return self._execute_bsi_aggregate(idx, call, shards)
        if name == "Rows":
            return self._execute_rows(idx, call, shards)
        if name == "GroupBy":
            return self._execute_groupby(idx, call, shards)
        if name == "IncludesColumn":
            return self._execute_includes_column(idx, call, shards)
        if name == "SetRowAttrs":
            return self._execute_set_row_attrs(idx, call)
        if name == "SetColumnAttrs":
            return self._execute_set_column_attrs(idx, call)
        if name in _BITMAP_CALLS:
            return self._execute_bitmap(idx, call, shards)
        raise PQLError(f"unsupported call {name!r}")

    # ------------------------------------------------------ key translation

    def _resolve_key(self, namespace: str, key: str, create: bool):
        """Key → ID. Known keys resolve locally; unknown ones go through
        key_resolver (the coordinator in a cluster — reference: translation
        primary) when wired, else the local store."""
        id_ = self.holder.translate.translate_one(namespace, key, create=False)
        if id_ is not None:
            return id_
        if self.key_resolver is not None:
            return self.key_resolver(namespace, key, create)
        if create:
            return self.holder.translate.translate_one(namespace, key, create=True)
        return None

    def _translate_col(self, idx: Index, col, create: bool = False):
        from pilosa_tpu.storage.translate import column_namespace

        if isinstance(col, int):
            return col
        if not idx.keys:
            raise PQLError(
                f"column key {col!r} on index {idx.name!r} without keys=true"
            )
        return self._resolve_key(column_namespace(idx.name), str(col), create)

    def _translate_row(self, idx: Index, field, row, create: bool = False):
        from pilosa_tpu.storage.translate import row_namespace

        if isinstance(row, int):
            return row
        if not field.options.keys:
            raise PQLError(
                f"row key {row!r} on field {field.name!r} without keys=true"
            )
        return self._resolve_key(
            row_namespace(idx.name, field.name), str(row), create
        )

    def _keys_of(self, namespace: str, ids):
        keys = self.holder.translate.keys_of(namespace, ids)
        if self.key_backfill is not None and any(k is None for k in keys):
            self.key_backfill()
            keys = self.holder.translate.keys_of(namespace, ids)
        return keys

    def _column_keys(self, idx: Index, columns):
        from pilosa_tpu.storage.translate import column_namespace

        return self._keys_of(column_namespace(idx.name), [int(c) for c in columns])

    def _row_keys(self, idx: Index, field, rows):
        from pilosa_tpu.storage.translate import row_namespace

        return self._keys_of(
            row_namespace(idx.name, field.name), [int(r) for r in rows]
        )

    # --------------------------------------------------------------- shards

    def _shards(self, idx: Index, shards=None) -> list[int]:
        if shards is not None:
            return list(shards)
        return idx.available_shards()

    # ------------------------------------------------------ batched mapping
    #
    # One compiled program + one device sync per query (executor/batch.py).
    # Subclasses override the three hooks to change placement/reduction:
    # DistExecutor (parallel/dist.py) shards the stacked leaves over a mesh
    # and swaps the program builders for shard_map+psum versions.

    def _shard_block(self, shard_list: list[int]):
        """Block for a query's shard list, memoized on the LIST OBJECT:
        Index.available_shards returns one memoized list until the shard
        set changes, so steady-state queries reuse one block — skipping
        the per-query sort of (up to) thousands of shard ids, the padded
        layout build, and the cache-key construction. Explicit shard
        lists (Options(shards=)) miss the identity check and build
        fresh, as before."""
        key = id(shard_list)
        entry = self._block_memo.get(key)
        if entry is not None and entry[0] is shard_list:
            self._block_memo.move_to_end(key)
            return entry[1]
        block = self._make_block(shard_list)
        if len(self._block_memo) >= 64:
            # LRU, not wholesale clear: explicit Options(shards=) lists
            # never recur (fresh list object per query) and must not
            # evict the hot available_shards entry when they age out
            self._block_memo.popitem(last=False)
        self._block_memo[key] = (shard_list, block)
        return block

    def _make_block(self, shard_list: list[int]):
        return batch.ShardBlock(shard_list)

    def _leaf_put(self, block):
        """Optional device_put override for stacked leaves (mesh sharding;
        the block supplies the global row count for multi-host feeding)."""
        return None

    def _note_reduce(self, reduce_kind: str, out_shape: tuple,
                     padded: int) -> None:
        """Reduction-lane wire accounting hook, called once per device
        dispatch with the packed result shape and the block's padded
        slot count. Single-device execution has no reduction wire —
        DistExecutor records dense-equivalent vs actual bytes here."""

    def _row_host(self, stacked, block):
        """Row-gather readback hook: device [padded, words] result →
        host array. DistExecutor's hierarchical mesh routes this through
        the roaring wire simulation (parallel/reduction.py)."""
        return np.asarray(stacked)

    def _program(self, structure, reduce_kind: str, leaf_ranks: tuple,
                 n_scalars: int):
        return batch.local_fn(structure, reduce_kind, leaf_ranks, n_scalars)

    def _groupby_level_program(self, filt_structure, n_filt: int,
                               n_scalars: int, n_gather: int, has_agg: bool,
                               quantized: bool = False):
        # single-device execution never quantizes (there is no wire);
        # DistExecutor routes quantized=True pruning levels through the
        # 8-bit ranking lane
        return batch.local_groupby_level_fn(
            filt_structure, n_filt, n_scalars, n_gather, has_agg
        )

    # EQuARX quantized candidate-ranking lane: inert on the base
    # executor (no inter-group wire to shrink); DistExecutor overrides
    # the predicate behind the topn-quantized-ranking knob.
    verify_quantized = False

    def _quant_ranking_active(self) -> bool:
        return False

    def _eval_operands(self, idx: Index, compiled: _Compiled, block,
                       extra_leaves=(), memoize: bool = True):
        """Resolve a compiled query's device leaves; scalars stay host
        ints (converted at dispatch — the micro-batch path ships a whole
        group's scalars as one array).

        Repeat (plan, block) assemblies are memoized for the duration of
        one residency generation: per-leaf cache lookups cost ~10 us of
        lock+LRU bookkeeping per query, which at micro-batched dispatch
        rates is a measurable slice of the serving path's host budget.
        Any write/evict/invalidate bumps the generation (residency.py),
        which eagerly clears the memo (generation listener registered in
        __init__). Correctness does not rest on the clears: every entry
        carries the generation read BEFORE its assembly and a hit must
        match the CURRENT generation, so a racing store of pre-write
        leaves into a just-cleared memo (assembler thread preempted
        across a write) produces an entry that can never be served.
        Identity (`is`) checks guard against id() reuse after
        plan-cache or block-memo eviction. Only plan-cache-resident
        plans (compiled.memoizable) are memoized — per-call plan
        objects (TopN phase 2, const0-degenerate trees) would fill the
        memo with dead-on-arrival entries whose wholesale clear at the
        size bound evicts the hot entries the memo exists for. A hit
        re-touches its leaves' residency LRU position (entry[5]): a
        served-on-every-query leaf must not look LRU-cold and become
        the first eviction victim under pressure."""
        memoize = memoize and not extra_leaves and compiled.memoizable
        if memoize:
            cache = residency.global_row_cache()
            if cache is not self._listened_cache:
                # the global cache can be swapped after construction
                # (Server.open's budget-sized cache); re-home the eager
                # clear listener so evictions on the LIVE cache drop our
                # array references, and dump entries from the old one.
                # Unregister from the old cache first: its bumps would
                # otherwise keep clearing a memo that no longer tracks
                # it, and a swap-back would stack duplicate listeners.
                # Locked double-check: concurrent serving threads racing
                # the swap must not both register.
                with self._rehome_lock:
                    if cache is not self._listened_cache:
                        if self._listened_cache is not None:
                            self._listened_cache.remove_generation_listener(
                                self._clear_operand_memo
                            )
                        cache.add_generation_listener(
                            self._clear_operand_memo
                        )
                        self._listened_cache = cache
                        self._operand_memo.clear()
            gen = cache.generation
            if gen != self._operand_memo_gen:
                self._operand_memo.clear()
                self._operand_memo_gen = gen
            mkey = (id(compiled), id(block))
            hit = self._operand_memo.get(mkey)
            if (hit is not None and hit[0] is compiled
                    and hit[1] is block and hit[4] == gen):
                cache.touch(hit[5])
                self._note_operands(idx, compiled, block, memo_hit=True)
                return hit[2], hit[3]
        put = self._leaf_put(block)
        leaves = self._resolve_leaves(idx, compiled, block, put)
        leaves.extend(extra_leaves)
        if not leaves:
            leaves = [batch.stacked_leaf(idx, _ZeroSpec(), block, put)]
        scalars = tuple(int(s) for s in compiled.scalars)
        if memoize:
            if len(self._operand_memo) >= 512:
                self._operand_memo.clear()
            leaf_keys = batch.leaf_keys(idx, compiled.specs, block)
            self._operand_memo[mkey] = (compiled, block, leaves, scalars,
                                        gen, leaf_keys)
        return leaves, scalars

    def _dispatch(self, node, reduce_kind: str, leaves, scalars):
        import jax.numpy as jnp

        from pilosa_tpu.utils.tracing import global_tracer

        fn = self._program(
            node, reduce_kind, tuple(l.ndim - 1 for l in leaves), len(scalars)
        )
        cost = current_cost()
        with global_tracer().span("device.dispatch", reduce=reduce_kind):
            # same boundaries as the span: enqueue time on the device
            # stream, attributed to the active request/call node
            t0 = time.perf_counter()
            out = fn(*leaves, *(jnp.asarray(s, jnp.int32) for s in scalars))
            if cost is not None:
                cost.note_dispatch(time.perf_counter() - t0)
        self._note_reduce(reduce_kind, out.shape, leaves[0].shape[0])
        return out

    def _resolve_leaves(self, idx: Index, compiled: _Compiled, block,
                        put) -> list:
        """Resolve a plan's stacked device leaves, with cost-plane
        accounting: shard-heat access recording + per-leaf PROFILE
        records (field, cache hit, containers decoded by type, bytes
        uploaded — deltas of the request context around each leaf)."""
        cost = current_cost()
        self._note_operands(idx, compiled, block, memo_hit=False,
                            cost=cost)
        node = (cost.current if cost is not None
                and cost.profile is not None else None)
        if node is None:
            return [batch.stacked_leaf(idx, spec, block, put)
                    for spec in compiled.specs]
        leaves = []
        for spec in compiled.specs:
            snap = (cost.row_cache_hits, cost.c_array, cost.c_bitmap,
                    cost.c_run, cost.device_bytes)
            leaves.append(batch.stacked_leaf(idx, spec, block, put))
            rec = {
                "field": getattr(spec, "field", None),
                "cacheHit": cost.row_cache_hits > snap[0],
                "containers": {"array": cost.c_array - snap[1],
                               "bitmap": cost.c_bitmap - snap[2],
                               "run": cost.c_run - snap[3]},
                "bytesMoved": cost.device_bytes - snap[4],
            }
            row = getattr(spec, "row", None)
            if row is not None:
                rec["row"] = int(row)
            node.leaves.append(rec)
        return leaves

    def _note_operands(self, idx: Index, compiled: _Compiled, block,
                       memo_hit: bool, cost=None) -> None:
        """Request-level accounting for one operand assembly: shards
        touched, operand-memo hit flag, and per-(index, field, shard)
        heat — the admission signal /debug/heatmap serves (storage/
        heat.py). Recorded only inside an active cost context (the
        serving path), so background work cannot skew tenant heat."""
        if cost is None:
            cost = current_cost()
            if cost is None:
                return
        cost.note_shards(len(block.shards))
        if memo_hit and cost.current is not None:
            cost.current.operand_memo_hit = True
        fields = {spec.field for spec in compiled.specs
                  if getattr(spec, "field", None) is not None}
        if fields:
            # one batched heat record per assembly (ONE lock round trip);
            # scope-qualified like every residency key
            global_heat().record_access_many(idx.name, fields,
                                             block.shards,
                                             scope=idx.scope)

    def _batched_eval(self, idx: Index, compiled: _Compiled, block,
                      reduce_kind: str, extra_leaves=()):
        leaves, scalars = self._eval_operands(idx, compiled, block, extra_leaves)
        return self._dispatch(compiled.node, reduce_kind, leaves, scalars)

    # ------------------------------------------------- query micro-batching
    #
    # Pipelined (submit) reductions are coalesced: queries sharing one
    # program shape (structure, reduce kind, operand shapes) accumulate in
    # a pending group and dispatch as ONE device program of
    # ``microbatch_max`` queries (batch.local_fn_batched) — amortizing the
    # fixed per-dispatch launch cost that otherwise rivals the device
    # compute of an entire query, and serving the whole group's results
    # with one [B, ...] readback. A group also flushes when any of its
    # Deferreds resolves, so results are never held hostage. Leaves are
    # captured at submit time: writes between submit and flush patch the
    # residency cache functionally (new arrays), so an in-flight query
    # keeps its snapshot.

    def _microbatch_enqueue(self, node, reduce_kind: str, leaves, scalars):
        """Queue one pipelined query; returns a thunk yielding this
        query's packed host result, or None when micro-batching is off
        (then the caller dispatches per-query)."""
        if self.microbatch_max <= 1:
            return None
        shapes = tuple(tuple(l.shape) for l in leaves)
        key = (node, reduce_kind, shapes, len(scalars))
        with self._mb_lock:
            group = self._pending.get(key)
            if group is None:
                # group size: microbatch_max, capped so the batched
                # program's total PER-DEVICE argument bytes stay under
                # budget (XLA accounts each parameter separately — see
                # MICROBATCH_ARG_BUDGET; mesh-sharded leaves cost
                # nbytes/n_devices per chip)
                per_query = (sum(l.nbytes for l in leaves)
                             // self.arg_shard_factor)
                limit = max(1, min(
                    self.microbatch_max,
                    self.microbatch_arg_budget // max(per_query, 1),
                ))
                # floor to a power of two: the flush pads batches to
                # pow2 sizes, so a non-pow2 cap (budget-derived, e.g. 5)
                # would reintroduce an unbounded program-shape family
                limit = 1 << (limit.bit_length() - 1)
                group = self._pending[key] = {"rows": [], "out": None,
                                              "limit": limit}
            i = len(group["rows"])
            group["rows"].append((tuple(leaves), scalars))
            if len(group["rows"]) >= group["limit"]:
                self._flush_group_locked(key, group)

        def read():
            with self._mb_lock:
                if group["out"] is None:
                    self._flush_group_locked(key, group)
                out = group["out"]
            if not isinstance(out, np.ndarray):
                out = np.asarray(out)  # blocking readback, outside the lock
                with self._mb_lock:
                    group["out"] = out
            return out[i]

        return read

    def _program_batched(self, structure, reduce_kind: str, leaf_ranks: tuple,
                         n_scalars: int, n_queries: int):
        """Micro-batched program builder hook (one program, ``n_queries``
        same-shape queries). DistExecutor swaps in the shard_map+psum
        version so the mesh path keeps micro-batching."""
        return batch.local_fn_batched(structure, reduce_kind, leaf_ranks,
                                      n_scalars, n_queries)

    def _flush_group_locked(self, key, group) -> None:
        """Dispatch a pending group as one program (caller holds _mb_lock).

        The batch axis pads to the next power of two (duplicating the
        last row — same array objects, so no host copies) and readers
        index only the real rows. Without this, a serving wave of K
        concurrent queries dispatches a K-row program for EVERY distinct
        K, and XLA compiles each batch size from scratch — a wave
        pipeline under varied load would spend its time in the compiler.
        Padding bounds the shape family to {1,2,4,8,16} per structure."""
        if group["out"] is not None:
            return
        node, reduce_kind, shapes, n_scalars = key
        rows = group["rows"]
        n_prog = min(group["limit"], next_pow2(len(rows)))
        padded = rows + [rows[-1]] * (n_prog - len(rows))
        fn = self._program_batched(
            node, reduce_kind, tuple(len(s) - 1 for s in shapes),
            n_scalars, n_prog,
        )
        args = [leaf for leaves, _ in padded for leaf in leaves]
        if n_scalars:
            args.append(np.asarray([s for _, s in padded], np.int32))
        from pilosa_tpu.utils.tracing import global_tracer

        # the span lands in the trace of whichever request flushed the
        # group — truthful attribution: that request paid the dispatch,
        # its batchmates ride for free (tagged with the shared size);
        # the cost plane attributes the dispatch the same way
        cost = current_cost()
        with global_tracer().span("device.dispatch", reduce=reduce_kind,
                                  batch=len(rows)):
            if cost is None:
                group["out"] = fn(*args)
            else:
                t0 = time.perf_counter()
                group["out"] = fn(*args)
                cost.note_dispatch(time.perf_counter() - t0,
                                   batch=len(rows))
        self._note_reduce(reduce_kind, group["out"].shape, shapes[0][0])
        if self._pending.get(key) is group:
            del self._pending[key]

    # --------------------------------------------------------- bitmap calls

    def _execute_bitmap(self, idx: Index, call: Call, shards=None) -> RowResult:
        return self._submit_bitmap(idx, call, shards).result()

    def _submit_bitmap(self, idx: Index, call: Call, shards=None,
                       pipeline: bool = False) -> "Deferred":
        """Row-materializing calls: the fused program is enqueued at
        submit time; the [padded, words] readback (the only multi-row
        device→host transfer in the system) happens at result()."""
        compiled = self._compile_cached(idx, call)
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return Deferred(
                value=self._finish_row_result(idx, call, RowResult({}))
            )
        block = self._shard_block(shard_list)
        stacked = self._batched_eval(idx, compiled, block, "row")
        # row attrs snapshot at SUBMIT time, like the bitmap data (a
        # SetRowAttrs between submit and result must not tear the
        # result); column-key translation stays at result() — the
        # translate log is append-only, so ids→keys cannot change
        attrs = self._row_result_attrs(idx, call)

        def finish() -> RowResult:
            host = self._row_host(stacked, block)
            segments = {}
            for i, shard in enumerate(block.shards):
                if host[i].any():
                    # copy: a view would pin the whole padded readback
                    segments[shard] = host[i].copy()
            res = RowResult(segments, attrs=attrs)
            if idx.keys:
                res.keys = [
                    k for k in self._column_keys(idx, res.columns().tolist())
                    if k is not None
                ]
            return res

        if pipeline:
            return Deferred(finish)
        return Deferred(value=finish())

    def _row_result_attrs(self, idx: Index, call: Call) -> dict:
        """Row attrs for a plain Row call (reference: Row results carry
        the row's attribute set)."""
        if call.name == "Row" and call.condition_field()[0] is None:
            try:
                field_name, row = self._row_field_and_value(call)
                field = idx.field(field_name)
                if field is not None and field.row_attrs is not None:
                    row_id = self._translate_row(idx, field, row, create=False)
                    if row_id is not None:
                        return field.row_attrs.attrs(row_id)
            except PQLError:
                pass
        return {}

    def _finish_row_result(self, idx: Index, call: Call, res: RowResult) -> RowResult:
        """Attach row attrs (plain Row calls) and translated column keys."""
        res.attrs = self._row_result_attrs(idx, call) or res.attrs
        if idx.keys:
            res.keys = [
                k for k in self._column_keys(idx, res.columns().tolist())
                if k is not None
            ]
        return res

    def _execute_count(self, idx: Index, call: Call, shards=None) -> int:
        return self._submit_count(idx, call, shards).result()

    def _submit_count(self, idx: Index, call: Call, shards=None,
                      pipeline: bool = False) -> "Deferred":
        if len(call.children) != 1:
            raise PQLError("Count requires exactly one child call")
        compiled = self._compile_cached(idx, call.children[0], wrap="count")
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return Deferred(value=0)
        block = self._shard_block(shard_list)
        return self._submit_reduction(
            idx, compiled, block, "count", pipeline,
            lambda packed: int(batch.merge_split(packed)),
        )

    def _submit_reduction(self, idx: Index, compiled: _Compiled, block,
                          reduce_kind: str, pipeline: bool,
                          finish) -> "Deferred":
        """Shared dispatch tail for pipelined scalar reductions (Count and
        the BSI aggregates): micro-batch same-shape pipelined queries into
        one program, else dispatch per query; ``finish`` maps this query's
        packed host row to its result."""
        if pipeline:
            leaves, scalars = self._eval_operands(idx, compiled, block)
            read = self._microbatch_enqueue(
                compiled.node, reduce_kind, leaves, scalars
            )
            if read is not None:
                return Deferred(lambda: finish(read()))
            packed = self._dispatch(compiled.node, reduce_kind, leaves,
                                    scalars)
        else:
            packed = self._batched_eval(idx, compiled, block, reduce_kind)
        return Deferred(lambda: finish(np.asarray(packed)))

    def includes_target(self, idx: Index, call: Call, shards=None):
        """Resolve IncludesColumn's target: (numeric column, shard), or
        None when the answer is trivially False (unknown column key, or
        an Options(shards=) restriction excluding the column's shard).
        Shared by the single-node and cluster dispatch paths so the
        key/shard semantics cannot drift."""
        col = call.arg("column")
        if col is None:
            raise PQLError("IncludesColumn requires column=")
        if len(call.children) != 1:
            raise PQLError("IncludesColumn requires one child call")
        col = self._translate_col(idx, col, create=False)
        if col is None:
            return None  # unknown column key: not included
        shard = shard_of(col)
        if shards is not None and shard not in shards:
            return None  # Options(shards=) excludes the column's shard
        return col, shard

    def _execute_includes_column(self, idx: Index, call: Call,
                                 shards=None) -> bool:
        target = self.includes_target(idx, call, shards)
        if target is None:
            return False
        col, shard = target
        pos = position(col)
        compiled = self._compile_cached(idx, call.children[0])
        words = np.asarray(compiled.eval(idx, shard))
        return bool((words[pos // 32] >> np.uint32(pos % 32)) & np.uint32(1))

    def _execute_options(self, idx: Index, call: Call, shards=None):
        res = self._execute_call(
            idx, options_child(call), options_restrict_shards(call, shards)
        )
        return apply_options_result(idx, call, res)

    # -------------------------------------------------------------- compile

    def _compile_cached(self, idx: Index, call: Call,
                        wrap: str | None = None,
                        build: Callable | None = None) -> _Compiled:
        """_compile with a plan memo. parse() memoizes query text to one
        immutable Call tree, so the tree's identity keys repeated queries
        — the serving hot path. A cached plan revalidates in two identity
        checks plus one int compare: the Call tree, the Index object (a
        delete_index + recreate under the same name restarts plan_epoch,
        so the epoch alone could alias a stale plan; the index is held
        weakly so the cache never pins a deleted index's bitmaps), and
        the index's schema epoch — bumped on field create/delete, which
        covers every compiled-in field property (views from time quantum,
        BSI base/bit_depth from min/max) since FieldOptions are immutable
        after creation. Plans whose tree degenerated to const0 (e.g. a
        row key unknown at compile time that a later write may create)
        are not cached."""
        key = (idx.name, id(call), wrap)
        entry = self._plan_cache.get(key)
        if entry is not None:
            call_ref, idx_ref, epoch, compiled = entry
            if (call_ref is call and idx_ref() is idx
                    and epoch == idx.plan_epoch):
                cost = current_cost()
                if cost is not None:
                    cost.note_plan(True)
                return compiled
        cost = current_cost()
        if cost is not None:
            cost.note_plan(False)
        # epoch snapshot BEFORE compiling: DDL racing the compile bumps
        # the epoch, so the entry (tagged pre-DDL) fails its next
        # validation instead of serving the stale plan under the new epoch
        epoch = idx.plan_epoch
        compiled = (self._compile(idx, call, wrap=wrap) if build is None
                    else build())
        if not _node_has_const0(compiled.node):
            if len(self._plan_cache) >= self.PLAN_CACHE_MAX:
                self._plan_cache.clear()
            self._plan_cache[key] = (call, weakref.ref(idx), epoch,
                                     compiled)
            compiled.memoizable = True
        return compiled

    def _compile(self, idx: Index, call: Call, wrap: str | None = None) -> _Compiled:
        specs: list = []
        scalars: list = []
        node = self._compile_node(idx, call, specs, scalars)
        if wrap == "count":
            node = ("count", node)
        return _Compiled(node, specs, scalars)

    def _compile_node(self, idx: Index, call: Call, specs, scalars):
        name = call.name
        if name == "Row" or name == "Range":
            return self._compile_row(idx, call, specs, scalars)
        if name in ("Union", "Intersect", "Xor"):
            if not call.children:
                return ("const0",)
            tag = {"Union": "or", "Intersect": "and", "Xor": "xor"}[name]
            node = self._compile_node(idx, call.children[0], specs, scalars)
            for child in call.children[1:]:
                node = (tag, node, self._compile_node(idx, child, specs, scalars))
            return node
        if name == "Difference":
            if not call.children:
                return ("const0",)
            node = self._compile_node(idx, call.children[0], specs, scalars)
            for child in call.children[1:]:
                node = ("diff", node, self._compile_node(idx, child, specs, scalars))
            return node
        if name == "Not":
            if len(call.children) != 1:
                raise PQLError("Not requires exactly one child call")
            exists = self._existence_node(idx, specs)
            return ("diff", exists, self._compile_node(idx, call.children[0], specs, scalars))
        if name == "All":
            return self._existence_node(idx, specs)
        if name == "Shift":
            if len(call.children) != 1:
                raise PQLError("Shift requires exactly one child call")
            n = call.arg("n", 1)
            scalars.append(int(n))
            return (
                "shift",
                self._compile_node(idx, call.children[0], specs, scalars),
                len(scalars) - 1,
            )
        raise PQLError(f"call {name!r} is not a bitmap (row-producing) call")

    def _compile_row(self, idx: Index, call: Call, specs, scalars):
        cond_field, cond = call.condition_field()
        if cond is not None:
            return self._compile_bsi_compare(idx, cond_field, cond, specs, scalars)
        field_name, row = self._row_field_and_value(call)
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        if not isinstance(row, int):
            row = self._translate_row(idx, field, row, create=False)
            if row is None:
                return ("const0",)  # unknown key → empty row
        if row < 0:
            return ("const0",)  # negative rows cannot exist
        views: tuple[str, ...]
        t_from, t_to = call.arg("from"), call.arg("to")
        if t_from is not None or t_to is not None:
            if field.options.type != TYPE_TIME:
                raise PQLError("from/to args require a time field")
            views = tuple(
                views_by_time_range(
                    VIEW_STANDARD,
                    field.options.time_quantum,
                    _parse_time(t_from),
                    _parse_time(t_to),
                )
            )
        else:
            views = (VIEW_STANDARD,)
        specs.append(_RowSpec(field_name, views, row))
        return ("leaf", len(specs) - 1)

    def _compile_bsi_compare(self, idx: Index, field_name: str, cond: Condition,
                             specs, scalars):
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        if field.options.type != TYPE_INT:
            raise PQLError(f"comparison on non-int field {field_name!r}")
        if cond.op == "><":
            lo, hi = cond.value
            if lo > hi:
                return ("const0",)
            ge = self._compile_bsi_compare(
                idx, field_name, Condition(">=", lo), specs, scalars
            )
            le = self._compile_bsi_compare(
                idx, field_name, Condition("<=", hi), specs, scalars
            )
            return ("and", ge, le)

        base = field.options.base
        depth = field.options.bit_depth
        max_stored = (1 << depth) - 1
        value = cond.value
        op = cond.op
        # isinstance check, not float(value): fractional predicates only
        # ever arrive as parser floats, and float(huge_int) overflows
        # where the pred>max_stored clamp below handles it fine.
        if isinstance(value, float) and not value.is_integer():
            # Stored values are integers, so a fractional predicate maps
            # exactly onto the integer lattice: x < 1.5 ⇔ x <= 1,
            # x > 1.5 ⇔ x >= 2, and ==/!= degenerate. Plain int() would
            # turn x < 1.5 into x < 1, wrongly excluding x == 1.
            if op == "==":
                return ("const0",)
            if op == "!=":
                return self._bsi_exists_node(field, specs)
            if math.isinf(value):
                # a ~310+-digit literal with a fractional part parses to
                # ±inf; floor() would raise, so clamp directly
                everything = (value > 0) == (op in ("<", "<="))
                return (self._bsi_exists_node(field, specs) if everything
                        else ("const0",))
            fl = math.floor(value)
            value, op = (fl, "<=") if op in ("<", "<=") else (fl + 1, ">=")
        pred = int(value) - base
        cond = Condition(op, value)
        exists = self._bsi_exists_node(field, specs)
        # range-clamp: out-of-range predicates degenerate to empty/universe
        if pred < 0:
            if cond.op in ("<", "<=", "=="):
                return ("const0",)
            return exists  # >, >=, != of anything stored
        if pred > max_stored:
            if cond.op in (">", ">=", "=="):
                return ("const0",)
            return exists
        planes_i = self._planes_index(field, specs)
        scalars.append(pred)
        return ("bsicmp", cond.op, planes_i, exists, len(scalars) - 1)

    def _planes_index(self, field, specs) -> int:
        for i, s in enumerate(specs):
            if isinstance(s, _PlanesSpec) and s.field == field.name:
                return i
        specs.append(_PlanesSpec(field.name, field.options.bit_depth))
        return len(specs) - 1

    def _bsi_exists_node(self, field, specs):
        specs.append(_RowSpec(field.name, (field.bsi_view_name(),), BSI_EXISTS_ROW))
        return ("leaf", len(specs) - 1)

    def _existence_node(self, idx: Index, specs):
        if not idx.track_existence:
            raise PQLError("Not/All require trackExistence on the index")
        specs.append(_RowSpec(EXISTENCE_FIELD, (VIEW_STANDARD,), 0))
        return ("leaf", len(specs) - 1)

    @staticmethod
    def _row_field_and_value(call: Call):
        for k, v in call.args.items():
            if k not in _RESERVED_ARGS and not isinstance(v, Condition):
                return k, v
        raise PQLError(f"{call.name} requires a field=row argument")

    # ------------------------------------------------------- BSI aggregates

    def _execute_bsi_aggregate(self, idx: Index, call: Call, shards=None) -> ValCount:
        return self._submit_bsi_aggregate(idx, call, shards).result()

    def _submit_bsi_aggregate(self, idx: Index, call: Call, shards=None,
                              pipeline: bool = False) -> "Deferred":
        field_name = call.arg("field") or call.arg("_field")
        if field_name is None:
            raise PQLError(f"{call.name} requires field=")
        field = idx.field(field_name)
        if field is None or field.options.type != TYPE_INT:
            raise PQLError(f"{call.name} requires an int field")
        filt_call = call.children[0] if call.children else None

        def build() -> _Compiled:
            specs: list = []
            scalars: list = []
            planes_i = self._planes_index(field, specs)
            filt_node = (self._compile_node(idx, filt_call, specs, scalars)
                         if filt_call else None)
            if call.name == "Sum":
                node = ("bsisum", planes_i, filt_node)
            else:
                node = ("bsiminmax", 1 if call.name == "Max" else 0,
                        planes_i, filt_node)
            return _Compiled(node, specs, scalars)

        compiled = self._compile_cached(idx, call, wrap="agg", build=build)
        base = field.options.base

        shard_list = self._shards(idx, shards)
        if not shard_list:
            return Deferred(value=ValCount(0, 0))
        block = self._shard_block(shard_list)

        if call.name == "Sum":
            reduce_kind = "bsisum"

            def finish(packed) -> ValCount:
                merged = batch.merge_split(packed)
                # [depth + 1]: plane counts ++ n
                count = int(merged[-1])
                total = sum(int(c) << i
                            for i, c in enumerate(merged[:-1].tolist()))
                return ValCount(total + base * count, count)
        else:
            reduce_kind = "max" if call.name == "Max" else "min"

            def finish(packed) -> ValCount:
                packed = np.asarray(packed)  # [best, count_lo, count_hi]
                best = int(packed[0])
                count = int(batch.merge_split(packed[1:]))
                if count == 0:
                    return ValCount(0, 0)
                return ValCount(best + base, count)

        return self._submit_reduction(
            idx, compiled, block, reduce_kind, pipeline, finish,
        )

    # ----------------------------------------------------------------- TopN

    def _execute_topn(self, idx: Index, call: Call, shards=None) -> list[Pair]:
        return self._submit_topn(idx, call, shards).result()

    def _submit_topn(self, idx: Index, call: Call, shards=None,
                     pipeline: bool = False) -> "Deferred":
        """TopN with a pipelineable phase 2. Phase 1 (ranked-cache
        candidates) is host-only; phase 2 — the exact recount over the
        stacked candidate matrix — is one ``countrows`` device program,
        which under ``submit()`` micro-batches with other pipelined TopNs
        of the same shape (candidate lists pad to the next power of two
        so same-field TopN streams share one program shape)."""
        field_name = call.arg("_field") or call.arg("field")
        if field_name is None:
            raise PQLError("TopN requires a field")
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        n = call.arg("n", 10)
        filt_call = call.children[0] if call.children else None
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return Deferred(value=[])
        view = field.view(VIEW_STANDARD)

        explicit_ids = call.arg("ids")
        if explicit_ids is not None:
            candidates = sorted(int(i) for i in explicit_ids)
        else:
            # phase 1: per-shard candidates from the ranked caches
            overfetch = max(n * TOPN_CANDIDATE_FACTOR, n + 10)
            cand: set[int] = set()
            for shard in shard_list:
                frag = view.fragment(shard) if view else None
                if frag is None:
                    continue
                cand.update(r for r, _ in frag.top(overfetch))
            candidates = sorted(cand)
        candidates = self._filter_topn_candidates(field, call, candidates)
        if not candidates:
            return Deferred(value=[])

        # phase 2: exact recount of every candidate across all shards —
        # countrows programs over stacked candidate matrices. The
        # candidate axis is CHUNKED to the per-device matrix byte budget
        # (a candidate row costs shards×128KiB; see
        # TOPN_MATRIX_BUDGET_BYTES) and each chunk pads to the chunk's
        # power-of-two size with ZERO rows (zeros match no write event,
        # so the residency patch routing stays exact) — so chunks of one
        # query AND pipelined TopN streams bucket into shared shapes and
        # micro-batch together.
        n_real = len(candidates)
        specs: list = []
        scalars: list = []
        filt_node = (
            self._compile_node(idx, filt_call, specs, scalars) if filt_call else None
        )
        node = ("countrows", len(specs), filt_node)
        block = self._shard_block(shard_list)
        bytes_per_cand = (
            block.padded * WORDS_PER_SHARD * 4 // self.arg_shard_factor
        )
        chunk_rows = max(
            1, min(next_pow2(n_real),
                   TOPN_MATRIX_BUDGET_BYTES // max(bytes_per_cand, 1))
        )
        chunk_rows = 1 << (chunk_rows.bit_length() - 1)  # round down to pow2

        # filter leaves/scalars are chunk-invariant: resolve once
        base_leaves, scalar_ints = self._eval_operands(
            idx, _Compiled(node, specs, scalars), block, memoize=False,
        ) if specs else ([], tuple(int(s) for s in scalars))
        put = self._leaf_put(block)

        def dispatch_chunks(cand_list, kind, use_pipeline, rows=None):
            """One (chunk, result thunk) per candidate chunk of `kind`
            (countrows = exact split sums; countrows_q = the quantized
            ranking lane). Chunks pad to ``rows`` (default chunk_rows)
            with ZERO rows — the widened-window recount passes its own
            smaller power-of-two so the exact pass pays for the window,
            not the full candidate set."""
            rows = chunk_rows if rows is None else rows
            chunk_reads = []
            for lo in range(0, len(cand_list), rows):
                chunk = cand_list[lo:lo + rows]
                matrix = batch.stacked_matrix(
                    idx, field_name, view, chunk, block, put,
                    pad_rows=rows - len(chunk),
                )
                leaves = base_leaves + [matrix]
                read = (self._microbatch_enqueue(node, kind, leaves,
                                                 scalar_ints)
                        if use_pipeline else None)
                if read is None:
                    packed = self._dispatch(node, kind, leaves,
                                            scalar_ints)
                    read = (lambda p: lambda: np.asarray(p))(packed)
                chunk_reads.append((chunk, read))
            return chunk_reads

        def exact_totals(cand_list, chunk_reads=None, rows=None):
            """Blocking exact recount: each chunk's packed
            [2, rows] split sums; the slice drops the all-zero pad
            rows (always zero counts)."""
            if chunk_reads is None:
                chunk_reads = dispatch_chunks(
                    cand_list, "countrows", False, rows=rows
                )
            totals: list[int] = []
            for chunk, read in chunk_reads:
                totals.extend(
                    batch.merge_split(np.asarray(read()))[:len(chunk)]
                    .tolist()
                )
            return totals

        def order_pairs(cand_list, totals):
            # threshold= : minimum global count to be included
            # (SURVEY-LOW surface, Appendix B — the upstream arg's exact
            # version gate is unverifiable with the mount empty;
            # conservative reading: a post-recount filter, so it never
            # changes which rows WOULD have qualified, only trims the
            # result). Applied after the exact phase-2 counts; the
            # cluster path strips it from mapped sub-queries and applies
            # it after the cross-node merge.
            floor = max(1, int(call.arg("threshold", 0) or 0))
            order = sorted(
                (int(-c), r)
                for r, c in zip(cand_list, totals) if c >= floor
            )
            if n:
                order = order[:n]
            return order

        # quantized candidate ranking (topn-quantized-ranking): rank ALL
        # candidates over the 8-bit scaled inter-group lane, widen the
        # top-n window by the transmitted error bound (any candidate the
        # perturbed ranking could have misplaced provably stays inside),
        # then recount ONLY the window on the lossless lanes — final
        # pairs are byte-identical to the all-lossless path because they
        # are computed from the same exact counts. ids= queries are
        # already an exact recount (no ranking to approximate), and with
        # n == 0 or nothing to cut the window is the whole set.
        quantized = (self._quant_ranking_active() and explicit_ids is None
                     and bool(n) and n_real > n)

        if quantized:
            from pilosa_tpu.parallel import reduction

            q_reads = dispatch_chunks(candidates, "countrows_q", pipeline)

            def finish_quantized() -> list[Pair]:
                approx = np.zeros(n_real, np.int64)
                err = np.zeros(n_real, np.int64)
                pos = 0
                for chunk, read in q_reads:
                    merged = batch.merge_split(np.asarray(read()))
                    a, e = reduction.split_quantized(merged, chunk_rows)
                    approx[pos:pos + len(chunk)] = a[:len(chunk)]
                    err[pos:pos + len(chunk)] = e[:len(chunk)]
                    pos += len(chunk)
                widx = reduction.quant_topn_window(approx, err, n)
                reduction.global_reduce_stats().note_quant_window(
                    len(widx), n_real
                )
                window = [candidates[i] for i in widx]
                # The recount chunks size to the WINDOW, not the full
                # candidate set — otherwise pad rows hand back the wire
                # bytes the quantized lane just saved.
                wrows = min(
                    chunk_rows, 1 << max(0, len(window) - 1).bit_length()
                ) or 1
                order = order_pairs(
                    window, exact_totals(window, rows=wrows)
                )
                if self.verify_quantized:
                    ref = order_pairs(candidates, exact_totals(candidates))
                    if order != ref:
                        raise AssertionError(
                            "quantized TopN diverged from lossless: "
                            f"{order} != {ref}"
                        )
                return self._finish_pairs(
                    idx, field, [Pair(r, -negc) for negc, r in order]
                )

            return Deferred(finish_quantized)

        reads = dispatch_chunks(candidates, "countrows", pipeline)

        def finish() -> list[Pair]:
            order = order_pairs(candidates, exact_totals(candidates, reads))
            return self._finish_pairs(
                idx, field, [Pair(r, -negc) for negc, r in order]
            )

        return Deferred(finish)

    @staticmethod
    def _filter_topn_candidates(field, call: Call, candidates: list[int]) -> list[int]:
        """TopN(attrName=, attrValue=): keep candidate rows whose attrs
        match (reference TopN attribute filter). One bulk read for the
        whole candidate set — the cross-shard overfetch makes this an
        O(candidates) list, and a per-candidate query loop would pay one
        sqlite round trip each."""
        attr_name = call.arg("attrName")
        if attr_name is None or field.row_attrs is None:
            return candidates
        attr_value = call.arg("attrValue")
        attr_map = field.row_attrs.bulk(candidates) if candidates else {}
        return [
            r for r in candidates
            if attr_map.get(r, {}).get(attr_name) == attr_value
        ]

    def _finish_pairs(self, idx: Index, field, pairs: list[Pair]) -> list[Pair]:
        """Attach row keys to TopN pairs for keyed fields."""
        if field.options.keys and pairs:
            keys = self._row_keys(idx, field, [p.id for p in pairs])
            for p, k in zip(pairs, keys):
                p.key = k
        return pairs

    # ----------------------------------------------------------------- Rows

    def _execute_rows(self, idx: Index, call: Call, shards=None):
        field_name = call.arg("_field") or call.arg("field")
        field = idx.field(field_name) if field_name else None
        like = call.arg("like")
        if like is not None and (field is None or not field.options.keys):
            raise PQLError("Rows(like=) requires a field with keys=true")
        ids = self._rows_ids(idx, call, shards)
        if field is not None and field.options.keys:
            keys = [k for k in self._row_keys(idx, field, ids) if k is not None]
            if like is not None:
                import re

                pattern = re.compile(
                    "^" + ".*".join(re.escape(p) for p in str(like).split("%")) + "$"
                )
                keys = [k for k in keys if pattern.match(k)]
            return keys
        return ids

    def _rows_ids(self, idx: Index, call: Call, shards=None) -> list[int]:
        field_name = call.arg("_field") or call.arg("field")
        if field_name is None:
            raise PQLError("Rows requires a field")
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        limit = call.arg("limit", 0)
        previous = call.arg("previous")
        column = call.arg("column")
        view = field.view(VIEW_STANDARD)
        if view is None:
            return []
        rows: set[int] = set()
        if column is not None:
            shard = shard_of(int(column))
            pos = position(int(column))
            frag = view.fragment(shard)
            if frag is not None:
                rows.update(frag.rows_containing(pos))
        else:
            # one O(#containers) metadata pass per fragment — exact
            # non-empty rows with no per-row count loop (fragment.row_counts)
            for shard in self._shards(idx, shards):
                frag = view.fragment(shard)
                if frag is not None:
                    rows.update(frag.row_counts()[0].tolist())
        out = sorted(rows)
        if previous is not None:
            out = [r for r in out if r > int(previous)]
        if limit:
            out = out[: int(limit)]
        return out

    # -------------------------------------------------------------- GroupBy

    def _groupby_prelude(self, idx: Index, call: Call, shards=None):
        """Shared GroupBy argument parsing/validation: returns
        (limit, filter call|None, aggregate int field|None, dims, having
        predicate|None) where dims is [(field_name, row_ids), ...]; dims
        is empty when any dimension has no rows (→ empty result)."""
        if not call.children or any(c.name != "Rows" for c in call.children):
            raise PQLError("GroupBy requires Rows(...) children")
        limit = call.arg("limit", 0)
        filt_call = call.arg("filter")
        if not isinstance(filt_call, Call):
            filt_call = None

        # aggregate=Sum(field=...) (reference GroupBy aggregate, v1.4+)
        agg_call = call.arg("aggregate")
        agg_field = None
        if isinstance(agg_call, Call):
            if agg_call.name != "Sum":
                raise PQLError("GroupBy aggregate supports only Sum(...)")
            agg_name = agg_call.arg("field") or agg_call.arg("_field")
            agg_field = idx.field(agg_name) if agg_name else None
            if agg_field is None or agg_field.options.type != TYPE_INT:
                raise PQLError("GroupBy aggregate requires an int field")

        # build having= eagerly (before the possibly-empty dims early
        # return) so a malformed condition errors even on empty results
        having = having_predicate(call, has_agg=agg_field is not None)

        dims = []
        for child in call.children:
            fname = child.arg("_field") or child.arg("field")
            row_ids = self._rows_ids(idx, child, shards)
            if not row_ids:
                return limit, filt_call, agg_field, [], having
            dims.append((fname, row_ids))
        return limit, filt_call, agg_field, dims, having

    def _groupby_result(
        self, idx: Index, dims, counts: dict, sums: dict, agg_field, limit,
        having=None,
    ) -> list[GroupCount]:
        """Shared GroupBy result construction: rowID→rowKey translation for
        keyed dimension fields (reference GroupBy FieldRow carries RowKey
        when the field has keys), having filter, ordering, limit."""
        if having is not None:
            counts = {
                k: c for k, c in counts.items() if having(c, sums.get(k))
            }
        dim_keys: list[dict[int, str] | None] = []
        for fname, row_ids in dims:
            field = idx.field(fname)
            if field is not None and field.options.keys:
                translated = self._row_keys(idx, field, row_ids)
                dim_keys.append(dict(zip(row_ids, translated)))
            else:
                dim_keys.append(None)

        def field_row(i: int, row: int) -> dict:
            keys = dim_keys[i]
            if keys is not None and keys.get(row) is not None:
                return {"field": dims[i][0], "rowKey": keys[row]}
            return {"field": dims[i][0], "rowID": row}

        # Order by the emitted representation — numeric rowIDs first
        # (numerically), then rowKeys (lexicographically) — so every
        # execution path (single-node, SPMD, cluster merge) agrees on
        # ordering and limit truncation.
        def order(key: tuple) -> tuple:
            return tuple(
                (1, keys[row]) if (keys := dim_keys[i]) is not None
                and keys.get(row) is not None else (0, row)
                for i, row in enumerate(key)
            )

        out = [
            GroupCount(
                [field_row(i, row) for i, row in enumerate(key)],
                c,
                sum=sums.get(key) if agg_field is not None else None,
            )
            for key, c in sorted(counts.items(), key=lambda kv: order(kv[0]))
        ]
        if limit:
            out = out[: int(limit)]
        return out

    def _execute_groupby(self, idx: Index, call: Call, shards=None) -> list[GroupCount]:
        return self._submit_groupby(idx, call, shards).result()

    def _submit_groupby(self, idx: Index, call: Call, shards=None,
                        pipeline: bool = False) -> "Deferred":
        """GroupBy as batched device programs with level pruning.

        The reference recurses per shard over the dimension cross-product,
        pruning prefixes whose intersection is empty
        (executor.executeGroupByShard). Here each prefix level is ONE
        batched program — candidate prefixes are gathered out of the
        stacked dimension matrices, counted per shard, and reduced on
        device — so the whole GroupBy costs one device sync per dimension
        (and exactly one when the cross-product is small enough to skip
        pruning). Chunking inside a level is byte-budgeted
        (batch.GROUPBY_MASK_BUDGET_BYTES) so the dense group masks never
        outgrow HBM.

        Pipelined (submit): the common dense single-level case enqueues
        its level program WITHOUT the blocking readback — the host sync
        moves into ``Deferred.result()``, overlapping the round trip
        with whatever the serving loop enqueues next. The pruning path
        needs a readback per level to choose the next level's
        candidates, so it defers the whole evaluation to ``result()``.
        """
        limit, filt_call, agg_field, dims, having = self._groupby_prelude(
            idx, call, shards
        )
        if not dims:
            return Deferred(value=[])
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return Deferred(value=[])

        specs: list = []
        scalars: list = []
        filt_node = (
            self._compile_node(idx, filt_call, specs, scalars)
            if filt_call is not None
            else None
        )
        block = self._shard_block(shard_list)
        put = self._leaf_put(block)
        filt_leaves = [batch.stacked_leaf(idx, s, block, put) for s in specs]
        dim_mats = []
        for fname, row_ids in dims:
            field = idx.field(fname)
            view = field.view(VIEW_STANDARD) if field else None
            dim_mats.append(
                batch.stacked_matrix(idx, fname, view, row_ids, block, put)
            )
        planes = (
            batch.stacked_leaf(
                idx,
                _PlanesSpec(agg_field.name, agg_field.options.bit_depth),
                block, put,
            )
            if agg_field is not None
            else None
        )

        sizes = [len(row_ids) for _, row_ids in dims]
        total_groups = 1
        for n in sizes:
            total_groups *= n

        def collect(cand, counts_arr, agg_arrs) -> list[GroupCount]:
            counts: dict[tuple, int] = {}
            sums: dict[tuple, int] = {}
            base = agg_field.options.base if agg_field is not None else 0
            for j in range(cand.shape[0]):
                c = int(counts_arr[j])
                if c <= 0:
                    continue
                gkey = tuple(
                    dims[d][1][int(cand[j, d])] for d in range(cand.shape[1])
                )
                counts[gkey] = c
                if agg_arrs is not None:
                    n = int(agg_arrs[0][j])
                    pc = agg_arrs[1][:, j].tolist()
                    sums[gkey] = (
                        sum(int(v) << b for b, v in enumerate(pc)) + base * n
                    )
            return self._groupby_result(
                idx, dims, counts, sums, agg_field, limit, having=having,
            )

        if total_groups <= GROUPBY_DENSE_MAX_GROUPS:
            # small cross-product: every group in one level; the level
            # program is enqueued NOW, the readback waits for result()
            cand = np.zeros((1, 0), np.int32)
            for n in sizes:
                cand = _index_cross(cand, n)
            packed, layout = self._groupby_level_enqueue(
                block, filt_leaves, filt_node, scalars, dim_mats, cand,
                planes, agg_field,
            )
            has_agg = planes is not None
            depth = agg_field.options.bit_depth if has_agg else 0

            def finish() -> list[GroupCount]:
                counts_arr, agg_arrs = _groupby_level_unpack(
                    np.asarray(packed), layout, cand.shape[0], has_agg,
                    depth,
                )
                return collect(cand, counts_arr, agg_arrs)

            if pipeline:
                return Deferred(finish)
            return Deferred(value=finish())

        def run_pruned() -> list[GroupCount]:
            # prefix pruning: extend one dimension at a time, dropping
            # empty prefixes after each level (AND only shrinks groups);
            # each level's readback gates the next level's candidates.
            # With quantized ranking on, NON-final levels count over the
            # 8-bit lane and keep any candidate whose count+bound could
            # be nonzero (zero quantizes exactly to zero, so a true
            # survivor can never be pruned); the final level is always
            # lossless, so reported counts — and therefore results —
            # stay byte-identical.
            quant = self._quant_ranking_active()
            cand = np.zeros((1, 0), np.int32)
            counts_arr, agg_arrs = None, None
            for k in range(len(dims)):
                cand = _index_cross(cand, sizes[k])
                last = k == len(dims) - 1
                counts_arr, agg_arrs = self._groupby_eval_level(
                    block, filt_leaves, filt_node, scalars,
                    dim_mats[: k + 1], cand,
                    planes if last else None,
                    agg_field if last else None,
                    quantized=quant and not last,
                )
                keep = counts_arr > 0
                cand = cand[keep]
                counts_arr = counts_arr[keep]
                if agg_arrs is not None:
                    agg_arrs = (agg_arrs[0][keep], agg_arrs[1][:, keep])
                if cand.shape[0] == 0:
                    return []
            return collect(cand, counts_arr, agg_arrs)

        if pipeline:
            return Deferred(run_pruned)
        return Deferred(value=run_pruned())

    def _groupby_eval_level(self, block, filt_leaves, filt_node,
                            scalars, dim_mats, cand: np.ndarray, planes,
                            agg_field, quantized: bool = False):
        """Evaluate one pruning level: enqueue + blocking readback.
        ``quantized`` levels return per-candidate count UPPER BOUNDS
        (approx + error bound) — valid only for gating survival, never
        for reported counts."""
        packed, layout = self._groupby_level_enqueue(
            block, filt_leaves, filt_node, scalars, dim_mats, cand,
            planes, agg_field, quantized=quantized,
        )
        has_agg = planes is not None
        depth = agg_field.options.bit_depth if has_agg else 0
        return _groupby_level_unpack(
            np.asarray(packed), layout, cand.shape[0], has_agg, depth,
            quantized=quantized,
        )

    def _groupby_level_enqueue(self, block, filt_leaves, filt_node,
                               scalars, dim_mats, cand: np.ndarray, planes,
                               agg_field, quantized: bool = False):
        """Dispatch one level's per-candidate counts (plus BSI aggregate
        partials on the final level), chunked to the mask byte budget,
        all chunks concatenated on device. Returns (device packed array,
        chunk layout) — no host sync."""
        import jax.numpy as jnp

        n_gather = len(dim_mats)
        has_agg = planes is not None
        depth = agg_field.options.bit_depth if has_agg else 0
        c_total = cand.shape[0]
        chunk = batch.groupby_chunk_groups(block, n_gather, depth)
        if quantized and has_agg:
            raise AssertionError(
                "quantized GroupBy levels never carry aggregates "
                "(the final level is always lossless)"
            )
        fn = self._groupby_level_program(
            filt_node, len(filt_leaves), len(scalars), n_gather, has_agg,
            quantized=quantized,
        )
        jscalars = tuple(jnp.asarray(s, jnp.int32) for s in scalars)

        packs = []
        layout = []  # (padded, actual) per chunk
        for lo in range(0, c_total, chunk):
            ci = cand[lo: lo + chunk]
            actual = ci.shape[0]
            padded = min(chunk, next_pow2(actual))
            if padded > actual:
                ci = np.concatenate(
                    [ci, np.zeros((padded - actual, n_gather), np.int32)]
                )
            idx_arrays = tuple(
                jnp.asarray(ci[:, d], jnp.int32) for d in range(n_gather)
            )
            args = list(filt_leaves) + list(dim_mats)
            if has_agg:
                args.append(planes)
            args.extend(idx_arrays)
            packs.append(fn(*args, *jscalars))
            self._note_reduce("groupby_q" if quantized else "groupby",
                              packs[-1].shape, block.padded)
            layout.append((padded, actual))

        packed = jnp.concatenate(packs) if len(packs) > 1 else packs[0]
        return packed, layout

    # ---------------------------------------------------------------- writes

    def _execute_set(self, idx: Index, call: Call) -> bool:
        col = call.arg("_col")
        if col is None:
            raise PQLError("Set requires a column")
        col = self._translate_col(idx, col, create=True)
        if col < 0:
            raise PQLError(f"column {col} is negative")
        field_name, row = self._row_field_and_value(call)
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        if field.options.type == TYPE_INT:
            try:
                changed = field.set_value(col, int(row))
            except ValueError as e:
                raise PQLError(str(e)) from e
        else:
            row = self._translate_row(idx, field, row, create=True)
            _check_row(row)
            ts = call.arg("timestamp")
            timestamp = _parse_time(ts) if ts is not None else None
            changed = field.set_bit(int(row), col, timestamp=timestamp)
        idx.mark_columns_exist([col])
        return changed

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        col = call.arg("_col")
        if col is None:
            raise PQLError("Clear requires a column")
        col = self._translate_col(idx, col, create=False)
        if col is None:
            return False  # unknown column key: nothing to clear
        if col < 0:
            raise PQLError(f"column {col} is negative")
        field_name, row = self._row_field_and_value(call)
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        if field.options.type == TYPE_INT:
            return field.clear_value(col)
        row = self._translate_row(idx, field, row, create=False)
        if row is None:
            return False
        _check_row(row)
        return field.clear_bit(int(row), col)

    def _execute_clear_row(self, idx: Index, call: Call, shards=None) -> bool:
        field_name, row = self._row_field_and_value(call)
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        row = self._translate_row(idx, field, row, create=False)
        if row is None:
            return False  # unknown row key: nothing to clear
        _check_row(row)
        view = field.view(VIEW_STANDARD)
        changed = False
        if view is not None:
            for shard in self._shards(idx, shards):
                frag = view.fragment(shard)
                if frag is not None:
                    changed |= frag.clear_row(int(row)) > 0
        return changed

    def _execute_set_row_attrs(self, idx: Index, call: Call) -> None:
        """SetRowAttrs(field, rowID, attr=value, ...) — reference
        executor.executeSetRowAttrs (SURVEY.md §2 #12)."""
        field_name = call.arg("_field")
        if field_name is None:
            raise PQLError("SetRowAttrs requires a field")
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        row = call.arg("_col")
        if row is None:
            raise PQLError("SetRowAttrs requires a row id")
        row = self._translate_row(idx, field, row, create=True)
        attrs = _attr_args(call)
        # the field-name arg can collide with an attr key; the reference
        # disambiguates by position — we've already consumed _field
        field.row_attrs.set_attrs(int(row), attrs)
        return None

    def _execute_set_column_attrs(self, idx: Index, call: Call) -> None:
        col = call.arg("_col")
        if col is None:
            raise PQLError("SetColumnAttrs requires a column id")
        col = self._translate_col(idx, col, create=True)
        idx.column_attrs.set_attrs(int(col), _attr_args(call))
        return None

    def _execute_store(self, idx: Index, call: Call, shards=None) -> bool:
        if len(call.children) != 1:
            raise PQLError("Store requires one child call")
        field_name, row = self._row_field_and_value(call)
        field = idx.field(field_name)
        if field is None:
            # validate BEFORE the implicit create so a rejected query
            # leaves no phantom field behind (an implicitly created
            # field has keys=false, so a string row can never translate)
            _check_row(row)
            field = idx.create_field(field_name)
        else:
            row = self._translate_row(idx, field, row, create=True)
            _check_row(row)
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return True
        compiled = self._compile_cached(idx, call.children[0])
        block = self._shard_block(shard_list)
        host = np.asarray(self._batched_eval(idx, compiled, block, "row"))
        for i, shard in enumerate(block.shards):
            frag = field.view(VIEW_STANDARD, create=True).fragment(shard, create=True)
            frag.write_row_words(int(row), host[i])
        return True


def _groupby_level_unpack(host: np.ndarray, layout, c_total: int,
                          has_agg: bool, depth: int,
                          quantized: bool = False):
    """Unpack a level's concatenated chunk sections (host side):
    per-candidate counts, plus (n, plane counts) with an aggregate.
    ``quantized`` sections are [2·(padded+blocks)] ranking-lane packs;
    the returned counts are approx + error bound — an UPPER bound that
    only ever gates pruning survival."""
    if quantized:
        from pilosa_tpu.parallel import reduction

        counts = np.zeros(c_total, np.int64)
        off = out_off = 0
        for padded, actual in layout:
            width = reduction.quant_total_elems(padded)
            merged = batch.merge_split(
                host[off:off + 2 * width].reshape(2, width)
            )
            approx, err = reduction.split_quantized(merged, padded)
            counts[out_off:out_off + actual] = (approx + err)[:actual]
            off += 2 * width
            out_off += actual
        return counts, None

    def take2(off: int, n: int, padded: int) -> np.ndarray:
        """Merge one split-sum section [2·padded] → int64[n]."""
        return batch.merge_split(
            host[off:off + 2 * padded].reshape(2, padded)[:, :n]
        )

    counts = np.zeros(c_total, np.int64)
    n_g = np.zeros(c_total, np.int64) if has_agg else None
    pc = np.zeros((depth, c_total), np.int64) if has_agg else None
    off = out_off = 0
    for padded, actual in layout:
        counts[out_off:out_off + actual] = take2(off, actual, padded)
        if has_agg:
            n_g[out_off:out_off + actual] = take2(
                off + 2 * padded, actual, padded
            )
            pc_flat = host[off + 4 * padded:off + (4 + 2 * depth) * padded]
            pc[:, out_off:out_off + actual] = batch.merge_split(
                pc_flat.reshape(2, depth, padded)[:, :, :actual]
            )
            off += (4 + 2 * depth) * padded
        else:
            off += 2 * padded
        out_off += actual
    return counts, (n_g, pc) if has_agg else None


def options_child(call: Call) -> Call:
    """Validate and return an Options() call's single child."""
    if len(call.children) != 1:
        raise PQLError("Options requires one child call")
    return call.children[0]


def options_restrict_shards(call: Call, shards):
    """Apply Options(shards=) to an engine-supplied shard list. The two
    INTERSECT: an engine list (a remote sub-query's per-node assignment,
    or a request-level ?shards= param) must never be widened by the
    user restriction — overriding it would make every replica evaluate
    the full user set and double-count in the cross-node merge. Shared
    by the single-node executor and the cluster layer so the semantics
    cannot drift."""
    opt = call.arg("shards")
    if opt is None:
        return shards
    opt = sorted({int(s) for s in opt})  # dedup: each shard counts once
    return opt if shards is None else sorted(set(opt) & set(shards))


def apply_options_result(idx: Index, call: Call, res):
    """The result-side tail of Options(): columnAttrs / excludeColumns
    on row-materializing results (applied after any cross-node merge)."""
    if isinstance(res, RowResult):
        if call.arg("columnAttrs"):
            res.column_attrs = column_attr_sets(idx, res)
        if call.arg("excludeColumns"):
            return strip_columns(res)
    return res


def column_attr_sets(idx: Index, res: RowResult) -> list[dict]:
    """columnAttrs option output: one bulk attr-store read for the
    result's columns (shared by PQL Options() and the request-level URL
    param so the two spellings cannot drift)."""
    cols = res.columns().tolist()
    attr_map = idx.column_attrs.bulk(cols) if cols else {}
    return [{"id": c, "attrs": attr_map[c]} for c in cols if c in attr_map]


def strip_columns(res: RowResult) -> RowResult:
    """excludeColumns option: drop the column identities (translated keys
    included — they ARE the columns on a keyed index) while keeping row
    attrs and any computed columnAttrs. Shared by PQL Options() and the
    request-level URL param."""
    out = RowResult({}, attrs=res.attrs,
                    keys=[] if res.keys is not None else None)
    out.column_attrs = res.column_attrs
    return out


def _condition_value(v):
    """Numeric coercion for Condition thresholds: int and float pass
    through untruncated (``count < 1.5`` must keep count==1 groups —
    int(1.5) → ``< 1`` would drop them), quoted numerics parse, junk
    raises PQLError (→ HTTP 400) instead of a bare TypeError."""
    if isinstance(v, (int, float)):
        return v
    try:
        return int(v)
    except (TypeError, ValueError):
        try:
            return float(v)
        except (TypeError, ValueError):
            raise PQLError(
                f"condition value {v!r} is not numeric"
            ) from None


def condition_test(cond: Condition, val: int) -> bool:
    """Evaluate a PQL Condition against a scalar (having= filters)."""
    if cond.op == "><":
        lo, hi = cond.value
        return _condition_value(lo) <= val <= _condition_value(hi)
    ref = _condition_value(cond.value)
    return {
        "<": val < ref, "<=": val <= ref, ">": val > ref, ">=": val >= ref,
        "==": val == ref, "!=": val != ref,
    }[cond.op]


def having_predicate(call: Call, has_agg: bool):
    """GroupBy(having=Condition(count > N)) / Condition(sum > N).

    SURVEY-LOW surface (Appendix B: exact upstream version gate
    unverifiable with the mount empty). Conservative reading implemented:
    exactly one condition on ``count`` or ``sum``, applied to fully
    merged groups BEFORE limit truncation — so having trims groups, never
    changes their counts, and a sum condition requires
    aggregate=Sum(...). Returns ``pred(count, sum) -> bool`` or None.
    """
    having = call.arg("having")
    if having is None:
        return None
    if not isinstance(having, Call) or having.name != "Condition":
        raise PQLError("having= requires Condition(count/sum <op> value)")
    conds = [(k, v) for k, v in having.args.items()
             if isinstance(v, Condition)]
    if len(conds) != 1 or conds[0][0] not in ("count", "sum"):
        raise PQLError(
            "having= supports exactly one condition on count or sum"
        )
    subject, cond = conds[0]
    if subject == "sum" and not has_agg:
        raise PQLError("having on sum requires aggregate=Sum(...)")

    def pred(count: int, sum_) -> bool:
        val = count if subject == "count" else int(sum_ or 0)
        return condition_test(cond, val)

    return pred


def _attr_args(call: Call) -> dict:
    """Named args of an attrs call, excluding reserved/positional ones."""
    return {
        k: v for k, v in call.args.items() if k not in _RESERVED_ARGS
    }


_BITMAP_CALLS = {
    "Row", "Union", "Intersect", "Difference", "Xor", "Not", "All", "Shift",
    "Range",
}

# Call types whose submit() ENQUEUES device work without blocking —
# the only ones a serving pipeline should coalesce. Everything else
# (Rows and other host-eager reads) evaluates fully inside submit(), so
# routing it through a single dispatcher thread would serialize work
# that N handler threads previously overlapped.
_PIPELINED_CALLS = (
    {"Count", "Sum", "Min", "Max", "TopN", "GroupBy"} | _BITMAP_CALLS
)


def pipeline_coalescable(query) -> bool:
    """True when every call in the query micro-batches under submit()
    (Options unwraps to its child for the purpose)."""
    def one(call) -> bool:
        if call.name == "Options":
            return bool(call.children) and one(call.children[0])
        return call.name in _PIPELINED_CALLS

    calls = getattr(query, "calls", None)
    return calls is not None and all(one(c) for c in calls)


def _index_cross(cand: np.ndarray, n: int) -> np.ndarray:
    """Extend candidate index tuples [P, k] by every index of the next
    dimension → [P·n, k+1]."""
    p = cand.shape[0]
    left = np.repeat(cand, n, axis=0)
    right = np.tile(np.arange(n, dtype=np.int32), p)[:, None]
    return np.concatenate([left, right], axis=1)


def _check_row(row) -> None:
    if not isinstance(row, int):
        raise PQLError(f"row key {row!r} requires key translation (field keys)")
    if row < 0:
        raise PQLError(f"row {row} is negative")


def _parse_time(value) -> dt.datetime:
    if isinstance(value, dt.datetime):
        return value
    return dt.datetime.fromisoformat(str(value))
