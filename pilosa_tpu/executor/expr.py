"""Fused bitmap-expression compiler.

A PQL bitmap call tree is lowered to a *structure* — nested hashable
tuples with leaf indices — and each distinct structure is traced+compiled
once (module-level cache). Evaluation takes (leaves, scalars) where leaves
are device-resident uint32 rows / BSI plane matrices and scalars are
query-time integers (shift amounts, BSI predicates), so re-running the
same query shape with different rows or predicates reuses the compiled
kernel.

This is the TPU replacement for the reference's per-container op dispatch
(executor.go executeBitmapCallShard over roaring containers — SURVEY.md
§3.2): XLA fuses the entire tree into one HBM pass, including the final
popcount for Count.

Node grammar (structure tuples):
  ('leaf', i)                     — uint32[words] row leaf
  ('const0',)                     — empty row
  ('and'|'or'|'xor'|'diff', a, b)
  ('flipall', a)                  — bitwise NOT over the full shard width
  ('shift', a, j)                 — shift by scalars[j]
  ('bsicmp', op, i_planes, i_exists_leaf, j_pred) — BSI comparison row
  ('count', a)                    — int32 scalar popcount reduction
  ('countrows', i_matrix, a|None) — int32[rows] popcount per matrix row,
                                    optionally masked by bitmap node a
  ('bsisum', i_planes, a|None)    — (int32[depth] plane counts, int32 n)
  ('bsiminmax', want_max, i_planes, a|None) — (value, count)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_U32 = jnp.uint32

# BSI plane-matrix row layout (matches storage.field BSI_* constants).
PLANES_EXISTS = 0
PLANES_SIGN = 1
PLANES_OFFSET = 2

_JIT_CACHE: dict = {}


def evaluate(structure, leaves, scalars):
    """Run a structure against device leaves; compiled once per structure."""
    fn = _JIT_CACHE.get(structure)
    if fn is None:
        fn = _build(structure)
        _JIT_CACHE[structure] = fn
    return fn(tuple(leaves), tuple(jnp.asarray(s, jnp.int32) for s in scalars))


def _build(structure):
    def eval_fn(leaves, scalars):
        return _go(structure, leaves, scalars)

    return jax.jit(eval_fn)


def _go(node, leaves, scalars):
    tag = node[0]
    if tag == "leaf":
        return leaves[node[1]]
    if tag == "const0":
        return jnp.zeros_like(leaves[0]) if leaves else jnp.zeros(0, _U32)
    if tag == "and":
        return _go(node[1], leaves, scalars) & _go(node[2], leaves, scalars)
    if tag == "or":
        return _go(node[1], leaves, scalars) | _go(node[2], leaves, scalars)
    if tag == "xor":
        return _go(node[1], leaves, scalars) ^ _go(node[2], leaves, scalars)
    if tag == "diff":
        return _go(node[1], leaves, scalars) & ~_go(node[2], leaves, scalars)
    if tag == "flipall":
        return ~_go(node[1], leaves, scalars)
    if tag == "shift":
        from pilosa_tpu.ops.bitops import shift

        # inline the shift body so it fuses with the rest of the tree
        return shift.__wrapped__(_go(node[1], leaves, scalars), scalars[node[2]])
    if tag == "count":
        sub = _go(node[1], leaves, scalars)
        return jnp.sum(lax.population_count(sub).astype(jnp.int32))
    if tag == "countrows":
        matrix = leaves[node[1]]
        if node[2] is not None:
            matrix = matrix & _go(node[2], leaves, scalars)[None, :]
        return jnp.sum(lax.population_count(matrix).astype(jnp.int32), axis=-1)
    if tag == "bsicmp":
        return _bsi_compare(
            node[1], leaves[node[2]], _go(node[3], leaves, scalars),
            scalars[node[4]],
        )
    if tag == "bsisum":
        planes = leaves[node[1]]
        filt = planes[PLANES_EXISTS]
        if node[2] is not None:
            filt = filt & _go(node[2], leaves, scalars)
        bits = planes[PLANES_OFFSET:] & filt[None, :]
        plane_counts = jnp.sum(lax.population_count(bits).astype(jnp.int32), axis=-1)
        n = jnp.sum(lax.population_count(filt).astype(jnp.int32))
        return plane_counts, n
    if tag == "bsiminmax":
        planes = leaves[node[2]]
        filt = planes[PLANES_EXISTS]
        if node[3] is not None:
            filt = filt & _go(node[3], leaves, scalars)
        return _bsi_minmax(bool(node[1]), planes, filt)
    raise ValueError(f"unknown expr node {tag!r}")


def _bsi_compare(op: str, planes, exists, pred):
    """BSI comparison against a traced predicate (classic O(depth)
    bit-sliced algorithm, vectorized over the whole shard row).

    planes: uint32[2+depth, words] (exists, sign, bit 0 … LSB-first).
    pred is the *offset-encoded* predicate (executor subtracts the base and
    range-clamps before calling).
    """
    depth = planes.shape[0] - PLANES_OFFSET
    zeros = jnp.zeros_like(exists)
    eq, lt, gt = exists, zeros, zeros
    for i in reversed(range(depth)):
        p = planes[PLANES_OFFSET + i]
        bit = (pred >> i) & 1
        is1 = (bit == 1)
        lt = lt | jnp.where(is1, eq & ~p, zeros)
        gt = gt | jnp.where(is1, zeros, eq & p)
        eq = eq & jnp.where(is1, p, ~p)
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return gt
    if op == ">=":
        return gt | eq
    if op == "==":
        return eq
    if op == "!=":
        return exists & ~eq
    raise ValueError(f"bad bsi op {op!r}")


def _bsi_minmax(want_max: bool, planes, candidates):
    """Greedy MSB-first walk: returns (offset-encoded extremum, count).

    count == 0 means no candidates (executor reports null).
    """
    depth = planes.shape[0] - PLANES_OFFSET
    value = jnp.int32(0)
    for i in reversed(range(depth)):
        p = planes[PLANES_OFFSET + i]
        t = candidates & (p if want_max else ~p)
        nonempty = jnp.any(t != 0)
        candidates = jnp.where(nonempty, t, candidates)
        if want_max:
            bit = nonempty.astype(jnp.int32)
        else:
            # for min, picking ~p means the bit is 0; forced to 1 only when
            # no candidate has a 0 in this plane
            bit = jnp.logical_not(nonempty).astype(jnp.int32)
        value = value | (bit << i)
    n = jnp.sum(lax.population_count(candidates).astype(jnp.int32))
    return value, n
