"""Batched shard evaluation: one compiled program, one device sync per query.

The reference executor maps shards with a goroutine pool and reduces
partials on the host (executor.go mapReduce — SURVEY.md §3.2). A literal
translation — one device dispatch + one host readback per shard — is
hostile to TPU serving: a blocking device→host sync costs a full
host↔device round trip, so per-shard syncs put the query floor at
O(shards × RTT). Here the whole map+reduce phase is ONE XLA program over
stacked leaves ``uint32[n_shards, ...]`` (vmapped per shard, reduced on
device) and exactly ONE packed result array crosses back to the host.

Leaves are built once per (query leaf, shard set) and cached in device
HBM via the residency LRU (storage.residency), so steady-state queries
touch the host only for the final packed result. Writes are routed to
resident leaves as in-place device scatter patches (see the
cached-stacked-leaves section below) rather than evicting them.

``ShardBlock`` is the local (single-device) layout; parallel.mesh's
``ShardAssignment`` extends it with mesh padding, and parallel.dist swaps
the program builder for shard_map+psum versions of the same reductions.

Reduce kinds and their packed results (all int32 unless noted):
  'count'     → [2]: split-sum scalar (see below)
  'countrows' → [2, n_rows] split sums
  'bsisum'    → [2, depth + 1]: per-plane popcount split sums ++ [n]
  'min'/'max' → [3]: [offset-encoded extremum, count_lo, count_hi]
                (count==0 → empty)
  'row'       → uint32[n_shards_padded, words] (stays dense; the only
                multi-row readback)

Split sums: device accumulators are int32 (no x64), and a per-shard
popcount can reach 2^20, so a plain int32 sum wraps past ~2^11 full
shards. Every cross-shard sum is therefore carried in two int32 channels
— lo 15 bits and hi bits of each per-shard value summed separately —
and recombined on the host as ``hi·2^15 + lo``, exact to 2^15 shards
(32 billion columns) per query.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pilosa_tpu.executor import expr
from pilosa_tpu.shardwidth import WORDS_PER_SHARD, next_pow2
from pilosa_tpu.storage import residency

INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1

# Block-layout key interning table (see ShardBlock.key). Tokens are
# monotonic — never reused even across overflow resets, so a stale
# resident entry can never alias a new layout's key.
_KEY_INTERN: dict[tuple, tuple] = {}
_KEY_INTERN_SEQ = itertools.count()

# Split-sum carry point: per-shard summands are ≤ 2^20, so the lo channel
# (15 bits) sums safely over 2^16 shards and the hi channel (≤ 2^5 per
# shard) even further.
SPLIT_SHIFT = 15
SPLIT_MASK = (1 << SPLIT_SHIFT) - 1


def split_sum(x, axis=None):
    """Sum int32 per-shard values in two overflow-safe int32 channels.
    Returns stacked [2, ...]: (lo-bit sums, hi-bit sums)."""
    lo = jnp.sum(x & SPLIT_MASK, axis=axis)
    hi = jnp.sum(x >> SPLIT_SHIFT, axis=axis)
    return jnp.stack([lo, hi])


def merge_split(packed: np.ndarray) -> np.ndarray:
    """Host-side recombination of split sums [2, ...] → int64 [...]."""
    packed = np.asarray(packed, np.int64)
    return (packed[1] << SPLIT_SHIFT) + packed[0]


class ShardBlock:
    """Orders a query's shard list as the leading axis of stacked leaves.

    The padded slot count buckets to the next power of two so a growing
    index recompiles each query shape O(log shards) times instead of on
    every new shard (XLA compiles are tens of seconds on TPU; the cost is
    ≤2x zero slots on stacked leaves, which reduce to nothing). The mesh
    form (parallel.mesh.ShardAssignment) additionally pads to a multiple
    of the device count so the leading axis shards evenly.
    """

    def __init__(self, shards: list[int]):
        self.shards = sorted(shards)
        self.padded = next_pow2(max(len(self.shards), 1))
        self.n_devices = 1
        self._key = None
        # single-process defaults; the multi-host ShardAssignment
        # (parallel/mesh.py) narrows local_slots to this process's rows
        # and clears patchable (write events then patch the addressable
        # single-device PIECE holding the slot — _patch_sharded — instead
        # of scattering into the whole array)
        self.local_slots = (0, self.padded)
        self.patchable = True

    def key(self) -> tuple:
        # Interned: leaf-cache keys embed the block key, and hashing a
        # 1k-shard tuple on every residency lookup is measurable on the
        # serving path. Equal layouts (shards, padding, device count,
        # local slot span) share one small token, so equal blocks built
        # at different times still hit the same cache entries; the full
        # tuple is hashed once per distinct layout.
        if self._key is None:
            full = (tuple(self.shards), self.padded, self.n_devices,
                    self.local_slots)
            if len(_KEY_INTERN) >= 4096:
                # runaway distinct layouts (pathological Options(
                # shards=) traffic): reset — orphaned residency entries
                # simply age out of the LRU; tokens stay monotonic so
                # none can alias
                _KEY_INTERN.clear()
            # setdefault: atomic under the GIL, so two threads racing the
            # same new layout agree on ONE token (a loser's token would
            # split the residency cache for that layout forever)
            self._key = _KEY_INTERN.setdefault(
                full, ("blk", next(_KEY_INTERN_SEQ))
            )
        return self._key

    @property
    def host_rows(self) -> int:
        """Rows this process materializes on host: padded single-process,
        the local slot span under multi-host feeding."""
        lo, hi = self.local_slots
        return hi - lo

    def stack(self, per_shard_fn, inner: tuple | None = None) -> np.ndarray:
        """Build the [host_rows, ...] host array for this process's slots
        (all of [0, padded) single-process): per_shard_fn(shard) → row
        block; empty slots are zeros. ``inner`` is the per-shard row
        shape; when omitted it is probed by decoding one shard (an
        all-padding process then pays a wasted decode — callers with a
        statically known shape should pass it)."""
        lo, hi = self.local_slots
        local = self.shards[lo:min(hi, len(self.shards))]
        first = per_shard_fn(local[0]) if local else None
        if first is not None:
            inner = first.shape
        elif inner is None:
            # all-padding process: still must feed correctly-shaped zeros
            inner = per_shard_fn(self.shards[0]).shape if self.shards else ()
        out = np.zeros((hi - lo,) + tuple(inner), np.uint32)
        for i, s in enumerate(local):
            out[i] = first if i == 0 else per_shard_fn(s)
        return out


# ------------------------------------------------------- host decode helpers


def host_row(idx, spec, shard: int) -> np.ndarray:
    """Dense uint32[words] for a _RowSpec leaf on one shard (host side)."""
    field = idx.field(spec.field)
    acc = None
    for vname in spec.views:
        view = field.view(vname) if field else None
        frag = view.fragment(shard) if view else None
        if frag is None:
            continue
        words = frag.row_words(spec.row)
        acc = words if acc is None else np.bitwise_or(acc, words)
    return acc if acc is not None else np.zeros(WORDS_PER_SHARD, np.uint32)


def host_planes(idx, spec, shard: int, depth: int) -> np.ndarray:
    """uint32[depth, words] BSI plane matrix for one shard (host side).
    A delete_field racing the decode reads zeros, not a dead object."""
    field = idx.field(spec.field)
    view = field.view(field.bsi_view_name()) if field is not None else None
    frag = view.fragment(shard) if view else None
    if frag is None:
        return np.zeros((depth, WORDS_PER_SHARD), np.uint32)
    return np.stack([frag.row_words(r) for r in range(depth)])


# ------------------------------------------------------ cached stacked leaves
#
# Leaves are keyed WITHOUT a write generation: a fragment mutation is
# routed (residency.apply_write) to exactly the dependent leaves, which
# are patched on device — a scatter of the affected shard slot — instead
# of being evicted. SURVEY.md §7.3 hard part #3: writes no longer force
# the next query to re-decode and re-upload its whole working set.


@jax.jit
def _or_delta(arr, slot, word_idx, masks):
    """OR sparse word masks into one shard slot of a [S, W] leaf.
    word_idx is host-deduplicated; padding repeats (0, mask 0), which
    .at[].max resolves correctly against any real mask for word 0."""
    delta = jnp.zeros((arr.shape[-1],), jnp.uint32).at[word_idx].max(masks)
    return arr.at[slot].set(arr[slot] | delta)


@jax.jit
def _andnot_delta(arr, slot, word_idx, masks):
    delta = jnp.zeros((arr.shape[-1],), jnp.uint32).at[word_idx].max(masks)
    return arr.at[slot].set(arr[slot] & ~delta)


@jax.jit
def _or_delta_row(arr, slot, row, word_idx, masks):
    """Same for one row of a [S, R, W] matrix leaf."""
    delta = jnp.zeros((arr.shape[-1],), jnp.uint32).at[word_idx].max(masks)
    return arr.at[slot, row].set(arr[slot, row] | delta)


@jax.jit
def _andnot_delta_row(arr, slot, row, word_idx, masks):
    delta = jnp.zeros((arr.shape[-1],), jnp.uint32).at[word_idx].max(masks)
    return arr.at[slot, row].set(arr[slot, row] & ~delta)


def _word_masks(positions) -> tuple[np.ndarray, np.ndarray]:
    """In-shard positions → (unique word indices, OR-combined masks),
    padded to the next power of two so delta scatters compile O(log n)
    distinct shapes."""
    positions = np.asarray(positions, np.uint32)
    words = (positions >> 5).astype(np.int32)
    bits = np.uint32(1) << (positions & np.uint32(31))
    uw = np.unique(words)
    masks = np.zeros(uw.size, np.uint32)
    idx = np.searchsorted(uw, words)
    np.bitwise_or.at(masks, idx, bits)
    n = next_pow2(max(uw.size, 1))
    out_w = np.zeros(n, np.int32)
    out_m = np.zeros(n, np.uint32)
    out_w[: uw.size] = uw
    out_m[: uw.size] = masks
    return out_w, out_m


def _patch_sharded(arr, slot: int, make_patch):
    """Patch one global row of a multi-process sharded array WITHOUT a
    collective: rewrite only the addressable single-device piece holding
    ``slot`` (a single-device program on that piece's device) and
    reassemble the global handle from the per-device buffers — every
    other piece's buffer is reused as-is. Each process's handle only
    contributes its own addressable data to SPMD execution, so a
    process-local reassembly is all a local write needs (SURVEY.md §7.3
    hard part #3, multi-host case — VERDICT r3 #6)."""
    pieces = list(arr.addressable_shards)
    datas = [p.data for p in pieces]
    for i, p in enumerate(pieces):
        sl = p.index[0]
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else arr.shape[0]
        if start <= slot < stop:
            datas[i] = make_patch(datas[i], slot - start)
            return jax.make_array_from_single_device_arrays(
                arr.shape, arr.sharding, datas
            )
    return arr  # slot not addressable here: nothing local to patch


def _make_probe(block: ShardBlock, match, row_pos_of, decode_row,
                delta_on_clear: bool):
    """Shared write-routing probe for every stacked-leaf kind.

    match(ev) → is this event for our leaf's (view, row) surface?
    row_pos_of(ev) → inner row axis position, or None for [S, W] leaves.
    decode_row(ev) → fresh host words for the affected (shard, row), the
    fallback when the exact delta can't be applied.
    delta_on_clear → clears may delta-patch (single-view leaves only: with
    multiple OR'd views a cleared bit may survive via another view).

    Non-patchable blocks (multi-host ShardAssignment): a whole-array
    scatter on a multi-process global array would be a collective every
    process must join, but a write event fires only on the process whose
    holder received the write — so the patch is applied per-piece
    (_patch_sharded): the addressable single-device buffer holding the
    shard's slot is rewritten locally and the global handle reassembled,
    with no host round trip and no purge-refeed of unrelated slots.
    Correctness contract: a shard's writes must be applied on (at least)
    the process owning that shard's slot — the cluster layer routes
    writes to fragment owners, which the slot layout mirrors; a process
    observing a foreign shard's write has nothing local to patch (its
    pieces don't contain that slot) and leaves its handle untouched.
    """
    slot_of = {s: i for i, s in enumerate(block.shards)}
    per_piece = not block.patchable
    slot_lo, slot_hi = block.local_slots

    def probe(ev):
        slot = slot_of.get(ev.shard)
        if slot is None or not match(ev):
            return None
        if per_piece and not (slot_lo <= slot < slot_hi):
            # foreign shard's write observed on this process: none of our
            # addressable pieces contain that slot — nothing local to do
            return None
        row_pos = row_pos_of(ev) if row_pos_of is not None else None
        if ev.added or (ev.added is False and delta_on_clear):
            if ev.positions is not None:
                word_idx, masks = _word_masks(ev.positions)
                if row_pos is None:
                    fn = _or_delta if ev.added else _andnot_delta
                    if per_piece:
                        return lambda arr: _patch_sharded(
                            arr, slot,
                            lambda piece, r: fn(piece, r, word_idx, masks),
                        )
                    return lambda arr: fn(arr, slot, word_idx, masks)
                fn = _or_delta_row if ev.added else _andnot_delta_row
                if per_piece:
                    return lambda arr: _patch_sharded(
                        arr, slot,
                        lambda piece, r: fn(piece, r, row_pos, word_idx,
                                            masks),
                    )
                return lambda arr: fn(arr, slot, row_pos, word_idx, masks)

        def set_row(arr_or_piece, r):
            new = jnp.asarray(decode_row(ev))
            if row_pos is None:
                return arr_or_piece.at[r].set(new)
            return arr_or_piece.at[r, row_pos].set(new)

        if per_piece:
            return lambda arr: _patch_sharded(arr, slot, set_row)
        return lambda arr: set_row(arr, slot)

    return probe


def leaf_key(idx, spec, block: ShardBlock) -> tuple:
    """Residency key for a compiled spec's stacked leaf (must stay in
    lockstep with stacked_leaf below — the executor's operand memo uses
    these keys to re-touch LRU positions on memo hits)."""
    from pilosa_tpu.executor.executor import (
        PQLError,
        _PlanesSpec,
        _RowSpec,
        _ZeroSpec,
    )

    # idx.scope (the holder-unique data-dir path) leads every key: two
    # Holders in one process (in-process clusters, embedded multi-server)
    # hold DIFFERENT replicas' data under identical index/field names, and
    # a shared-cache hit across them served one node a stale copy of
    # another's row (membership-churn property sweep). The zero leaf
    # stays unscoped: all-zero content is identical everywhere.
    if isinstance(spec, _RowSpec):
        return ("stack", idx.scope, idx.name, spec.field, spec.views,
                spec.row, block.key())
    if isinstance(spec, _PlanesSpec):
        return ("stackp", idx.scope, idx.name, spec.field, 2 + spec.depth,
                block.key())
    if isinstance(spec, _ZeroSpec):
        return ("stackz", block.key())
    raise PQLError(f"unknown leaf spec {type(spec).__name__}")


def leaf_keys(idx, specs, block: ShardBlock) -> tuple:
    """Residency keys for a plan's leaves (operand-memo LRU re-touch)."""
    return tuple(leaf_key(idx, s, block) for s in specs)


def stacked_leaf(idx, spec, block: ShardBlock, device_put=None):
    """Device-resident stacked leaf for a compiled spec, via the residency
    LRU. ``device_put`` overrides placement (mesh sharding)."""
    from pilosa_tpu.executor.executor import (
        PQLError,
        _PlanesSpec,
        _RowSpec,
        _ZeroSpec,
    )

    cache = residency.global_row_cache()
    if isinstance(spec, _RowSpec):
        key = leaf_key(idx, spec, block)

        def decode():
            return block.stack(lambda shard: host_row(idx, spec, shard),
                               inner=(WORDS_PER_SHARD,))

        def probe():  # factory: only built when the key isn't registered
            views = frozenset(spec.views)
            return _make_probe(
                block,
                match=lambda ev: ev.row == spec.row and ev.view in views,
                row_pos_of=None,
                decode_row=lambda ev: host_row(idx, spec, ev.shard),
                delta_on_clear=len(spec.views) == 1,
            )
    elif isinstance(spec, _PlanesSpec):
        from pilosa_tpu.storage.view import view_name_bsi

        # compile-time depth + name-derived view: a delete_field racing
        # the query resolves to zeros instead of a dead dereference
        depth = 2 + spec.depth
        bsi_view = view_name_bsi(spec.field)
        key = leaf_key(idx, spec, block)

        def decode():
            return block.stack(
                lambda shard: host_planes(idx, spec, shard, depth),
                inner=(depth, WORDS_PER_SHARD),
            )

        def decode_row(ev):
            field = idx.field(spec.field)  # live schema: None post-delete
            view = field.view(bsi_view) if field is not None else None
            frag = view.fragment(ev.shard) if view else None
            if frag is None:
                return np.zeros(WORDS_PER_SHARD, np.uint32)
            return frag.row_words(ev.row)

        def probe():
            return _make_probe(
                block,
                match=lambda ev: ev.view == bsi_view and ev.row < depth,
                row_pos_of=lambda ev: ev.row,
                decode_row=decode_row,
                delta_on_clear=True,
            )
    elif isinstance(spec, _ZeroSpec):
        key = leaf_key(idx, spec, block)

        def decode():
            return np.zeros((block.host_rows, WORDS_PER_SHARD), np.uint32)

        return cache.get_row(key, decode, device_put=device_put)
    else:
        raise PQLError(f"unknown leaf spec {type(spec).__name__}")

    return cache.get_or_build(key, (idx.scope, idx.name, spec.field),
                               probe, decode,
                              device_put=device_put)


def stacked_matrix(idx, field_name: str, view, row_ids, block: ShardBlock,
                   device_put=None, pad_rows: int = 0):
    """Stacked row matrix ``uint32[padded, len(row_ids) + pad_rows,
    words]`` of one view (TopN phase-2 candidates, GroupBy dimensions),
    HBM-cached. ``pad_rows`` appends all-zero rows (shape bucketing for
    pipelined TopN) — zeros, NOT duplicates of a real row: a duplicate
    would break the write-patch routing, which maps each row id to ONE
    inner position."""
    cache = residency.global_row_cache()
    view_name = view.name if view is not None else None
    n_rows = len(row_ids) + pad_rows
    key = ("stackm", idx.scope, idx.name, field_name, view_name,
           tuple(row_ids), pad_rows, block.key())

    def live_view():
        # resolve by NAME at decode time, never through the captured
        # object: a delete_field racing the build must read the live
        # schema (None / the recreated field), not a dead view's bitmap
        field = idx.field(field_name)
        return field.view(view_name) if field and view_name else None

    def decode():
        v = live_view()

        def per_shard(shard):
            frag = v.fragment(shard) if v else None
            if frag is None:
                return np.zeros((n_rows, WORDS_PER_SHARD), np.uint32)
            rows = [frag.row_words(r) for r in row_ids]
            rows.extend(
                np.zeros(WORDS_PER_SHARD, np.uint32)
                for _ in range(pad_rows)
            )
            return np.stack(rows)

        return block.stack(per_shard, inner=(n_rows, WORDS_PER_SHARD))

    def decode_row(ev):
        v = live_view()
        frag = v.fragment(ev.shard) if v else None
        if frag is None:
            return np.zeros(WORDS_PER_SHARD, np.uint32)
        return frag.row_words(ev.row)

    def probe():
        row_pos_of = {r: i for i, r in enumerate(row_ids)}
        return _make_probe(
            block,
            match=lambda ev: ev.view == view_name and ev.row in row_pos_of,
            row_pos_of=lambda ev: row_pos_of[ev.row],
            decode_row=decode_row,
            delta_on_clear=True,
        )

    return cache.get_or_build(key, (idx.scope, idx.name, field_name),
                               probe, decode,
                              device_put=device_put)


# ------------------------------------------------------ local program builder

_LOCAL_JIT_CACHE: dict = {}


def minmax_mask(values, counts, want_max: bool):
    """Per-shard masking for the Min/Max merge: shards with no candidates
    (count 0 — including padded slots) are replaced by the opposite-extreme
    sentinel so they lose every comparison. Returns (masked, valid)."""
    valid = counts > 0
    sentinel = INT32_MIN if want_max else INT32_MAX
    return jnp.where(valid, values, sentinel), valid


def minmax_at_best(values, counts, valid, best):
    """Split-sum count of candidates holding the extremum (pre-reduction:
    the SPMD builder psums this across the mesh before packing)."""
    return split_sum(jnp.where(valid & (values == best), counts, 0))


def minmax_finalize(best, n, any_valid):
    """Pack [best, count_lo, count_hi] int32 (count 0 → empty result)."""
    best = jnp.where(any_valid, best, 0)
    return jnp.concatenate([best.astype(jnp.int32)[None], n])


def minmax_merge(values, counts, want_max: bool):
    """Device-side cross-shard Min/Max merge (single device: plain
    reductions; the SPMD builder composes the same helpers with pmax/psum)."""
    masked, valid = minmax_mask(values, counts, want_max)
    best = jnp.max(masked) if want_max else jnp.min(masked)
    n = minmax_at_best(values, counts, valid, best)
    return minmax_finalize(best, n, jnp.any(valid))


# Reduction row width for the elementwise-count fast path. Measured on
# v5e (2026-07, /tmp/shape_test): axis-1 popcount sums over 2^18-word
# rows run at flat-array speed, while the natural 32768-word shard rows
# are ~8% slower (too many short reduction rows). Must divide any
# stacked block size: S_padded·2^15 words with S_padded a power of two.
COUNT_CHUNK_WORDS = 1 << 18


def count_elementwise_sub(structure, leaf_ranks: tuple):
    """For a ('count', sub) structure whose tree is purely elementwise
    over rank-1 word leaves (and/or/xor/diff/leaf/const0 — no shift,
    whose bit motion is per-shard, and no BSI ops), return ``sub``; else
    None. Such counts need no per-shard vmap: bit position never
    matters, so the whole stacked block reduces as one flat array in
    wider chunks (COUNT_CHUNK_WORDS) — the per-shard row width of 2^15
    words costs measurable reduction overhead on TPU.

    ``flipall`` deliberately DISQUALIFIES: the stacked block pads its
    shard axis to a power of two with zero slots, and an unmasked NOT
    turns those into all-ones words that the flat reduction would count.
    The compiler never emits it (Not lowers to diff(exists, x), masked
    by construction), so excluding it costs nothing and removes the
    latent hazard for hand-built trees (ADVICE r4)."""
    if not structure or structure[0] != "count":
        return None
    if any(r != 1 for r in leaf_ranks):
        return None

    def ok(n):
        if not isinstance(n, tuple):
            return True
        if n[0] in ("leaf", "const0"):
            return True
        if n[0] in ("and", "or", "xor", "diff"):
            return all(ok(c) for c in n[1:])
        return False

    return structure[1] if ok(structure[1]) else None


def count_flat(sub, leaves, scalars):
    """Evaluate an elementwise count subtree over whole stacked leaves
    and reduce popcounts in COUNT_CHUNK_WORDS-wide rows. Exact for any
    block size: per-chunk sums ≤ 2^23 fit int32 and cross-chunk sums ride
    the same split channels as the per-shard path."""
    words = expr._go(sub, leaves, scalars)
    chunk = min(COUNT_CHUNK_WORDS, words.size)
    rows = words.reshape(-1, chunk)
    counts = jnp.sum(lax.population_count(rows).astype(jnp.int32), axis=-1)
    return split_sum(counts)


def _local_body(structure, reduce_kind: str, leaf_ranks: tuple):
    """Uncompiled single-query evaluator body: vmap over the stacked
    shard axis + on-device reduction. Shared by the per-query program
    (local_fn) and the micro-batched program (local_fn_batched)."""
    n_leaves = len(leaf_ranks)
    count_sub = (count_elementwise_sub(structure, leaf_ranks)
                 if reduce_kind == "count" else None)

    def body(*args):
        leaves = args[:n_leaves]
        scalars = args[n_leaves:]

        if count_sub is not None:
            return count_flat(count_sub, leaves, scalars)

        def per_shard(*ls):
            return expr._go(structure, ls, scalars)

        out = jax.vmap(per_shard)(*leaves)
        if reduce_kind == "count":
            return split_sum(out)
        if reduce_kind == "countrows":
            return split_sum(out, axis=0)
        if reduce_kind == "bsisum":
            plane_counts, n = out  # [S, depth], [S]
            return jnp.concatenate(
                [split_sum(plane_counts, axis=0),
                 split_sum(n)[:, None]], axis=1
            )
        if reduce_kind in ("min", "max"):
            values, counts = out
            return minmax_merge(values, counts, reduce_kind == "max")
        return out  # 'row': [padded, words]

    return body


def local_fn(structure, reduce_kind: str, leaf_ranks: tuple, n_scalars: int):
    """Build (or fetch) the single-device batched evaluator for a query
    shape: vmap over the stacked shard axis + on-device reduction."""
    key = ("local", structure, reduce_kind, leaf_ranks, n_scalars)
    fn = _LOCAL_JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_local_body(structure, reduce_kind, leaf_ranks))
        _LOCAL_JIT_CACHE[key] = fn
    return fn


def batched_body(body1, n_leaves: int, n_scalars: int, n_queries: int):
    """Wrap a per-query evaluator body into the micro-batch calling
    convention shared by _flush_group_locked's dispatch, the local
    builder below, and the SPMD builder (parallel.dist._dist_fn_batched):
    args are B repetitions of the leaves, then (when the shape has
    scalars) ONE int32[B, n_scalars] array carrying every query's scalars
    in a single transfer; the per-query packed results come back stacked
    on axis 0."""

    def body(*args):
        if n_scalars:
            flat, scal = args[:-1], args[-1]
        else:
            flat, scal = args, None
        outs = []
        for i in range(n_queries):
            leaves_i = flat[i * n_leaves:(i + 1) * n_leaves]
            scalars_i = (
                tuple(scal[i, j] for j in range(n_scalars))
                if n_scalars else ()
            )
            outs.append(body1(*leaves_i, *scalars_i))
        return jnp.stack(outs)

    return body


def local_fn_batched(structure, reduce_kind: str, leaf_ranks: tuple,
                     n_scalars: int, n_queries: int):
    """ONE device program evaluating ``n_queries`` same-shape queries
    (Executor.submit micro-batching). Each program dispatch on a
    tunneled/remote backend carries a fixed launch cost comparable to the
    device compute of a whole 1B-column query; stacking a micro-batch of
    pipelined queries into one program amortizes it, and the single
    [B, ...] readback serves every query in the batch with one host
    round trip."""
    key = ("localB", structure, reduce_kind, leaf_ranks, n_scalars,
           n_queries)
    fn = _LOCAL_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    body1 = _local_body(structure, reduce_kind, leaf_ranks)
    fn = jax.jit(batched_body(body1, len(leaf_ranks), n_scalars, n_queries))
    _LOCAL_JIT_CACHE[key] = fn
    return fn


# HBM budget for the materialized per-level group masks ([C, words] per
# gathered dimension per shard block). Chunks are sized so the gathered
# intermediates stay under this even at full shard counts.
GROUPBY_MASK_BUDGET_BYTES = 256 << 20


def groupby_chunk_groups(block: ShardBlock, n_gather: int, depth: int) -> int:
    """Max candidate groups per level chunk under the mask byte budget."""
    s_per_dev = -(-block.padded // block.n_devices)
    bytes_per_group = s_per_dev * WORDS_PER_SHARD * 4 * (n_gather + depth)
    return max(1, GROUPBY_MASK_BUDGET_BYTES // max(bytes_per_group, 1))


def groupby_level_body(ls, idxs, scalars, filt_structure, n_filt: int,
                       n_gather: int, has_agg: bool):
    """Per-shard GroupBy level kernel shared by the local and SPMD
    builders: gather each candidate's row from every dimension matrix,
    AND them into [C, words] group masks, popcount per candidate; with an
    aggregate also per-candidate BSI plane counts (expr 'bsisum' semantics
    per group)."""
    filt_leaves = ls[:n_filt]
    dim_mats = ls[n_filt:n_filt + n_gather]
    mask = jnp.take(dim_mats[0], idxs[0], axis=0)  # [C, W]
    for d, ii in zip(dim_mats[1:], idxs[1:]):
        mask = mask & jnp.take(d, ii, axis=0)
    if filt_structure is not None:
        f = expr._go(filt_structure, filt_leaves, scalars)
        mask = mask & f[None, :]
    counts = jnp.sum(lax.population_count(mask).astype(jnp.int32), axis=-1)
    if not has_agg:
        return counts
    planes = ls[n_filt + n_gather]
    gmask = mask & planes[expr.PLANES_EXISTS][None, :]
    n_g = jnp.sum(lax.population_count(gmask).astype(jnp.int32), axis=-1)
    plane_counts = jnp.stack([
        jnp.sum(lax.population_count(planes[b][None, :] & gmask)
                .astype(jnp.int32), axis=-1)
        for b in range(expr.PLANES_OFFSET, planes.shape[0])
    ])  # [depth, C]
    return counts, n_g, plane_counts


def local_groupby_level_fn(filt_structure, n_filt: int, n_scalars: int,
                           n_gather: int, has_agg: bool):
    """Single-device GroupBy level program.

    Args: filt leaves ++ dim matrices [S, n_i, W] ++ (planes
    [S, depth+2, W] if agg) ++ candidate index arrays int32[C] (one per
    gathered dim) ++ scalars. Packed result (split sums, [2, ·] raveled):
    counts [2·C] without agg, else counts [2·C] ++ n_g [2·C] ++
    plane_counts [2·depth·C].
    """
    key = ("localgbl", filt_structure, n_filt, n_scalars, n_gather, has_agg)
    fn = _LOCAL_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    n_leaves = n_filt + n_gather + (1 if has_agg else 0)

    def body(*args):
        leaves = args[:n_leaves]
        idxs = args[n_leaves:n_leaves + n_gather]
        scalars = args[n_leaves + n_gather:]

        def per_shard(*ls):
            return groupby_level_body(
                ls, idxs, scalars, filt_structure, n_filt, n_gather, has_agg
            )

        out = jax.vmap(per_shard)(*leaves)
        if not has_agg:
            return split_sum(out, axis=0).ravel()
        counts, n_g, plane_counts = (split_sum(o, axis=0) for o in out)
        return jnp.concatenate(
            [counts.ravel(), n_g.ravel(), plane_counts.ravel()]
        )

    fn = jax.jit(body)
    _LOCAL_JIT_CACHE[key] = fn
    return fn
