"""Result types for query execution.

Reference: row.go (SURVEY.md §2 #2) — a Row is per-shard segments each
wrapping a bitmap, so cross-shard merges are cheap concatenation; plus the
pair/group shapes the executor reduces (Pairs for TopN, GroupCounts for
GroupBy).
"""

from __future__ import annotations

import json

import numpy as np

from pilosa_tpu.ops.packing import popcount_words, unpack_bits
from pilosa_tpu.shardwidth import SHARD_WIDTH


class RowResult:
    """Query-result set of columns: shard → dense uint32 words (host)."""

    def __init__(self, segments: dict[int, np.ndarray] | None = None, attrs=None, keys=None):
        self.segments = segments or {}
        self.attrs = attrs or {}
        self.keys = keys  # translated column keys, when the index uses keys
        self.column_attrs = None  # [{"id": col, "attrs": {...}}] via Options(columnAttrs=true)

    def columns(self) -> np.ndarray:
        parts = [
            unpack_bits(words, offset=shard * SHARD_WIDTH)
            for shard, words in sorted(self.segments.items())
        ]
        if not parts:
            return np.empty(0, np.uint64)
        return np.concatenate(parts)

    def count(self) -> int:
        return sum(popcount_words(w) for w in self.segments.values())

    def merge(self, other: "RowResult") -> "RowResult":
        """Cross-node reduce: union segments (shards are disjoint across
        owners, so collisions only appear with replication — union is
        correct either way)."""
        out = dict(self.segments)
        for shard, words in other.segments.items():
            if shard in out:
                out[shard] = np.bitwise_or(out[shard], words)
            else:
                out[shard] = words
        return RowResult(out, {**other.attrs, **self.attrs})

    def to_json(self) -> dict:
        if self.keys is not None:
            out = {"attrs": self.attrs, "keys": self.keys}
        else:
            out = {"attrs": self.attrs, "columns": self.columns().tolist()}
        if self.column_attrs is not None:
            out["columnAttrs"] = self.column_attrs
        return out


class Pair:
    """TopN result element (reference Pair{ID, Count})."""

    __slots__ = ("id", "count", "key")

    def __init__(self, id: int, count: int, key: str | None = None):
        self.id = id
        self.count = count
        self.key = key

    def to_json(self) -> dict:
        d = {"id": self.id, "count": self.count}
        if self.key is not None:
            d["key"] = self.key
        return d

    def __eq__(self, other):
        if not isinstance(other, Pair):
            return NotImplemented
        return (self.id == other.id and self.count == other.count
                and self.key == other.key)

    def __hash__(self):
        # key is attached after construction for keyed fields; exclude it
        # so the hash is stable over the Pair's lifetime
        return hash((self.id, self.count))

    def __repr__(self) -> str:
        return f"Pair(id={self.id}, count={self.count}, key={self.key!r})"


class ValCount:
    """Sum/Min/Max result (reference ValCount{Val, Count})."""

    __slots__ = ("value", "count")

    def __init__(self, value: int, count: int):
        self.value = value
        self.count = count

    def to_json(self) -> dict:
        return {"value": self.value, "count": self.count}

    def __eq__(self, other):
        if not isinstance(other, ValCount):
            return NotImplemented
        return self.value == other.value and self.count == other.count

    def __hash__(self):
        return hash((self.value, self.count))

    def __repr__(self) -> str:
        return f"ValCount(value={self.value}, count={self.count})"


class GroupCount:
    """GroupBy result element (reference GroupCount; ``sum`` set when the
    call carries aggregate=Sum(...))."""

    __slots__ = ("group", "count", "sum")

    def __init__(self, group: list[dict], count: int, sum: int | None = None):
        self.group = group  # [{"field": name, "rowID": id}, ...]
        self.count = count
        self.sum = sum

    def to_json(self) -> dict:
        out = {"group": self.group, "count": self.count}
        if self.sum is not None:
            out["sum"] = self.sum
        return out

    def __eq__(self, other):
        if not isinstance(other, GroupCount):
            return NotImplemented
        return (self.group == other.group and self.count == other.count
                and self.sum == other.sum)

    # value-equal but holds a list; deliberately unhashable
    __hash__ = None

    def __repr__(self) -> str:
        return (f"GroupCount(group={self.group}, count={self.count}, "
                f"sum={self.sum})")


def result_to_json(res):
    """Serialize any executor result for the HTTP response envelope."""
    if isinstance(res, (RowResult, Pair, ValCount, GroupCount)):
        return res.to_json()
    if isinstance(res, list):
        return [result_to_json(r) for r in res]
    if isinstance(res, np.integer):
        return int(res)
    return res


# ------------------------------------------------- pre-serialized responses
#
# The serving fast lane encodes hot result shapes (Count, Row, TopN pairs,
# ValCount) straight to compact-JSON bytes once, instead of dict-building
# then json.dumps per request. RowResult encodings memoize ON the result
# object — the encoded-bytes cache keyed by result identity — so a wave of
# identical coalesced queries (server/pipeline.py dedupe) pays the
# segment-unpack + encode exactly once however many clients asked.


def _dumps(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode()


def result_json_bytes(res) -> bytes:
    """Compact-JSON bytes of ``result_to_json(res)`` (exact same JSON
    value; whitespace-free encoding)."""
    if isinstance(res, bool):  # before int — bool subclasses int
        return b"true" if res else b"false"
    if isinstance(res, (int, np.integer)):
        return b"%d" % int(res)
    if isinstance(res, RowResult):
        cached = getattr(res, "_json_bytes", None)
        if cached is None:
            cached = res._json_bytes = _dumps(res.to_json())
        return cached
    if isinstance(res, ValCount):
        return b'{"value":%d,"count":%d}' % (res.value, res.count)
    return _dumps(result_to_json(res))


def results_json_bytes(results) -> bytes:
    """The whole ``{"results": [...]}`` response envelope as bytes."""
    return (b'{"results":['
            + b",".join(result_json_bytes(r) for r in results) + b"]}")
