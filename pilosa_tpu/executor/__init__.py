"""Query executor: PQL AST → fused XLA kernels per shard → reduced results.

Reference: executor.go (SURVEY.md §2 #12, §3.2): per-call dispatch with a
mapReduce core over shards. TPU re-design: instead of walking containers
per call, the whole bitmap expression tree of a query is compiled
(pilosa_tpu.executor.expr) into ONE jitted function per tree shape, so
``Count(Intersect(Union(a,b), Not(c)))`` runs as a single fused
bitwise+popcount pass over each shard's resident rows. Shard mapping is a
host loop on one chip (M2) and a shard_map over the mesh axis in the
distributed path (pilosa_tpu.parallel).
"""

from pilosa_tpu.executor.executor import Deferred, Executor
from pilosa_tpu.executor.result import RowResult
