"""ctypes bindings for the fastbits native library, with numpy fallback.

Public surface mirrors pilosa_tpu.ops.packing; ``available()`` reports
whether the native path is active. The library auto-builds on first import
when a toolchain exists (g++ baked into the image); PILOSA_TPU_NO_NATIVE=1
forces the numpy fallback.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_lib = None  # None = not tried yet; False = unavailable (cached); else CDLL


def _load():
    global _lib
    if _lib is not None:
        return _lib or None
    if os.environ.get("PILOSA_TPU_NO_NATIVE") == "1":
        return None
    from pilosa_tpu.native.build import build

    try:
        path = build()
        if path is None:
            _lib = False  # cache the miss: this runs in per-container
            return None   # hot loops, a PATH scan per call would bite
        lib = ctypes.CDLL(path)
        if not hasattr(lib, "union_sorted_u16"):
            # Stale .so predating the sorted-set symbols. dlopen caches
            # by path, so re-loading the rebuilt file at the SAME path
            # returns the stale handle — rebuild to a fresh temp name.
            import shutil
            import tempfile

            src = build(force=True)
            if src is None:
                _lib = False
                return None
            fresh = tempfile.NamedTemporaryFile(
                suffix=".so", delete=False
            ).name
            shutil.copy2(src, fresh)
            lib = ctypes.CDLL(fresh)
            os.unlink(fresh)  # mapping survives the unlink (Linux)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.pack_positions.argtypes = [u64p, ctypes.c_int64, u32p,
                                       ctypes.c_int64]
        lib.pack_positions.restype = None
        lib.unpack_positions.argtypes = [
            u32p, ctypes.c_int64, ctypes.c_uint64, u64p, ctypes.c_int64,
        ]
        lib.unpack_positions.restype = ctypes.c_int64
        lib.popcount_words.argtypes = [u32p, ctypes.c_int64]
        lib.popcount_words.restype = ctypes.c_uint64
        lib.or_words.argtypes = [u32p, u32p, ctypes.c_int64]
        lib.or_words.restype = None
        lib.runs_to_words.argtypes = [u16p, ctypes.c_int64, u32p]
        lib.runs_to_words.restype = None
        lib.union_sorted_u16.argtypes = [u16p, ctypes.c_int64, u16p,
                                         ctypes.c_int64, u16p]
        lib.union_sorted_u16.restype = ctypes.c_int64
        lib.diff_sorted_u16.argtypes = [u16p, ctypes.c_int64, u16p,
                                        ctypes.c_int64, u16p]
        lib.diff_sorted_u16.restype = ctypes.c_int64
    except (OSError, AttributeError):
        _lib = False  # unusable library: permanent numpy fallback
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def pack_positions(positions: np.ndarray, n_words: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    positions = np.ascontiguousarray(positions, np.uint64)
    out = np.zeros(n_words, np.uint32)
    lib.pack_positions(
        _ptr(positions, ctypes.c_uint64), positions.size,
        _ptr(out, ctypes.c_uint32), n_words,
    )
    return out


def unpack_positions(words: np.ndarray, offset: int = 0) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, np.uint32)
    cap = int(lib.popcount_words(_ptr(words, ctypes.c_uint32), words.size))
    out = np.empty(cap, np.uint64)
    n = lib.unpack_positions(
        _ptr(words, ctypes.c_uint32), words.size, offset,
        _ptr(out, ctypes.c_uint64), cap,
    )
    return out[:n]


def popcount_words(words: np.ndarray) -> int | None:
    lib = _load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, np.uint32)
    return int(lib.popcount_words(_ptr(words, ctypes.c_uint32), words.size))


def runs_to_words(runs: np.ndarray) -> np.ndarray | None:
    """Expand [n,2] inclusive uint16 run intervals to a 2048-word block."""
    lib = _load()
    if lib is None:
        return None
    runs = np.ascontiguousarray(runs, np.uint16)
    out = np.zeros(2048, np.uint32)
    lib.runs_to_words(_ptr(runs, ctypes.c_uint16), runs.shape[0],
                      _ptr(out, ctypes.c_uint32))
    return out


def union_sorted_u16(a: np.ndarray, b: np.ndarray) -> np.ndarray | None:
    """Union of two sorted unique uint16 arrays (two-pointer merge)."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, np.uint16)
    b = np.ascontiguousarray(b, np.uint16)
    out = np.empty(a.size + b.size, np.uint16)
    n = lib.union_sorted_u16(_ptr(a, ctypes.c_uint16), a.size,
                             _ptr(b, ctypes.c_uint16), b.size,
                             _ptr(out, ctypes.c_uint16))
    # copy: a view would pin the oversized merge buffer for the life of
    # the container that stores the result
    return out[:n].copy()


def diff_sorted_u16(a: np.ndarray, b: np.ndarray) -> np.ndarray | None:
    """a \\ b for sorted unique uint16 arrays."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, np.uint16)
    b = np.ascontiguousarray(b, np.uint16)
    out = np.empty(a.size, np.uint16)
    n = lib.diff_sorted_u16(_ptr(a, ctypes.c_uint16), a.size,
                            _ptr(b, ctypes.c_uint16), b.size,
                            _ptr(out, ctypes.c_uint16))
    return out[:n].copy()
