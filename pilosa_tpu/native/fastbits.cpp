// Native host-path kernels: bit pack/unpack/popcount.
//
// Role in the architecture: the TPU executes all query math
// (pilosa_tpu/ops via XLA); the *host* feeds it — decoding roaring
// containers into dense bit-packed rows for device_put, packing result
// bitmaps, and counting during imports. Those feeds are python/numpy hot
// spots (np.bitwise_or.at is an order of magnitude off peak), so they get
// a small C++ library. This mirrors the division of labor the driver
// expects: XLA for compute, native code for the runtime around it. The
// reference itself is pure Go (SURVEY.md §2.2); its equivalents are
// roaring.go's container codecs.
//
// Build: see build.py (g++ -O3 -shared). ABI: plain C, loaded via ctypes.

#include <cstdint>
#include <cstring>

extern "C" {

// Set bits at `positions[0..n)` in a zeroed word vector of `n_words`
// uint32 words. Positions beyond the vector are ignored (caller checks).
void pack_positions(const uint64_t* positions, int64_t n,
                    uint32_t* words, int64_t n_words) {
    const uint64_t limit = static_cast<uint64_t>(n_words) * 32u;
    for (int64_t i = 0; i < n; ++i) {
        const uint64_t p = positions[i];
        if (p < limit) {
            words[p >> 5] |= (1u << (p & 31u));
        }
    }
}

// Extract sorted bit positions (+offset) from a word vector.
// Returns the number written; writes at most `cap` entries.
int64_t unpack_positions(const uint32_t* words, int64_t n_words,
                         uint64_t offset, uint64_t* out, int64_t cap) {
    int64_t written = 0;
    for (int64_t w = 0; w < n_words; ++w) {
        uint32_t v = words[w];
        const uint64_t base = offset + (static_cast<uint64_t>(w) << 5);
        while (v != 0 && written < cap) {
            const int bit = __builtin_ctz(v);
            out[written++] = base + static_cast<uint64_t>(bit);
            v &= v - 1;
        }
        if (written >= cap && v != 0) return written;  // caller re-sizes
    }
    return written;
}

// Total set bits in a word vector.
uint64_t popcount_words(const uint32_t* words, int64_t n_words) {
    uint64_t total = 0;
    int64_t i = 0;
    // bulk as uint64 for throughput
    const int64_t pairs = n_words / 2;
    const uint64_t* w64 = reinterpret_cast<const uint64_t*>(words);
    for (int64_t j = 0; j < pairs; ++j) total += __builtin_popcountll(w64[j]);
    for (i = pairs * 2; i < n_words; ++i) total += __builtin_popcount(words[i]);
    return total;
}

// OR src into dst (n_words each) — fragment row union on host.
void or_words(uint32_t* dst, const uint32_t* src, int64_t n_words) {
    for (int64_t i = 0; i < n_words; ++i) dst[i] |= src[i];
}

// Expand run intervals [start,last] (inclusive, uint16 pairs) into a
// 2048-word (65536-bit) container block.
void runs_to_words(const uint16_t* runs, int64_t n_runs, uint32_t* words) {
    for (int64_t i = 0; i < n_runs; ++i) {
        uint32_t start = runs[2 * i];
        uint32_t last = runs[2 * i + 1];
        for (uint32_t b = start; b <= last; ++b) {
            words[b >> 5] |= (1u << (b & 31u));
            if (b == 65535u) break;  // avoid wrap
        }
    }
}

// Union of two SORTED UNIQUE uint16 arrays (two-pointer merge) — the
// ARRAY-container bulk-import path. `out` must hold na+nb; returns the
// merged length. Replaces np.union1d's concat+sort (O((n+m)log(n+m)))
// with O(n+m).
int64_t union_sorted_u16(const uint16_t* a, int64_t na,
                         const uint16_t* b, int64_t nb, uint16_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        const uint16_t x = a[i], y = b[j];
        if (x < y)      { out[k++] = x; ++i; }
        else if (y < x) { out[k++] = y; ++j; }
        else            { out[k++] = x; ++i; ++j; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

// a \ b for SORTED UNIQUE uint16 arrays — the remove path. `out` must
// hold na; returns the result length.
int64_t diff_sorted_u16(const uint16_t* a, int64_t na,
                        const uint16_t* b, int64_t nb, uint16_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na) {
        while (j < nb && b[j] < a[i]) ++j;
        if (j < nb && b[j] == a[i]) { ++i; continue; }
        out[k++] = a[i++];
    }
    return k;
}

}  // extern "C"
