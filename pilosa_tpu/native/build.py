"""Build the fastbits native library (g++, no external deps)."""

from __future__ import annotations

import os
import shutil
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "fastbits.cpp")
LIB = os.path.join(_DIR, "libfastbits.so")


def build(force: bool = False) -> str | None:
    """Compile the library if needed; returns the .so path or None when no
    toolchain is available (callers fall back to numpy)."""
    if not force and os.path.exists(LIB) and (
        os.path.getmtime(LIB) >= os.path.getmtime(SRC)
    ):
        return LIB
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        return None
    tmp = LIB + ".tmp"
    cmd = [gxx, "-O3", "-fPIC", "-shared", "-o", tmp, SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
        return None
    os.replace(tmp, LIB)
    return LIB


if __name__ == "__main__":
    path = build(force=True)
    print(path or "build failed / no compiler")
