"""Go-style duration parsing — ONE grammar for every knob.

The reference's TOML uses Go durations ('1m30s', '500ms'); bare numbers
are seconds. Shared by ServerConfig (server/server.py) and the SLO spec
parser (qos/slo.py) so the two can never drift — a unit accepted by one
knob must be accepted by all of them.
"""

from __future__ import annotations

import re

_NUMBER = r"[0-9]+(?:\.[0-9]+)?|\.[0-9]+"
_COMPOUND_RE = re.compile(rf"^(?:(?:{_NUMBER})(?:ms|us|s|m|h))+$")
_PARTS_RE = re.compile(rf"({_NUMBER})(ms|us|s|m|h)")
_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(value) -> float:
    """Seconds from a float or a Go-style duration string. Empty string
    is 0; malformed input raises ValueError rather than silently
    dropping trailing text."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip().lower()
    if not s:
        return 0.0
    if _COMPOUND_RE.fullmatch(s):
        return sum(float(num) * _UNITS[unit]
                   for num, unit in _PARTS_RE.findall(s))
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"invalid duration: {value!r}") from None
