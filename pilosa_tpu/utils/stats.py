"""StatsClient: counters/gauges/timings threaded through the engine.

Reference: stats/stats.go (SURVEY.md §2 #23) — a StatsClient interface
(Count/Gauge/Histogram/Timing with tags) with statsd and nop backends and
expvar always on. Here: an in-memory client that renders Prometheus text
for GET /metrics (statsd export can be layered on the same interface),
plus a Nop client for tests.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque

# Bounded per-series sample window backing the exported p50/p95 lines —
# a sliding window, not a decaying histogram: ingest fan-out and batch
# sizes change regime abruptly (bulk load starts/stops), and a window
# forgets the old regime after SAMPLE_WINDOW observations.
SAMPLE_WINDOW = 256


def _fmt_tags(tags: dict | None) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def _with_tag(tags_str: str, extra: str) -> str:
    """Splice one more label into an already-rendered tag block."""
    if not tags_str:
        return "{" + extra + "}"
    return tags_str[:-1] + "," + extra + "}"


def _quantile(samples, q: float) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class StatsClient:
    """In-memory stats registry; thread-safe."""

    def __init__(self, prefix: str = "pilosa_tpu"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        # [count, sum, sample window] — the window feeds quantile export
        self._timings: dict[tuple, list] = defaultdict(
            lambda: [0, 0.0, deque(maxlen=SAMPLE_WINDOW)]
        )
        # unit-free distributions (batch sizes, fan-out widths): same
        # shape as _timings but rendered without the _seconds unit suffix
        self._observations: dict[tuple, list] = defaultdict(
            lambda: [0, 0.0, deque(maxlen=SAMPLE_WINDOW)]
        )

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        with self._lock:
            self._counters[(name, _fmt_tags(tags))] += value

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._gauges[(name, _fmt_tags(tags))] = value

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        with self._lock:
            entry = self._timings[(name, _fmt_tags(tags))]
            entry[0] += 1
            entry[1] += seconds
            entry[2].append(seconds)

    def timer(self, name: str, tags: dict | None = None):
        return _Timer(self, name, tags)

    def histogram(self, name: str, value: float, tags: dict | None = None) -> None:
        self.timing(name, value, tags)

    def observe(self, name: str, value: float, tags: dict | None = None) -> None:
        """Record one sample of a unit-free distribution (batch size,
        fan-out width). Exported as count/sum/quantile lines without the
        _seconds suffix that timing() series carry."""
        with self._lock:
            entry = self._observations[(name, _fmt_tags(tags))]
            entry[0] += 1
            entry[1] += value
            entry[2].append(value)

    def quantile(self, name: str, q: float, tags: dict | None = None) -> float | None:
        """Windowed quantile of a timing or observation series (None if
        the series has no samples yet)."""
        key = (name, _fmt_tags(tags))
        with self._lock:
            entry = self._timings.get(key) or self._observations.get(key)
            samples = list(entry[2]) if entry else []
        return _quantile(samples, q) if samples else None

    def prometheus_text(self) -> str:
        lines = []
        with self._lock:
            for (name, tags), v in sorted(self._counters.items()):
                lines.append(f"{self.prefix}_{name}_total{tags} {v:g}")
            for (name, tags), v in sorted(self._gauges.items()):
                lines.append(f"{self.prefix}_{name}{tags} {v:g}")
            for (name, tags), (n, total, samples) in sorted(self._timings.items()):
                lines.append(f"{self.prefix}_{name}_seconds_count{tags} {n:g}")
                lines.append(f"{self.prefix}_{name}_seconds_sum{tags} {total:g}")
                for q in (0.5, 0.95):
                    if samples:
                        qt = _with_tag(tags, f'quantile="{q}"')
                        lines.append(
                            f"{self.prefix}_{name}_seconds{qt} "
                            f"{_quantile(samples, q):g}"
                        )
            for (name, tags), (n, total, samples) in sorted(
                self._observations.items()
            ):
                lines.append(f"{self.prefix}_{name}_count{tags} {n:g}")
                lines.append(f"{self.prefix}_{name}_sum{tags} {total:g}")
                for q in (0.5, 0.95):
                    if samples:
                        qt = _with_tag(tags, f'quantile="{q}"')
                        lines.append(
                            f"{self.prefix}_{name}{qt} "
                            f"{_quantile(samples, q):g}"
                        )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        with self._lock:
            dists = {}
            for source in (self._timings, self._observations):
                for (n, t), (count, total, samples) in source.items():
                    dists[f"{n}{t}"] = {
                        "count": count, "sum": total,
                        "p50": _quantile(samples, 0.5) if samples else None,
                        "p95": _quantile(samples, 0.95) if samples else None,
                    }
            return {
                "counters": {f"{n}{t}": v for (n, t), v in self._counters.items()},
                "gauges": {f"{n}{t}": v for (n, t), v in self._gauges.items()},
                "distributions": dists,
            }


class _Timer:
    def __init__(self, client: StatsClient, name: str, tags):
        self.client = client
        self.name = name
        self.tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.client.timing(self.name, time.perf_counter() - self._t0, self.tags)
        return False


class StatsdStatsClient(StatsClient):
    """StatsClient that additionally emits statsd UDP datagrams (reference
    stats/statsd/ backend; datadog-style |#tag:value extension)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa_tpu"):
        super().__init__(prefix)
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._addr = (host, port)

    def _emit(self, name: str, value, kind: str, tags: dict | None) -> None:
        tag_part = ""
        if tags:
            tag_part = "|#" + ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
        try:
            self._sock.sendto(
                f"{self.prefix}.{name}:{value}|{kind}{tag_part}".encode(),
                self._addr,
            )
        except OSError:
            pass  # stats must never disturb the engine

    def count(self, name, value=1, tags=None):
        super().count(name, value, tags)
        self._emit(name, value, "c", tags)

    def gauge(self, name, value, tags=None):
        super().gauge(name, value, tags)
        self._emit(name, value, "g", tags)

    def timing(self, name, seconds, tags=None):
        super().timing(name, seconds, tags)
        self._emit(name, round(seconds * 1e3, 3), "ms", tags)

    def observe(self, name, value, tags=None):
        super().observe(name, value, tags)
        self._emit(name, value, "h", tags)


class NopStatsClient(StatsClient):
    """Discards everything (reference stats.NopStatsClient)."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass


_global: StatsClient | None = None


def global_stats() -> StatsClient:
    global _global
    if _global is None:
        _global = StatsClient()
    return _global


def set_global_stats(client: StatsClient) -> None:
    global _global
    _global = client
