"""StatsClient: counters/gauges/timings threaded through the engine.

Reference: stats/stats.go (SURVEY.md §2 #23) — a StatsClient interface
(Count/Gauge/Histogram/Timing with tags) with statsd and nop backends and
expvar always on. Here: an in-memory client that renders Prometheus text
for GET /metrics (statsd export can be layered on the same interface),
plus a Nop client for tests.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque

# Bounded per-series sample window backing the exported p50/p95 lines —
# a sliding window, not a decaying histogram: ingest fan-out and batch
# sizes change regime abruptly (bulk load starts/stops), and a window
# forgets the old regime after SAMPLE_WINDOW observations.
SAMPLE_WINDOW = 256

# Cumulative histogram buckets (seconds) for every timing series: the
# windowed p50/p95 summary lines stay (human-readable, regime-fresh), and
# each timer ALSO exports stock-Prometheus `_bucket`/`_sum`/`_count`
# series under the `<name>_hist_seconds` family so a scrape can compute
# quantiles server-side (histogram_quantile) over any window. Log-spaced
# 1 ms → 10 s: the serving path lives in single-digit ms, repair/sync
# passes in seconds.
HISTOGRAM_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def escape_label(value) -> str:
    """Prometheus label-value escaping (exposition format §label
    values): backslash, double-quote, and newline must be escaped —
    client-controlled values (tenant headers, index names) interpolated
    unescaped would corrupt the whole /metrics page for every scraper."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_tags(tags: dict | None) -> str:
    if not tags:
        return ""
    # escape values: tag values include CLIENT-controlled strings (the
    # qos_shed tenant tag comes straight from X-Pilosa-Tenant), and one
    # embedded quote would corrupt the whole exposition page
    inner = ",".join(f'{k}="{escape_label(v)}"'
                     for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def _with_tag(tags_str: str, extra: str) -> str:
    """Splice one more label into an already-rendered tag block."""
    if not tags_str:
        return "{" + extra + "}"
    return tags_str[:-1] + "," + extra + "}"


def _quantile(samples, q: float) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _meta_lines(family: str, mtype: str, help_text: str | None,
                seen: set) -> list[str]:
    """`# HELP` + `# TYPE` for one metric family, emitted once per
    exposition (Prometheus text format §comments). ``seen`` dedupes
    families that appear with several tag sets."""
    if family in seen:
        return []
    seen.add(family)
    return [
        f"# HELP {family} {help_text or family.replace('_', ' ')}",
        f"# TYPE {family} {mtype}",
    ]


def prometheus_block(pairs: dict, prefix: str, subsystem: str = "",
                     help_map: dict | None = None,
                     seen: set | None = None) -> str:
    """Render a name→value dict as Prometheus lines WITH `# HELP`/`# TYPE`
    metadata: names ending in ``_total`` type as counters, everything
    else as gauges. Shared by every /metrics block the HTTP handler
    appends after the stats registry (serving, qos, wal, tracing), so
    exposition-format compliance lives in one place. ``seen`` dedupes
    family metadata ACROSS blocks: a family the registry already
    declared (e.g. the tagged ``qos_shed_total`` beside the block's
    untagged total) must not get a second TYPE line on the page."""
    seen = seen if seen is not None else set()
    lines: list[str] = []
    middle = f"{subsystem}_" if subsystem else ""
    for name, value in sorted(pairs.items()):
        family = f"{prefix}_{middle}{name}"
        mtype = "counter" if name.endswith("_total") else "gauge"
        lines.extend(_meta_lines(
            family, mtype, (help_map or {}).get(name), seen
        ))
        # ints emit exactly — %g would quantize large counters (byte
        # totals, request counts) to 6 significant digits and make
        # rate() stair-step (the residency exporter documented this
        # hazard first)
        rendered = value if isinstance(value, int) else f"{value:g}"
        lines.append(f"{family} {rendered}")
    return "\n".join(lines) + ("\n" if lines else "")


class StatsClient:
    """In-memory stats registry; thread-safe."""

    def __init__(self, prefix: str = "pilosa_tpu"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        # [count, sum, sample window, cumulative bucket counts] — the
        # window feeds the summary-quantile export, the buckets feed the
        # stock histogram export (one slot per HISTOGRAM_BUCKETS_S bound;
        # +Inf is implicit — it equals the count)
        self._timings: dict[tuple, list] = defaultdict(
            lambda: [0, 0.0, deque(maxlen=SAMPLE_WINDOW),
                     [0] * len(HISTOGRAM_BUCKETS_S)]
        )
        # unit-free distributions (batch sizes, fan-out widths): same
        # shape as _timings but rendered without the _seconds unit suffix
        self._observations: dict[tuple, list] = defaultdict(
            lambda: [0, 0.0, deque(maxlen=SAMPLE_WINDOW)]
        )

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        with self._lock:
            self._counters[(name, _fmt_tags(tags))] += value

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._gauges[(name, _fmt_tags(tags))] = value

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        with self._lock:
            entry = self._timings[(name, _fmt_tags(tags))]
            entry[0] += 1
            entry[1] += seconds
            entry[2].append(seconds)
            buckets = entry[3]
            for i, bound in enumerate(HISTOGRAM_BUCKETS_S):
                if seconds <= bound:
                    buckets[i] += 1
                    break

    def timer(self, name: str, tags: dict | None = None):
        return _Timer(self, name, tags)

    def histogram(self, name: str, value: float, tags: dict | None = None) -> None:
        self.timing(name, value, tags)

    def observe(self, name: str, value: float, tags: dict | None = None) -> None:
        """Record one sample of a unit-free distribution (batch size,
        fan-out width). Exported as count/sum/quantile lines without the
        _seconds suffix that timing() series carry."""
        with self._lock:
            entry = self._observations[(name, _fmt_tags(tags))]
            entry[0] += 1
            entry[1] += value
            entry[2].append(value)

    def quantile(self, name: str, q: float, tags: dict | None = None) -> float | None:
        """Windowed quantile of a timing or observation series (None if
        the series has no samples yet)."""
        key = (name, _fmt_tags(tags))
        with self._lock:
            entry = self._timings.get(key) or self._observations.get(key)
            samples = list(entry[2]) if entry else []
        return _quantile(samples, q) if samples else None

    def prometheus_text(self, seen: set | None = None) -> str:
        """Exposition-format render: every family leads with `# HELP` +
        `# TYPE` (counter/gauge/summary/histogram). Timers export BOTH
        the windowed summary (`X_seconds{quantile=}` + count/sum, regime-
        fresh p50/p95) and a cumulative stock histogram under the sibling
        `X_hist_seconds` family — same observations, two consumers: a
        human tailing /metrics and a Prometheus computing
        histogram_quantile over arbitrary windows. ``seen`` (shared with
        the page's other blocks) dedupes family metadata page-wide."""
        lines: list[str] = []
        seen = seen if seen is not None else set()
        with self._lock:
            for (name, tags), v in sorted(self._counters.items()):
                family = f"{self.prefix}_{name}_total"
                lines.extend(_meta_lines(family, "counter", None, seen))
                lines.append(f"{family}{tags} {v:g}")
            for (name, tags), v in sorted(self._gauges.items()):
                family = f"{self.prefix}_{name}"
                lines.extend(_meta_lines(family, "gauge", None, seen))
                lines.append(f"{family}{tags} {v:g}")
            for (name, tags), entry in sorted(self._timings.items()):
                n, total, samples, buckets = entry
                family = f"{self.prefix}_{name}_seconds"
                lines.extend(_meta_lines(
                    family, "summary",
                    f"{name} latency (windowed p50/p95 over the last "
                    f"{SAMPLE_WINDOW} samples)", seen,
                ))
                lines.append(f"{family}_count{tags} {n:g}")
                lines.append(f"{family}_sum{tags} {total:g}")
                for q in (0.5, 0.95):
                    if samples:
                        qt = _with_tag(tags, f'quantile="{q}"')
                        lines.append(
                            f"{family}{qt} {_quantile(samples, q):g}"
                        )
                hist = f"{self.prefix}_{name}_hist_seconds"
                lines.extend(_meta_lines(
                    hist, "histogram",
                    f"{name} latency (cumulative histogram)", seen,
                ))
                acc = 0
                for bound, count in zip(HISTOGRAM_BUCKETS_S, buckets):
                    acc += count
                    bt = _with_tag(tags, f'le="{bound:g}"')
                    lines.append(f"{hist}_bucket{bt} {acc:g}")
                bt = _with_tag(tags, 'le="+Inf"')
                lines.append(f"{hist}_bucket{bt} {n:g}")
                lines.append(f"{hist}_sum{tags} {total:g}")
                lines.append(f"{hist}_count{tags} {n:g}")
            for (name, tags), (n, total, samples) in sorted(
                self._observations.items()
            ):
                family = f"{self.prefix}_{name}"
                lines.extend(_meta_lines(
                    family, "summary",
                    f"{name} distribution (windowed p50/p95)", seen,
                ))
                lines.append(f"{family}_count{tags} {n:g}")
                lines.append(f"{family}_sum{tags} {total:g}")
                for q in (0.5, 0.95):
                    if samples:
                        qt = _with_tag(tags, f'quantile="{q}"')
                        lines.append(
                            f"{family}{qt} {_quantile(samples, q):g}"
                        )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        with self._lock:
            dists = {}
            for source in (self._timings, self._observations):
                for (n, t), entry in source.items():
                    count, total, samples = entry[0], entry[1], entry[2]
                    dists[f"{n}{t}"] = {
                        "count": count, "sum": total,
                        "p50": _quantile(samples, 0.5) if samples else None,
                        "p95": _quantile(samples, 0.95) if samples else None,
                    }
            return {
                "counters": {f"{n}{t}": v for (n, t), v in self._counters.items()},
                "gauges": {f"{n}{t}": v for (n, t), v in self._gauges.items()},
                "distributions": dists,
            }


class _Timer:
    def __init__(self, client: StatsClient, name: str, tags):
        self.client = client
        self.name = name
        self.tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.client.timing(self.name, time.perf_counter() - self._t0, self.tags)
        return False


class StatsdStatsClient(StatsClient):
    """StatsClient that additionally emits statsd UDP datagrams (reference
    stats/statsd/ backend; datadog-style |#tag:value extension)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa_tpu"):
        super().__init__(prefix)
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._addr = (host, port)

    def _emit(self, name: str, value, kind: str, tags: dict | None) -> None:
        tag_part = ""
        if tags:
            tag_part = "|#" + ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
        try:
            self._sock.sendto(
                f"{self.prefix}.{name}:{value}|{kind}{tag_part}".encode(),
                self._addr,
            )
        except OSError:
            pass  # stats must never disturb the engine

    def count(self, name, value=1, tags=None):
        super().count(name, value, tags)
        self._emit(name, value, "c", tags)

    def gauge(self, name, value, tags=None):
        super().gauge(name, value, tags)
        self._emit(name, value, "g", tags)

    def timing(self, name, seconds, tags=None):
        super().timing(name, seconds, tags)
        self._emit(name, round(seconds * 1e3, 3), "ms", tags)

    def observe(self, name, value, tags=None):
        super().observe(name, value, tags)
        self._emit(name, value, "h", tags)


class NopStatsClient(StatsClient):
    """Discards everything (reference stats.NopStatsClient)."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass


_global: StatsClient | None = None


def global_stats() -> StatsClient:
    global _global
    if _global is None:
        _global = StatsClient()
    return _global


def set_global_stats(client: StatsClient) -> None:
    global _global
    _global = client
