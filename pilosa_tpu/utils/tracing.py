"""Distributed tracing: context-propagating sampled spans + query inspector.

Reference: tracing/tracing.go (SURVEY.md §2 #24) — upstream wraps a global
OpenTracing tracer (Jaeger) so every request carries a span context across
goroutines and RPC hops. The r6 port was a thread-local stub: every span
started on a pool thread was orphaned and nothing crossed a node. This
rewrite is the real thing, sized for the serving planes PRs 1-6 built:

- **contextvars, not thread-locals**: the active span rides
  ``contextvars``, and every cross-thread handoff in the system — the
  ``utils.pool`` fan-outs, the serving pipeline's wave queue, hedge legs,
  the wave batcher — captures the submitting context and restores it on
  the worker, so a span started anywhere lands in its request's tree.
- **Sampling, zero-cost off**: ``sample_rate`` (0..1) decides per REQUEST
  ROOT. Rate 0 returns a shared no-op handle — no allocation, no context
  write. Child spans never re-sample: they join the active trace or no-op.
- **Cross-node propagation**: internal hops carry
  ``X-Pilosa-Trace: <trace_id>:<parent_span_id>``; the callee roots a
  remote span under that parent and (for query hops) returns its finished
  subtree in the response, so the coordinator's ``/debug/traces`` renders
  ONE tree spanning the cluster.
- **In-flight inspector**: ``QueryTracker`` (always on, lock-free stage
  updates) backs ``GET /debug/queries`` — upstream's long-running-query
  view: trace id, PQL, index, age, current stage, shards outstanding.

On TPU the device-side story stays the JAX profiler; ``start_jax_trace``
wraps ``jax.profiler`` and is exposed live at ``POST /debug/trace-device``.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
from collections import deque

# Request header carrying trace context on internal hops
# (cluster_exec sub-queries, wave batches, sync manifest/blocks).
TRACE_HEADER = "X-Pilosa-Trace"


def _new_trace_id() -> str:
    return f"{random.getrandbits(64):016x}"


def _new_span_id() -> str:
    return f"{random.getrandbits(48):012x}"


class Span:
    """One timed operation in a trace tree.

    ``children`` may be appended from several threads (list.append is
    atomic under the GIL); ``to_json`` snapshots. ``remote`` holds
    already-serialized subtrees returned by peers over the wire — they
    render as children with their own (peer-assigned) span ids whose
    ``parentId`` is this span's id."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "parent",
                 "start", "end", "tags", "children", "remote")

    def __init__(self, name: str, tags: dict | None = None,
                 trace_id: str | None = None, parent: "Span | None" = None,
                 parent_id: str | None = None):
        self.name = name
        self.trace_id = trace_id or _new_trace_id()
        self.span_id = _new_span_id()
        self.parent = parent
        self.parent_id = parent.span_id if parent is not None else parent_id
        self.start = time.perf_counter()
        self.end = None
        self.tags = tags if tags is not None else {}
        self.children: list[Span] = []
        self.remote: list[dict] = []

    @property
    def duration(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def finish(self) -> None:
        if self.end is None:
            self.end = time.perf_counter()

    def root(self) -> "Span":
        s = self
        while s.parent is not None:
            s = s.parent
        return s

    def add_remote(self, subtree: dict) -> None:
        """Attach a peer's serialized span subtree under this span."""
        if isinstance(subtree, dict):
            self.remote.append(subtree)

    def header_value(self) -> str:
        """This span as an ``X-Pilosa-Trace`` value (child hops parent
        to it)."""
        return f"{self.trace_id}:{self.span_id}"

    def to_json(self) -> dict:
        out = {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "durationMs": round(self.duration * 1e3, 3),
            "tags": self.tags,
            "children": ([c.to_json() for c in list(self.children)]
                         + list(self.remote)),
        }
        if self.parent_id is not None:
            out["parentId"] = self.parent_id
        return out


def parse_trace_header(value: str | None):
    """``"<trace_id>:<span_id>"`` → tuple, or None when absent/malformed
    (a malformed header must degrade to untraced, never 500)."""
    if not value:
        return None
    parts = value.strip().split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]


# The active span of the current logical request. None = not in a trace;
# _NOT_SAMPLED = the request's root made a negative sampling decision, so
# inner span sites must not re-sample their own roots.
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "pilosa_tpu_trace_span", default=None
)
_NOT_SAMPLED = object()


def current_span() -> Span | None:
    cur = _current_span.get()
    return cur if isinstance(cur, Span) else None


class _NopHandle:
    """Shared no-op span handle: tracing off (or unsampled subtree) costs
    one contextvar read and zero allocations."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOP = _NopHandle()


class _SpanHandle:
    """Context manager activating one span in the current context."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._token = _current_span.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        span = self._span
        span.finish()
        if exc is not None and "error" not in span.tags:
            span.tags["error"] = str(exc) or exc_type.__name__
        _current_span.reset(self._token)
        if span.parent is None:
            self._tracer._record_root(span)
        return False


class _SuppressHandle:
    """Marks the request NOT SAMPLED for its whole context, so inner span
    sites (executor.Execute, remote legs) cannot root their own traces."""

    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _current_span.set(_NOT_SAMPLED)
        return None

    def __exit__(self, *exc):
        _current_span.reset(self._token)
        return False


@contextlib.contextmanager
def use_span(span: Span):
    """Re-activate an existing span in this context (the query-batch
    receiver runs one item's submit and resolve phases at different
    points of its loop)."""
    token = _current_span.set(span)
    try:
        yield span
    finally:
        _current_span.reset(token)


class Tracer:
    """Sampled, context-propagating tracer; keeps the last N root trees."""

    def __init__(self, enabled: bool = False, keep: int = 64,
                 sample_rate: float | None = None):
        # legacy constructor surface: enabled=True meant always-on
        self.sample_rate = (sample_rate if sample_rate is not None
                            else (1.0 if enabled else 0.0))
        self.keep = keep
        self._lock = threading.Lock()
        self.finished: deque = deque(maxlen=keep)
        self.sampled_traces = 0
        self.spans_started = 0

    # legacy boolean surface (server config `tracing = true`, old tests)
    @property
    def enabled(self) -> bool:
        return self.sample_rate > 0.0

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self.sample_rate = 1.0 if value else 0.0

    # ------------------------------------------------------------ span sites

    def span(self, name: str, **tags):
        """Child span joining the active trace; no-op outside one.

        Join-only by design: instrumentation sites scattered through the
        planes (conn.checkout, wal.barrier, device.dispatch, ...) must
        never root standalone trees off background traffic — only the
        designated root sites (``request_root``, ``remote_root``,
        ``root_span``) start traces."""
        cur = _current_span.get()
        if cur is None or cur is _NOT_SAMPLED:
            return _NOP
        self.spans_started += 1
        span = Span(name, tags, trace_id=cur.trace_id, parent=cur)
        cur.children.append(span)
        return _SpanHandle(self, span)

    def root_span(self, name: str, **tags):
        """Join the active trace, or — outside one — ROOT a new trace
        subject to sampling. For sites that ARE a sensible trace root
        when reached directly: ``executor.Execute`` (in-process callers,
        tests, CLI) and ``sync.pass`` (the anti-entropy ticker)."""
        cur = _current_span.get()
        if cur is None:
            return self._maybe_root(name, tags)
        return self.span(name, **tags)

    def request_root(self, name: str, **tags):
        """Root span site for an EDGE request: samples once, and on a
        negative decision suppresses sampling for the whole request so
        exactly zero or one tree exists per request."""
        cur = _current_span.get()
        if isinstance(cur, Span):  # nested (in-process client re-entry)
            return self.span(name, **tags)
        rate = self.sample_rate
        if rate <= 0.0:
            return _NOP
        if rate < 1.0 and random.random() >= rate:
            return _SuppressHandle()
        self.sampled_traces += 1
        self.spans_started += 1
        return _SpanHandle(self, Span(name, tags))

    def remote_span(self, header_value: str | None, name: str,
                    **tags) -> Span | None:
        """A DETACHED remote-rooted span for split-phase work: the
        query-batch receiver runs one item's submit and resolve at
        different points of its loop, re-activating the span with
        ``use_span`` each time. Returns None when the header is absent
        or malformed. Close with ``finish_root``. Single-phase handlers
        should use ``remote_root`` (the context-manager form) instead —
        both keep root-span lifecycle accounting inside this class."""
        parsed = parse_trace_header(header_value)
        if parsed is None:
            return None
        self.spans_started += 1
        return Span(name, tags, trace_id=parsed[0], parent_id=parsed[1])

    def finish_root(self, span: Span) -> None:
        """End a detached root span (``remote_span``) and record it in
        the finished ring."""
        span.finish()
        self._record_root(span)

    def remote_root(self, header_value: str | None, name: str, **tags):
        """Root span for a remote hop carrying ``X-Pilosa-Trace``. The
        coordinator already sampled, so the callee always traces when the
        header parses; without one, local sampling is SUPPRESSED — a
        remote sub-query belongs to its root's decision either way."""
        parsed = parse_trace_header(header_value)
        if parsed is None:
            return _SuppressHandle()
        trace_id, parent_id = parsed
        self.spans_started += 1
        return _SpanHandle(
            self, Span(name, tags, trace_id=trace_id, parent_id=parent_id)
        )

    def _maybe_root(self, name: str, tags: dict):
        rate = self.sample_rate
        if rate <= 0.0:
            return _NOP
        if rate < 1.0 and random.random() >= rate:
            return _NOP
        self.sampled_traces += 1
        self.spans_started += 1
        return _SpanHandle(self, Span(name, tags))

    # -------------------------------------------------------------- finished

    def _record_root(self, span: Span) -> None:
        self.finished.append(span)  # deque(maxlen): atomic, bounded

    def record_foreign_tree(self, tree: dict) -> None:
        """Record an ALREADY-SERIALIZED finished tree — a serving
        worker's edge span (its own process rooted and finished it, the
        owner-side subtree already grafted) shipped over the handshake
        channel so this process's /debug/traces shows one tree per
        request whatever the deployment shape."""
        if isinstance(tree, dict):
            self.sampled_traces += 1
            self.finished.append(_ForeignTree(tree))

    def recent(self) -> list[dict]:
        return [s.to_json() for s in list(self.finished)]

    def clear(self) -> None:
        self.finished.clear()
        self.sampled_traces = 0
        self.spans_started = 0

    def metrics(self) -> dict:
        return {
            "tracing_sampled_traces_total": self.sampled_traces,
            "tracing_spans_total": self.spans_started,
            "tracing_finished_traces": len(self.finished),
            "tracing_sample_rate": self.sample_rate,
        }


class _ForeignTree:
    """A finished span tree serialized by ANOTHER process (serving
    worker); quacks like a Span for the finished ring."""

    __slots__ = ("tree",)

    def __init__(self, tree: dict):
        self.tree = tree

    def to_json(self) -> dict:
        return self.tree


_global_tracer: Tracer | None = None


def global_tracer() -> Tracer:
    global _global_tracer
    if _global_tracer is None:
        _global_tracer = Tracer()
    return _global_tracer


def set_global_tracer(tracer: Tracer) -> None:
    global _global_tracer
    _global_tracer = tracer


# ------------------------------------------------------ in-flight inspector


class InflightQuery:
    """One live query's inspector record. ``stage`` and
    ``shards_outstanding`` are plain attribute writes (no lock): the
    writers are the query's own threads and readers tolerate tearing —
    this is a debugging view, not an accounting ledger."""

    __slots__ = ("qid", "trace_id", "index", "pql", "tenant", "remote",
                 "started", "started_wall", "stage", "shards_outstanding")

    def __init__(self, qid: int, index: str, pql: str, tenant: str,
                 remote: bool, trace_id: str | None):
        self.qid = qid
        self.trace_id = trace_id
        self.index = index
        self.pql = pql
        self.tenant = tenant
        self.remote = remote
        self.started = time.perf_counter()
        self.started_wall = time.time()
        self.stage = "start"
        self.shards_outstanding: int | None = None

    def to_json(self) -> dict:
        out = {
            "id": self.qid,
            "index": self.index,
            "pql": self.pql,
            "tenant": self.tenant,
            "remote": self.remote,
            "ageSeconds": round(time.perf_counter() - self.started, 4),
            "stage": self.stage,
        }
        if self.trace_id is not None:
            out["traceId"] = self.trace_id
        if self.shards_outstanding is not None:
            out["shardsOutstanding"] = self.shards_outstanding
        return out


_current_query: contextvars.ContextVar = contextvars.ContextVar(
    "pilosa_tpu_inflight_query", default=None
)


def current_query() -> InflightQuery | None:
    """The inspector record of the query owning this context (rides the
    same capture-and-restore hops as the trace context), so deep layers
    (cluster fan-out) can update stage/shards without plumbing."""
    return _current_query.get()


class QueryTracker:
    """Registry of in-flight queries behind ``GET /debug/queries``.

    Always on by default — the long-running-query view matters exactly
    when something is stuck, regardless of trace sampling. Cost per query
    is one lock round trip each for start/finish; ``enabled = False``
    turns even that off (the bench's bare baseline)."""

    def __init__(self):
        self.enabled = True
        self._lock = threading.Lock()
        self._live: dict[int, InflightQuery] = {}
        self._next = 0
        self.started_total = 0

    def start(self, index: str, pql, tenant: str = "default",
              remote: bool = False) -> InflightQuery | None:
        if not self.enabled:
            return None
        cur = current_span()
        q = InflightQuery(
            0, index,
            (pql[:1024] if isinstance(pql, str) else str(pql)[:1024]),
            tenant, remote, cur.trace_id if cur is not None else None,
        )
        with self._lock:
            self._next += 1
            q.qid = self._next
            self.started_total += 1
            self._live[q.qid] = q
        return q

    def activate(self, q: InflightQuery):
        """Bind ``q`` to the current context; returns a reset token."""
        return _current_query.set(q)

    def finish(self, q: InflightQuery | None, token=None) -> None:
        if q is None:
            return
        if token is not None:
            _current_query.reset(token)
        with self._lock:
            self._live.pop(q.qid, None)

    def snapshot(self) -> list[dict]:
        with self._lock:
            live = list(self._live.values())
        return [q.to_json() for q in
                sorted(live, key=lambda q: q.started)]

    def metrics(self) -> dict:
        with self._lock:
            return {
                "inflight_queries": len(self._live),
                "queries_tracked_total": self.started_total,
            }


_global_query_tracker: QueryTracker | None = None


def global_query_tracker() -> QueryTracker:
    global _global_query_tracker
    if _global_query_tracker is None:
        _global_query_tracker = QueryTracker()
    return _global_query_tracker


# ----------------------------------------------------------- device tracing


@contextlib.contextmanager
def start_jax_trace(log_dir: str):
    """Capture an XLA/JAX profiler trace around a block (TPU-side tracing;
    view with xprof/tensorboard). Live capture around real traffic is
    exposed at ``POST /debug/trace-device?secs=N`` (server/http.py)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
