"""Tracing: spans through handler → execute → per-shard map.

Reference: tracing/tracing.go (SURVEY.md §2 #24) — a global tracer wrapper
(OpenTracing + Jaeger upstream). Here: an in-process tracer recording span
trees with wall times, exportable as JSON (and gated to zero overhead when
disabled). On TPU the device-side story is the JAX profiler; start_jax_trace
wraps ``jax.profiler`` so a query's XLA execution can be captured alongside
host spans.
"""

from __future__ import annotations

import contextlib
import threading
import time


class Span:
    __slots__ = ("name", "start", "end", "tags", "children")

    def __init__(self, name: str, tags: dict | None = None):
        self.name = name
        self.start = time.perf_counter()
        self.end = None
        self.tags = tags or {}
        self.children: list[Span] = []

    @property
    def duration(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "durationMs": round(self.duration * 1e3, 3),
            "tags": self.tags,
            "children": [c.to_json() for c in self.children],
        }


class Tracer:
    """Per-thread span stacks; keeps the last N finished root spans."""

    def __init__(self, enabled: bool = False, keep: int = 64):
        self.enabled = enabled
        self.keep = keep
        self._local = threading.local()
        self._lock = threading.Lock()
        self.finished: list[Span] = []

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        if not self.enabled:
            yield None
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        s = Span(name, tags)
        if stack:
            stack[-1].children.append(s)
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.perf_counter()
            stack.pop()
            if not stack:
                with self._lock:
                    self.finished.append(s)
                    del self.finished[: -self.keep]

    def recent(self) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in self.finished]


_global_tracer: Tracer | None = None


def global_tracer() -> Tracer:
    global _global_tracer
    if _global_tracer is None:
        _global_tracer = Tracer()
    return _global_tracer


def set_global_tracer(tracer: Tracer) -> None:
    global _global_tracer
    _global_tracer = tracer


@contextlib.contextmanager
def start_jax_trace(log_dir: str):
    """Capture an XLA/JAX profiler trace around a block (TPU-side tracing;
    view with xprof/tensorboard)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
