"""Infra utilities: stats, tracing, logging (reference L1 — SURVEY.md §1)."""
