"""Infra utilities: stats, tracing, logging (reference L1 — SURVEY.md §1)."""


def as_int_list(seq) -> list:
    """Python ints from any id sequence. Routed imports hand numpy
    slices straight to the wire encoders; ``.tolist()`` converts the
    whole buffer in C, where a per-element ``int()`` loop costs more
    than the HTTP frame on large batches. Shared by the JSON
    (parallel/client.py) and protobuf (wire/serializer.py) encode paths
    so the fast path cannot drift between them."""
    tolist = getattr(seq, "tolist", None)
    if tolist is not None:
        return tolist()
    return [int(v) for v in seq]
