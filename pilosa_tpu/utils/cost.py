"""Query cost plane: per-request cost context, PQL PROFILE trees, and
the per-tenant CostLedger.

PR 7's tracing answers *where time goes*; this plane answers *who spends
it and on what*. Three consumers share one collection pipeline:

- **CostContext** — one per edge request, activated on a contextvar that
  rides every cross-thread handoff the tracer already rides (utils/pool,
  the serving pipeline's wave queue, hedge legs). Instrumented sites
  (device dispatch, residency lookups, roaring container decodes) do ONE
  contextvar read and a few attribute adds; with the plane disabled
  (``set_cost_enabled(False)``, the bench's bare baseline) the read
  returns None and the site costs a predicate.
- **QueryProfile** — built only when the request asked ``profile=true``:
  a per-AST-node tree (wall/device ms, shards, containers scanned by
  type, rows materialized, cache hits, bytes moved) assembled
  cluster-wide by grafting each remote leg's returned profile the way
  the tracer grafts span subtrees (docs/OBSERVABILITY.md).
- **CostLedger** — always-on per-(tenant, index) accounting (queries,
  device-ms, container scans, ingest rows, egress bytes) behind
  ``GET /debug/tenants`` and the ``tenant_*`` metrics block.

The cost model follows the roaring container taxonomy (Chambi et al.
1402.6407; Lemire et al. 1709.07821): array/bitmap/run containers
touched on the decode path plus result cardinality are cheap to count
exactly and predict device cost well — decodes happen only on residency
misses, so steady-state hot queries pay no per-container accounting.
"""

from __future__ import annotations

import contextvars
import threading

# Global kill switch (bench baselines): current_cost() returns None and
# new_cost_context() refuses, so every instrumented site degrades to one
# predicate. Shipping default is ON — the ledger and heat map are the
# always-on accounting surfaces.
_enabled = True


def set_cost_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


def cost_enabled() -> bool:
    return _enabled


_cost_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "pilosa_tpu_cost_ctx", default=None
)


def current_cost() -> "CostContext | None":
    """The active request's cost context (None when the plane is off or
    outside a request). One contextvar read — the instrumented sites'
    whole fast-path cost."""
    return _cost_ctx.get() if _enabled else None


class ProfileNode:
    """One AST node's execution profile. Structure mirrors the parsed
    Call tree; measured counters land on the node ACTIVE while the work
    ran (the executing call for fused kernels — leaf-level detail rides
    the ``leaves`` list, one record per resolved device operand)."""

    __slots__ = ("name", "pql", "wall_s", "device_s", "dispatches",
                 "max_batch", "shards", "c_array", "c_bitmap", "c_run",
                 "row_cache_hits", "row_cache_misses", "plan_cache_hit",
                 "operand_memo_hit", "rows_materialized", "device_bytes",
                 "reduce_dense_bytes", "reduce_actual_bytes",
                 "reduce_quant_bytes", "children", "leaves")

    def __init__(self, name: str, pql: str = ""):
        self.name = name
        self.pql = pql
        self.wall_s = 0.0
        self.device_s = 0.0
        self.dispatches = 0
        self.max_batch = 0
        self.shards = 0
        self.c_array = 0
        self.c_bitmap = 0
        self.c_run = 0
        self.row_cache_hits = 0
        self.row_cache_misses = 0
        self.plan_cache_hit = False
        self.operand_memo_hit = False
        self.rows_materialized = 0
        self.device_bytes = 0
        self.reduce_dense_bytes = 0
        self.reduce_actual_bytes = 0
        self.reduce_quant_bytes = 0
        # static AST skeleton (ready-to-emit dicts, shared via the
        # skeleton memo — never mutated)
        self.children: list[dict] = []
        self.leaves: list[dict] = []

    def to_json(self) -> dict:
        out = {
            "name": self.name,
            "pql": self.pql,
            "wallMs": round(self.wall_s * 1e3, 3),
            "deviceMs": round(self.device_s * 1e3, 3),
            "dispatches": self.dispatches,
            "maxDispatchBatch": self.max_batch,
            "shards": self.shards,
            "containers": {"array": self.c_array, "bitmap": self.c_bitmap,
                           "run": self.c_run},
            "rowsMaterialized": self.rows_materialized,
            "rowCacheHits": self.row_cache_hits,
            "rowCacheMisses": self.row_cache_misses,
            "planCacheHit": self.plan_cache_hit,
            "operandMemoHit": self.operand_memo_hit,
            "bytesMoved": self.device_bytes,
        }
        if self.reduce_dense_bytes:
            # hierarchical reduction plane engaged (parallel/reduction.py):
            # what the flat dense path would have moved vs the encoded
            # inter-group lane this dispatch actually paid for
            out["reduceBytes"] = {"denseEquiv": self.reduce_dense_bytes,
                                  "actual": self.reduce_actual_bytes}
            if self.reduce_quant_bytes:
                # portion of `actual` that crossed on the 8-bit EQuARX
                # ranking lane (topn-quantized-ranking)
                out["reduceBytes"]["quantized"] = self.reduce_quant_bytes
        if self.leaves:
            out["leaves"] = self.leaves
        if self.children:
            out["children"] = self.children
        return out


def _call_pql(call) -> str:
    try:
        return call.to_pql()[:512]
    except Exception:
        return str(getattr(call, "name", call))[:512]


def _ast_children_json(call) -> list[dict]:
    """Static child skeleton of a Call tree, as ready-to-emit dicts: the
    compiler fuses children into one kernel, so child nodes carry
    structure (name + PQL fragment) while measured counters land on the
    executing ancestor."""
    return [
        {"name": child.name, "pql": _call_pql(child),
         "children": _ast_children_json(child)}
        for child in getattr(call, "children", ()) or ()
    ]


# parse() memoizes query text to one immutable Call tree, so the static
# skeleton (children dicts + top-level PQL render) keys by identity —
# repeat profiled queries skip the to_pql walk. Cleared wholesale at the
# bound (same policy as the executor's plan cache); entries carry the
# Call so id() reuse after GC cannot alias.
_SKELETON_MEMO: dict[int, tuple] = {}
_SKELETON_MEMO_MAX = 1024


def _call_skeleton(call) -> tuple[str, list]:
    key = id(call)
    hit = _SKELETON_MEMO.get(key)
    if hit is not None and hit[0] is call:
        return hit[1], hit[2]
    pql = _call_pql(call)
    children = _ast_children_json(call)
    if len(_SKELETON_MEMO) >= _SKELETON_MEMO_MAX:
        _SKELETON_MEMO.clear()
    _SKELETON_MEMO[key] = (call, pql, children)
    return pql, children


class QueryProfile:
    """Per-request PROFILE assembly: one ProfileNode per top-level call
    (created lazily by position so the submit phase on the pipeline
    dispatcher and the resolve phase on the request thread address the
    SAME node), plus remote grafts — each cluster leg's returned profile
    attached under the node that paid for the hop."""

    def __init__(self, index: str, pql: str, node_id: str = "local"):
        self.index = index
        self.pql = pql if isinstance(pql, str) else str(pql)
        self.node_id = node_id
        self._lock = threading.Lock()
        self._calls: dict[int, ProfileNode] = {}
        self.remote: list[dict] = []
        # serving-wave facts (set by server/pipeline.py): a dedupe hit
        # means this request rode an identical wavemate's execution —
        # the honest explanation for a near-zero tree. result_cache_hit
        # is its cross-wave sibling (serving/rescache.py): the request
        # was answered from pre-serialized cached bytes, no execution
        # at all (the API emits a stub tree with the flag set).
        self.wave_size = 1
        self.dedupe_hit = False
        self.result_cache_hit = False

    def node_for(self, i: int, call) -> ProfileNode:
        with self._lock:
            node = self._calls.get(i)
            if node is None:
                pql, children = _call_skeleton(call)
                node = ProfileNode(getattr(call, "name", "call"), pql)
                node.children = children
                self._calls[i] = node
            return node

    def add_remote(self, node_id: str, shards: int, subtree: dict) -> None:
        """Graft one remote leg's finished profile (the peer's own
        QueryProfile.to_json()) — list.append is atomic under the GIL."""
        if isinstance(subtree, dict):
            self.remote.append(
                {"node": node_id, "shards": shards, "profile": subtree}
            )

    def to_json(self, ctx: "CostContext | None" = None) -> dict:
        with self._lock:
            calls = [self._calls[i].to_json()
                     for i in sorted(self._calls)]
        out = {
            "node": self.node_id,
            "index": self.index,
            "pql": self.pql[:1024],
            "wave": self.wave_size,
            "dedupeHit": self.dedupe_hit,
            "resultCacheHit": self.result_cache_hit,
            "calls": calls,
            "remote": list(self.remote),
        }
        if ctx is not None:
            out["totals"] = ctx.totals()
        return out


class CostContext:
    """Per-request cost accumulator. Writers are the request's own
    threads (the pipeline ships the request's context to the dispatcher
    and back, so submit/resolve phases are sequential for one request);
    plain attribute adds, no lock — this feeds an accounting ledger and
    a debugging profile, not a correctness invariant."""

    __slots__ = ("tenant", "index", "device_s", "dispatches", "shards",
                 "c_array", "c_bitmap", "c_run", "row_cache_hits",
                 "row_cache_misses", "plan_cache_hits", "plan_cache_misses",
                 "rows_materialized", "device_bytes", "reduce_dense_bytes",
                 "reduce_actual_bytes", "reduce_quant_bytes", "profile",
                 "current")

    def __init__(self, tenant: str = "default", index: str = "",
                 profile: QueryProfile | None = None):
        self.tenant = tenant
        self.index = index
        self.device_s = 0.0
        self.dispatches = 0
        self.shards = 0
        self.c_array = 0
        self.c_bitmap = 0
        self.c_run = 0
        self.row_cache_hits = 0
        self.row_cache_misses = 0
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.rows_materialized = 0
        self.device_bytes = 0
        self.reduce_dense_bytes = 0
        self.reduce_actual_bytes = 0
        self.reduce_quant_bytes = 0
        self.profile = profile
        self.current: ProfileNode | None = None

    # ------------------------------------------------------- site helpers

    def note_dispatch(self, seconds: float, batch: int = 1) -> None:
        self.device_s += seconds
        self.dispatches += 1
        node = self.current
        if node is not None:
            node.device_s += seconds
            node.dispatches += 1
            if batch > node.max_batch:
                # mirrors the span's batch= tag: a flushed micro-batch's
                # inflated deviceMs is explained by the shared size
                node.max_batch = batch

    def note_shards(self, n: int) -> None:
        self.shards += n
        node = self.current
        if node is not None:
            node.shards += n

    def note_containers(self, array: int, bitmap: int, run: int) -> None:
        self.c_array += array
        self.c_bitmap += bitmap
        self.c_run += run
        node = self.current
        if node is not None:
            node.c_array += array
            node.c_bitmap += bitmap
            node.c_run += run

    def note_cache(self, hit: bool) -> None:
        if hit:
            self.row_cache_hits += 1
        else:
            self.row_cache_misses += 1
        node = self.current
        if node is not None:
            if hit:
                node.row_cache_hits += 1
            else:
                node.row_cache_misses += 1

    def note_upload(self, nbytes: int) -> None:
        self.device_bytes += nbytes
        node = self.current
        if node is not None:
            node.device_bytes += nbytes

    def note_rows(self, n: int) -> None:
        self.rows_materialized += n
        node = self.current
        if node is not None:
            node.rows_materialized += n

    def note_reduce(self, dense: int, actual: int,
                    quantized: int = 0) -> None:
        """One reduction-lane crossing on the hierarchical mesh
        (parallel/reduction.py): flat dense-equivalent bytes vs the
        encoded bytes actually modeled on the inter-group wire.
        ``quantized`` marks the portion of ``actual`` that crossed on
        the 8-bit EQuARX ranking lane."""
        self.reduce_dense_bytes += dense
        self.reduce_actual_bytes += actual
        self.reduce_quant_bytes += quantized
        node = self.current
        if node is not None:
            node.reduce_dense_bytes += dense
            node.reduce_actual_bytes += actual
            node.reduce_quant_bytes += quantized

    def note_plan(self, hit: bool) -> None:
        if hit:
            self.plan_cache_hits += 1
        else:
            self.plan_cache_misses += 1
        node = self.current
        if node is not None:
            node.plan_cache_hit = hit

    def container_scans(self) -> int:
        return self.c_array + self.c_bitmap + self.c_run

    def totals(self) -> dict:
        out = {
            "deviceMs": round(self.device_s * 1e3, 3),
            "dispatches": self.dispatches,
            "shards": self.shards,
            "containers": {"array": self.c_array, "bitmap": self.c_bitmap,
                           "run": self.c_run},
            "rowCacheHits": self.row_cache_hits,
            "rowCacheMisses": self.row_cache_misses,
            "planCacheHits": self.plan_cache_hits,
            "planCacheMisses": self.plan_cache_misses,
            "rowsMaterialized": self.rows_materialized,
            "bytesMoved": self.device_bytes,
        }
        if self.reduce_dense_bytes:
            out["reduceBytes"] = {"denseEquiv": self.reduce_dense_bytes,
                                  "actual": self.reduce_actual_bytes}
            if self.reduce_quant_bytes:
                out["reduceBytes"]["quantized"] = self.reduce_quant_bytes
        return out


class _NodeScope:
    """Activate one profile node as the context's attribution target for
    a block (per-call submit/resolve phases)."""

    __slots__ = ("_ctx", "_node", "_prev")

    def __init__(self, ctx: CostContext, node: ProfileNode | None):
        self._ctx = ctx
        self._node = node

    def __enter__(self):
        self._prev = self._ctx.current
        self._ctx.current = self._node
        return self._node

    def __exit__(self, *exc):
        self._ctx.current = self._prev
        return False


def use_node(ctx: CostContext | None, node: ProfileNode | None):
    if ctx is None:
        return _NOP_SCOPE
    return _NodeScope(ctx, node)


class _NopScope:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOP_SCOPE = _NopScope()


def new_cost_context(tenant: str, index: str,
                     profile: QueryProfile | None = None
                     ) -> CostContext | None:
    if not _enabled:
        return None
    return CostContext(tenant, index, profile)


def activate_cost(ctx: CostContext | None):
    """Bind ``ctx`` on the contextvar; returns a reset token (None when
    ctx is None — finish_cost handles both)."""
    if ctx is None:
        return None
    return _cost_ctx.set(ctx)


def deactivate_cost(token) -> None:
    if token is not None:
        _cost_ctx.reset(token)


# ---------------------------------------------------------------- ledger


# Ledger counter names, in snapshot/export order. New columns append
# (the fold indexes below are positional).
_LEDGER_KEYS = ("queries", "errors", "wall_ms", "device_ms",
                "container_scans", "row_cache_misses", "rows_materialized",
                "ingest_rows", "egress_bytes", "result_cache_hits")

# Bounded tenant-pair cardinality: a tenant-id flood must not grow the
# ledger (or the /metrics page) without bound; overflow lands in one
# aggregate bucket so the totals stay exact.
LEDGER_MAX_PAIRS = 512
_OVERFLOW = ("__other__", "__other__")


class CostLedger:
    """Per-(tenant, index) usage accounting — the quota/capacity view.

    Low overhead by construction: one lock round trip per REQUEST (not
    per sample) — the request's CostContext accumulated everything
    lock-free, and ``record_query`` folds it in with one dict update."""

    def __init__(self, max_pairs: int = LEDGER_MAX_PAIRS):
        self._lock = threading.Lock()
        self._t: dict[tuple[str, str], list] = {}
        self.max_pairs = max_pairs

    def _entry(self, tenant: str, index: str) -> list:
        key = (tenant, index)
        e = self._t.get(key)
        if e is None:
            if len(self._t) >= self.max_pairs:
                key = _OVERFLOW
                e = self._t.get(key)
                if e is not None:
                    return e
            e = self._t[key] = [0] * len(_LEDGER_KEYS)
        return e

    def record_query(self, tenant: str, index: str,
                     ctx: CostContext | None, elapsed_s: float,
                     error: bool = False,
                     result_cache_hit: bool = False) -> None:
        """``result_cache_hit`` bills a serving-fast-lane cache hit as a
        query with near-zero device-ms (its ctx carries no dispatches)
        instead of letting it vanish from the ledger — /debug/tenants
        stays the truth about who the node serves, not just who it
        executes for."""
        with self._lock:
            e = self._entry(tenant, index)
            e[0] += 1
            if error:
                e[1] += 1
            e[2] += elapsed_s * 1e3
            if result_cache_hit:
                e[9] += 1
            if ctx is not None:
                e[3] += ctx.device_s * 1e3
                e[4] += ctx.container_scans()
                e[5] += ctx.row_cache_misses
                e[6] += ctx.rows_materialized

    def add_ingest(self, tenant: str, index: str, rows: int) -> None:
        with self._lock:
            self._entry(tenant, index)[7] += int(rows)

    def add_egress(self, tenant: str, index: str, nbytes: int) -> None:
        with self._lock:
            self._entry(tenant, index)[8] += int(nbytes)

    # ------------------------------------------------------------- views

    def snapshot(self) -> list[dict]:
        with self._lock:
            items = [(k, list(v)) for k, v in self._t.items()]
        return [
            {"tenant": t, "index": i,
             **{name: (round(v, 3) if isinstance(v, float) else v)
                for name, v in zip(_LEDGER_KEYS, vals)}}
            for (t, i), vals in sorted(items)
        ]

    def top(self, k: int = 10, by: str = "device_ms") -> list[dict]:
        """Top-K offender view: the (tenant, index) pairs spending the
        most of one resource."""
        if by not in _LEDGER_KEYS:
            raise ValueError(
                f"unknown cost column {by!r} (want one of "
                f"{', '.join(_LEDGER_KEYS)})"
            )
        rows = self.snapshot()
        rows.sort(key=lambda r: r[by], reverse=True)
        return rows[:k]

    def metrics(self) -> dict:
        """Untagged aggregate block (always exported, zeros from scrape
        one); the tagged per-tenant series ride prometheus_lines."""
        with self._lock:
            agg = [0] * len(_LEDGER_KEYS)
            for vals in self._t.values():
                for i, v in enumerate(vals):
                    agg[i] += v
            pairs = len(self._t)
        out = {f"{name}_total": (round(v, 3) if isinstance(v, float) else v)
               for name, v in zip(_LEDGER_KEYS, agg)}
        out["tracked_pairs"] = pairs
        return out

    def prometheus_lines(self, prefix: str, seen: set | None = None,
                         max_series: int = 64) -> str:
        """Tagged per-(tenant, index) series under the ``tenant_``
        subsystem, capped to the ``max_series`` busiest pairs by
        device-ms (the page must not scale with tenant cardinality —
        the full table lives at /debug/tenants). A sum() over a family
        is the cluster aggregate; the cardinality gauge is untagged."""
        from pilosa_tpu.utils.stats import (
            _meta_lines,
            escape_label,
            prometheus_block,
        )

        seen = seen if seen is not None else set()
        text = prometheus_block(
            {"tracked_pairs": len(self._t)}, prefix, "tenant", seen=seen,
        )
        full = self.snapshot()
        lines: list[str] = []
        for name in _LEDGER_KEYS:
            family = f"{prefix}_tenant_{name}_total"
            lines.extend(_meta_lines(
                family, "counter", f"per-tenant {name.replace('_', ' ')}",
                seen,
            ))
            # rank PER FAMILY: the top ingest tenant may have near-zero
            # device-ms, and a device_ms-only ranking would hide it from
            # its own series once the pair count exceeds the cap
            rows = sorted(full, key=lambda r: r[name],
                          reverse=True)[:max_series]
            for r in rows:
                v = r[name]
                rendered = v if isinstance(v, int) else f"{v:g}"
                # escape: tenant is the CLIENT-controlled header — an
                # unescaped quote would corrupt the whole /metrics page
                lines.append(
                    f'{family}{{tenant="{escape_label(r["tenant"])}",'
                    f'index="{escape_label(r["index"])}"}} {rendered}'
                )
        return text + "\n".join(lines) + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._t.clear()
