"""Logger interface (reference: logger/logger.go — SURVEY.md §2 #25)."""

from __future__ import annotations

import logging
import sys


def new_standard_logger(name: str = "pilosa_tpu", verbose: bool = False) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    return logger


def nop_logger() -> logging.Logger:
    logger = logging.getLogger("pilosa_tpu.nop")
    logger.addHandler(logging.NullHandler())
    logger.propagate = False
    return logger
