"""Diagnostics phone-home (reference: diagnostics.go — SURVEY.md §2 #22).

Hourly anonymized usage report (version, platform, node count) POSTed to a
configurable endpoint. **Disabled by default** (the reference ships it on;
we flip the default — and this environment has zero egress anyway, so the
reporter also swallows network failures silently by design).
"""

from __future__ import annotations

import json
import threading
import urllib.request

DEFAULT_INTERVAL = 3600.0


class DiagnosticsCollector:
    def __init__(self, api, endpoint: str = "", interval: float = DEFAULT_INTERVAL):
        self.api = api
        self.endpoint = endpoint
        self.interval = interval
        self._timer: threading.Timer | None = None
        self._closed = False

    @property
    def enabled(self) -> bool:
        return bool(self.endpoint)

    def payload(self) -> dict:
        import platform

        from pilosa_tpu import __version__

        info = {
            "version": __version__,
            "os": platform.system(),
            "arch": platform.machine(),
            "numNodes": len(self.api.cluster.nodes) if self.api.cluster else 1,
            "numIndexes": len(self.api.holder.indexes),
        }
        return info

    def start(self) -> None:
        if not self.enabled or self._closed:
            return
        self._timer = threading.Timer(self.interval, self._flush)
        self._timer.daemon = True
        self._timer.start()

    def _flush(self) -> None:
        try:
            req = urllib.request.Request(
                self.endpoint,
                data=json.dumps(self.payload()).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=10)
        except Exception:
            pass  # diagnostics must never disturb the server
        self.start()

    def close(self) -> None:
        self._closed = True
        if self._timer is not None:
            self._timer.cancel()
