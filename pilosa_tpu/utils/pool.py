"""Bounded concurrent map for cross-node HTTP fan-out.

The reference maps remote nodes concurrently — one goroutine per
sub-query/fetch (executor.go mapReduce remote branch, SURVEY.md §2 #12,
§3.2) — so cross-node wall time is the max of the per-node latencies,
not the sum. Python analog: a short-lived thread pool per fan-out; the
threads spend their lives blocked in HTTP I/O, so the GIL is irrelevant
and pool construction cost (~100 µs) is noise against network RTTs.
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import ThreadPoolExecutor

# Wide enough to cover every peer of a realistically sized cluster in one
# wave; bounded so a pathological node count cannot spawn unbounded
# threads per query.
MAX_FANOUT = 16


def concurrent_map(fn, items, max_workers: int = MAX_FANOUT,
                   return_exceptions: bool = False) -> list:
    """Apply ``fn`` to every item concurrently; results in input order.

    The first exception propagates to the caller (after in-flight calls
    finish — pool shutdown joins its threads); callers wanting per-item
    error tolerance pass ``return_exceptions=True``, which returns each
    item's Exception in place of its result so one failed item cannot
    abort (or hide the results of) the rest — the routed-import fan-out
    relies on this to report exactly which nodes failed while every
    healthy node's batch still lands.

    Context propagation: each worker invocation runs inside a COPY of the
    submitting thread's ``contextvars`` context, so the active trace span
    and in-flight-query record (utils/tracing.py) survive the hop — a
    span started on a fan-out thread lands in its request's tree instead
    of being orphaned. Copies are O(1) (immutable HAMT) and per-item, so
    concurrent workers never contend on one Context object.
    """
    items = list(items)
    call = fn
    if return_exceptions:
        def call(x):
            try:
                return fn(x)
            except Exception as e:  # per-item capture, surfaced in-order
                return e
    if len(items) <= 1:
        return [call(x) for x in items]
    ctxs = [contextvars.copy_context() for _ in items]
    with ThreadPoolExecutor(max_workers=min(max_workers, len(items))) as pool:
        return list(pool.map(lambda p: p[0].run(call, p[1]),
                             zip(ctxs, items)))


def spawn(thunk):
    """Start ``thunk`` on a daemon thread NOW; returns a ``join()`` that
    blocks for (and re-raises from) it.

    The asymmetric sibling of run_concurrently, for pipelined execution
    (ClusterExecutor.submit): the remote fan-out must START at submit
    time but be AWAITED at result() time, so device enqueue, remote HTTP,
    and the caller's other submits all overlap.
    """
    box: dict = {}
    ctx = contextvars.copy_context()  # trace/inspector context rides along

    def run():
        try:
            box["value"] = ctx.run(thunk)
        except BaseException as e:  # joined and re-raised on the caller
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()

    def join():
        t.join()
        if "error" in box:
            raise box["error"]
        return box["value"]

    return join


def run_concurrently(*thunks) -> list:
    """Run zero-arg thunks concurrently; results in input order.

    Used to overlap the coordinator's LOCAL shard evaluation with the
    remote fan-out (reference mapReduce runs the local mapper goroutines
    and remote sub-queries in the same errgroup): distributed query wall
    time is max(local, slowest peer), not their sum.
    """
    return concurrent_map(lambda f: f(), thunks)
