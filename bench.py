"""Benchmark: the north-star metric on real hardware.

BASELINE.json: "PQL Intersect+Count rows/sec/chip @ 1B cols" — a fused
bitwise-AND + popcount over two 1-billion-column rows (954 shards of 2^20
columns), the device kernel behind Count(Intersect(Row(a), Row(b))).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline compares against a single-CPU-node reference executing the
same logical op with numpy (np.bitwise_and + np.bitwise_count), measured
on this machine — the reference repo publishes no numbers and its mount
is empty (BASELINE.md), so the CPU baseline is measured, not quoted.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_COLS = 1 << 30  # one billion columns
DENSITY_BITS = 1 << 17  # bits set per shard-row (~12.5% density)


def _make_rows(n_shards: int, words_per_shard: int, seed: int) -> np.ndarray:
    """Random bit-packed [n_shards, words] rows, built without python loops."""
    rng = np.random.default_rng(seed)
    # random 32-bit words with ~12.5% bit density via AND of three randoms
    a = rng.integers(0, 1 << 32, size=(n_shards, words_per_shard), dtype=np.uint64)
    b = rng.integers(0, 1 << 32, size=(n_shards, words_per_shard), dtype=np.uint64)
    c = rng.integers(0, 1 << 32, size=(n_shards, words_per_shard), dtype=np.uint64)
    return (a & b & c).astype(np.uint32)


def bench_tpu(a_host: np.ndarray, b_host: np.ndarray, iters: int = 20):
    """Times both the XLA-fused path and the Pallas kernel; returns the
    faster (dt, result, kernel_name)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def intersect_count(a, b):
        return jnp.sum(lax.population_count(a & b).astype(jnp.uint32))

    a = jax.device_put(a_host)
    b = jax.device_put(b_host)

    def timeit(fn):
        result = int(fn(a, b))  # warm up + compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(a, b)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters, result

    xla_dt, result = timeit(intersect_count)
    best = (xla_dt, result, "xla")
    if jax.default_backend() == "tpu":
        try:
            from pilosa_tpu.ops.pallas_kernels import intersect_count_pallas

            pallas_dt, pallas_result = timeit(intersect_count_pallas)
            if pallas_result == result and pallas_dt < xla_dt:
                best = (pallas_dt, result, "pallas")
        except Exception:
            pass  # Mosaic quirk → stay on the XLA path
    return best


def bench_cpu_reference(a: np.ndarray, b: np.ndarray, iters: int = 3) -> tuple[float, int]:
    """Single-node CPU doing the same logical work (numpy vectorized —
    generous to the baseline: the Go reference walks roaring containers)."""
    result = int(np.bitwise_count(a & b).sum())
    t0 = time.perf_counter()
    for _ in range(iters):
        np.bitwise_count(a & b).sum()
    dt = (time.perf_counter() - t0) / iters
    return dt, result


def main() -> None:
    from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

    n_shards = -(-N_COLS // SHARD_WIDTH)  # 1024 shards = 2^30 cols
    a = _make_rows(n_shards, WORDS_PER_SHARD, seed=1)
    b = _make_rows(n_shards, WORDS_PER_SHARD, seed=2)

    tpu_dt, tpu_result, kernel = bench_tpu(a, b)
    cpu_dt, cpu_result = bench_cpu_reference(a, b)
    if tpu_result != cpu_result:
        raise AssertionError(f"result mismatch tpu={tpu_result} cpu={cpu_result}")

    cols_per_sec = N_COLS / tpu_dt
    print(
        json.dumps(
            {
                "metric": "intersect_count_cols_per_sec_1B",
                "value": round(cols_per_sec, 1),
                "unit": "columns/sec/chip",
                "vs_baseline": round(cpu_dt / tpu_dt, 2),
                "kernel": kernel,
            }
        )
    )


if __name__ == "__main__":
    main()
