"""Benchmark: the north-star metric on real hardware.

BASELINE.json: "PQL Intersect+Count rows/sec/chip @ 1B cols" — the fused
bitwise-AND + popcount device kernel behind Count(Intersect(Row(a), Row(b))),
measured as sustained throughput over a stream of independent 1-billion-column
queries (the shape a serving node actually sees; the batched executor issues
one compiled program per query, executor/batch.py).

Method notes (they matter on this harness):
- The device holds K=8 *distinct* 1B-column row pairs (2 GiB total) so every
  query streams real data from HBM — no operand reuse inflation.
- Each timed call folds a unique uint32 salt into one operand inside the
  fused kernel (free: it fuses into the read stream). Identical repeated
  executions can otherwise be served from an execution cache on tunneled
  backends, which would measure nothing.
- Dispatch is pipelined: enqueue all iterations, then force completion via a
  host transfer of the last result (single-device streams are ordered).
- best-of-trials to damp tunnel latency noise.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline compares against a single-CPU-node reference executing the same
logical op with numpy (np.bitwise_and + np.bitwise_count) on this machine —
the reference repo publishes no numbers and its mount is empty (BASELINE.md),
so the CPU baseline is measured, not quoted.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_COLS = 1 << 30  # one billion columns per query row
K_PAIRS = 8  # distinct resident row pairs (2 GiB HBM)
ITERS = 24
TRIALS = 4


def _make_rows(k: int, n_words: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1 << 32, size=(k, n_words), dtype=np.uint32)


def bench_tpu(a_host: np.ndarray, b_host: np.ndarray):
    """Sustained per-chip throughput of the fused intersect+count kernel over
    a pipelined stream of salted batch queries. Returns (dt_per_call,
    per-pair counts for salt=SALT0, kernel name)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def batch_intersect_count(a, b, salt):
        return jnp.sum(lax.population_count(a & (b ^ salt)).astype(jnp.uint32), axis=1)

    a = jax.device_put(a_host)
    b = jax.device_put(b_host)
    jax.block_until_ready((a, b))

    salt = 0
    ref = np.asarray(batch_intersect_count(a, b, jnp.uint32(salt)))  # compile + verify ref
    salt += 1

    best = float("inf")
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        outs = []
        for _ in range(ITERS):
            outs.append(batch_intersect_count(a, b, jnp.uint32(salt)))
            salt += 1
        np.asarray(outs[-1])  # stream-ordered: last done => all done
        best = min(best, (time.perf_counter() - t0) / ITERS)
    return best, ref, "xla"


def bench_cpu_reference(a: np.ndarray, b: np.ndarray, iters: int = 3) -> tuple[float, np.ndarray]:
    """Single-node CPU doing the same logical work (numpy vectorized and
    cache-blocked — generous to the baseline: the Go reference walks roaring
    containers per shard)."""
    k, n_words = a.shape

    def run(salt: int) -> np.ndarray:
        out = np.zeros(k, np.uint64)
        s = np.uint32(salt)
        chunk = 1 << 22
        for i in range(0, n_words, chunk):
            out += np.bitwise_count(a[:, i : i + chunk] & (b[:, i : i + chunk] ^ s)).sum(
                axis=1, dtype=np.uint64
            )
        return out

    ref = run(0).astype(np.uint32)
    best = float("inf")
    for salt in range(1, iters + 1):
        t0 = time.perf_counter()
        run(salt)
        best = min(best, time.perf_counter() - t0)
    return best, ref


def main() -> None:
    n_words = N_COLS // 32
    a = _make_rows(K_PAIRS, n_words, seed=1)
    b = _make_rows(K_PAIRS, n_words, seed=2)

    tpu_dt, tpu_ref, kernel = bench_tpu(a, b)
    cpu_dt, cpu_ref = bench_cpu_reference(a, b)
    if not np.array_equal(tpu_ref, cpu_ref):
        raise AssertionError(f"result mismatch tpu={tpu_ref} cpu={cpu_ref}")

    cols_per_sec = K_PAIRS * N_COLS / tpu_dt
    print(
        json.dumps(
            {
                "metric": "intersect_count_cols_per_sec_1B",
                "value": round(cols_per_sec, 1),
                "unit": "columns/sec/chip",
                "vs_baseline": round(cpu_dt / tpu_dt, 2),
                "kernel": kernel,
                "batch": K_PAIRS,
            }
        )
    )


if __name__ == "__main__":
    main()
