"""Benchmark: the north-star metric through the REAL framework path.

Headline number: ``Count(Intersect(Row(a=k), Row(b=j)))`` — the exact
BASELINE.json op — executed end-to-end by ``Executor.submit``: PQL parse
→ expression compile → residency-cached stacked leaves in HBM → micro-
batched fused programs (8 queries per dispatch) → pipelined readback —
at 1B columns per query (1024 shards), with the dataset built through
the storage tree (holder → field → view → fragment bulk_import). Also
measured and printed: the raw fused-kernel ceiling (the same
bitwise+popcount with zero framework around it) and the executor/kernel
ratio.

Method notes (they matter on this harness):
- The device holds 2·K_ROWS distinct 1B-column stacked leaves (2 GiB)
  via the residency LRU, so every query streams real data from HBM.
- Anti-memoization: tunneled backends can serve IDENTICAL repeated
  executions from a cache without touching the device. The kernel path
  folds a unique uint32 salt into its read stream; the executor path
  cycles row pairs (k, j) with a phase-drifting step so no micro-batch
  dispatch ever repeats an argument tuple inside the run.
- Dispatch is pipelined (Executor.submit): enqueue all iterations, then
  force completion by resolving the LAST Deferred (single-device streams
  are ordered). The blocking final readback (~66 ms tunnel RTT here) is
  amortized over ITERS and reported as rtt_floor_ms.
- best-of-trials to damp tunnel latency noise.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

vs_baseline compares against a single-CPU-node reference executing the
same logical op with numpy (np.bitwise_and + np.bitwise_count) on this
machine — the reference repo publishes no numbers and its mount is empty
(BASELINE.md), so the CPU baseline is measured, not quoted.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import tempfile
import threading
import time

import numpy as np

# If the device backend neither initializes nor fails within this long
# (observed failure mode of the tunneled relay: ~25 min hang at init,
# then UNAVAILABLE), emit a diagnostic JSON line instead of hanging the
# driver forever. Generous vs the ~40 s worst-case first compile.
DEVICE_WATCHDOG_SECONDS = 900.0

# Headline metric identity, shared by the result line and the watchdog's
# diagnostic line so a rename can't leave the failure under a stale key.
METRIC_NAME = "pql_intersect_count_cols_per_sec_1B"
METRIC_UNIT = "columns/sec/chip"


def _device_watchdog() -> threading.Event:
    """Arm a watchdog for backend init; set() the returned event once the
    first device op completes."""
    ready = threading.Event()

    def bark() -> None:
        if not ready.wait(DEVICE_WATCHDOG_SECONDS):
            print(json.dumps({
                "metric": METRIC_NAME,
                "value": 0, "unit": METRIC_UNIT, "vs_baseline": 0,
                "error": (
                    "device backend failed to initialize within "
                    f"{DEVICE_WATCHDOG_SECONDS:.0f}s (tunnel/relay down?)"
                ),
            }), flush=True)
            os._exit(3)

    threading.Thread(target=bark, daemon=True, name="device-watchdog").start()
    return ready

N_COLS = 1 << 30  # one billion columns per query
K_ROWS = 8  # distinct rows per field (2 GiB HBM in stacked leaves)

# Roofline reference: v5e HBM bandwidth ≈ 819 GB/s per chip (public spec,
# v5e: 16 GiB HBM2 @ ~819 GB/s). Count(Intersect(a, b)) streams both
# operands from HBM once — 2 × n_cols/8 = n_cols/4 bytes per query — and
# writes back O(1), so frac_hbm_peak ≈ how close the path runs to the
# bandwidth bound (2 loads per AND+popcount: firmly memory-bound,
# roofline is the right ceiling — VERDICT r3 #4).
HBM_PEAK_BYTES_PER_SEC = 819e9
BITS_PER_ROW_SHARD = 512  # set bits per (row, shard); throughput is
                          # density-independent (dense words on device)
KERNEL_ITERS = 256
EXEC_ITERS = 2048  # = 8 × KERNEL_ITERS: the kernel computes all K_ROWS
                   # row-queries per call, so equal-depth loops would
                   # amortize the final readback 8× better per COLUMN on
                   # the kernel side and the executor/kernel ratio would
                   # mostly measure that artifact. 8:1 equalizes the RTT
                   # share per column (~10% of a trial at 80 ms RTT).
TRIALS = 8  # best-of: the tunneled backend's throughput wanders ±25%
            # across seconds. Depths are also sized so the one blocking
            # final readback (~80 ms tunnel RTT, reported as
            # rtt_floor_ms) stays near ~10% of a trial's wall: at the
            # r4/r5 depths (96/256) it was 25-35% of every measured
            # number, and the "executor vs kernel" gap was mostly the
            # RTT-share difference between the two loops, not the paths.


# ------------------------------------------------------------ raw kernel path


def _make_rows(k: int, n_words: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1 << 32, size=(k, n_words), dtype=np.uint32)


def bench_kernel(a_host: np.ndarray, b_host: np.ndarray):
    """Ceiling: the fused intersect+count kernel with no framework around
    it, pipelined over salted batch queries. Returns (dt_per_call, ref
    counts for salt=0)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def batch_intersect_count(a, b, salt):
        return jnp.sum(lax.population_count(a & (b ^ salt)).astype(jnp.uint32), axis=1)

    a = jax.device_put(a_host)
    b = jax.device_put(b_host)
    jax.block_until_ready((a, b))

    salt = 0
    ref = np.asarray(batch_intersect_count(a, b, jnp.uint32(salt)))  # compile
    salt += 1

    best = float("inf")
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        outs = []
        for _ in range(KERNEL_ITERS):
            outs.append(batch_intersect_count(a, b, jnp.uint32(salt)))
            salt += 1
        np.asarray(outs[-1])  # stream-ordered: last done => all done
        best = min(best, (time.perf_counter() - t0) / KERNEL_ITERS)
    return best, ref


def bench_cpu_reference(a: np.ndarray, b: np.ndarray, iters: int = 3) -> tuple[float, np.ndarray]:
    """Single-node CPU doing the same logical work (numpy vectorized and
    cache-blocked — generous to the baseline: the Go reference walks
    roaring containers per shard)."""
    k, n_words = a.shape

    def run(salt: int) -> np.ndarray:
        out = np.zeros(k, np.uint64)
        s = np.uint32(salt)
        chunk = 1 << 22
        for i in range(0, n_words, chunk):
            out += np.bitwise_count(a[:, i : i + chunk] & (b[:, i : i + chunk] ^ s)).sum(
                axis=1, dtype=np.uint64
            )
        return out

    ref = run(0).astype(np.uint32)
    best = float("inf")
    for salt in range(1, iters + 1):
        t0 = time.perf_counter()
        run(salt)
        best = min(best, time.perf_counter() - t0)
    return best, ref


# -------------------------------------------------------------- executor path


def build_holder(tmp: str, n_shards: int):
    """The benchmark dataset through the real write path: K_ROWS rows in
    each of fields a/b, one bulk_import per (field, shard)."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage.view import VIEW_STANDARD

    holder = Holder(tmp).open()
    idx = holder.create_index("bench")
    rng = np.random.default_rng(7)
    rows = np.repeat(
        np.arange(1, K_ROWS + 1, dtype=np.uint64), BITS_PER_ROW_SHARD
    )
    for fname in ("a", "b"):
        f = idx.create_field(fname)
        view = f.view(VIEW_STANDARD, create=True)
        for shard in range(n_shards):
            cols = rng.integers(0, SHARD_WIDTH, rows.size, dtype=np.uint64)
            view.fragment(shard, create=True).bulk_import(rows, cols)
    return holder, idx


def _combo(g: int) -> tuple[int, int]:
    """Query-pair schedule: a permutation walk over the K_ROWS² row
    combos whose phase drifts every full cycle, so no window of
    microbatch_max consecutive queries (= one dispatch's argument tuple)
    repeats anywhere in the run — identical re-executions could otherwise
    be served by the tunnel's memoization without touching the device."""
    n = K_ROWS * K_ROWS
    c = (5 * g + g // n) % n
    return 1 + c // K_ROWS, 1 + c % K_ROWS


def oracle_count(idx, k: int, j: int, n_shards: int) -> int:
    from pilosa_tpu.storage.view import VIEW_STANDARD

    fa = idx.field("a").view(VIEW_STANDARD)
    fb = idx.field("b").view(VIEW_STANDARD)
    total = 0
    for shard in range(n_shards):
        aw = fa.fragment(shard).row_words(k)
        bw = fb.fragment(shard).row_words(j)
        total += int(np.bitwise_count(aw & bw).sum())
    return total


def bench_executor(holder, idx, n_shards: int):
    """Sustained throughput of the full query path, pipelined via
    Executor.submit. Returns (dt_per_query, microbatch, ok)."""
    from pilosa_tpu.executor import Executor

    ex = Executor(holder)

    def pql(k: int, j: int) -> str:
        return f"Count(Intersect(Row(a={k}), Row(b={j})))"

    # warm: decode + upload every row's stacked leaf, compile the B=1
    # program (sync path) and the micro-batched program (one full flush)
    for k in range(1, K_ROWS + 1):
        ex.execute("bench", pql(k, k))
    g = itertools.count(0)
    warm = [ex.submit("bench", pql(*_combo(next(g))))[0]
            for _ in range(ex.microbatch_max)]
    warm[-1].result()

    best = float("inf")
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        d = None
        for _ in range(EXEC_ITERS):
            d = ex.submit("bench", pql(*_combo(next(g))))[0]
        d.result()  # stream-ordered: last done => all done
        best = min(best, (time.perf_counter() - t0) / EXEC_ITERS)

    # correctness against the host oracle on fresh combos (outside timing)
    ok = True
    for _ in range(3):
        k, j = _combo(next(g))
        got = ex.execute("bench", pql(k, j))[0]
        ok = ok and got == oracle_count(idx, k, j, n_shards)
    return best, ex.microbatch_max, ok


def rtt_floor_ms() -> float:
    """Median wall time of a trivial blocking device round trip — the
    share of each trial spent on the single final readback."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x, s: jnp.sum(x) + s)
    x = jax.device_put(np.zeros(8, np.int32))
    samples = []
    for i in range(8):  # unique scalar: defeats execution-result caches
        t0 = time.perf_counter()
        int(f(x, i))
        samples.append(time.perf_counter() - t0)
    return round(float(np.median(samples)) * 1e3, 1)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--shards", type=int, default=N_COLS >> 20,
                        help="shards per query (default: 1024 = 1B cols)")
    args = parser.parse_args()
    n_shards = args.shards
    n_cols = n_shards << 20
    n_words = n_cols // 32

    ready = _device_watchdog()
    import jax
    import jax.numpy as jnp

    jnp.add(1, 1).block_until_ready()  # first device op: backend is up
    ready.set()  # a slow-but-alive backend is allowed to take its time
    a = _make_rows(K_ROWS, n_words, seed=1)
    b = _make_rows(K_ROWS, n_words, seed=2)
    kernel_dt, kernel_ref = bench_kernel(a, b)
    cpu_dt, cpu_ref = bench_cpu_reference(a, b)
    if not np.array_equal(kernel_ref, cpu_ref):
        raise AssertionError(f"kernel mismatch tpu={kernel_ref} cpu={cpu_ref}")
    del a, b

    with tempfile.TemporaryDirectory() as tmp:
        holder, idx = build_holder(tmp, n_shards)
        exec_dt, microbatch, ok = bench_executor(holder, idx, n_shards)
        holder.close()
    if not ok:
        raise AssertionError("executor result mismatch vs host oracle")

    exec_cols_per_sec = n_cols / exec_dt
    kernel_cols_per_sec = K_ROWS * n_cols / kernel_dt
    cpu_dt_per_col = cpu_dt / (K_ROWS * n_cols)
    # each column costs 2 bits = 1/4 byte of HBM traffic (both operands)
    exec_hbm = exec_cols_per_sec / 4
    kernel_hbm = kernel_cols_per_sec / 4
    print(
        json.dumps(
            {
                "metric": METRIC_NAME,
                "value": round(exec_cols_per_sec, 1),
                "unit": METRIC_UNIT,
                "vs_baseline": round(cpu_dt_per_col * exec_cols_per_sec, 2),
                "kernel_cols_per_sec": round(kernel_cols_per_sec, 1),
                "executor_vs_kernel": round(
                    exec_cols_per_sec / kernel_cols_per_sec, 3
                ),
                "hbm_bytes_per_sec": round(exec_hbm, 1),
                "kernel_hbm_bytes_per_sec": round(kernel_hbm, 1),
                "frac_hbm_peak": round(exec_hbm / HBM_PEAK_BYTES_PER_SEC, 3),
                "frac_hbm_peak_kernel": round(
                    kernel_hbm / HBM_PEAK_BYTES_PER_SEC, 3
                ),
                "kernel": "xla",
                "path": "executor.submit",
                "microbatch": microbatch,
                "iters": EXEC_ITERS,
                "rtt_floor_ms": rtt_floor_ms(),
            }
        )
    )


if __name__ == "__main__":
    main()
