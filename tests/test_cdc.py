"""CDC backbone (pilosa_tpu/cdc/): WAL tail change feed, frame wire
fuzz (test_shmring.py discipline), the HTTP tail route, cluster-safe
result caching via peer tailers, stale-bounded read replicas, and
point-in-time ``restore --as-of``."""

import json
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.cdc.feed import (
    DURABLE_SEQ_HEADER,
    NEXT_SEQ_HEADER,
    encode_events,
    iter_frames,
)
from pilosa_tpu.storage import wal as wal_mod
from pilosa_tpu.storage.backup import backup_holder, restore_holder
from pilosa_tpu.storage.field import VIEW_STANDARD
from pilosa_tpu.storage.holder import Holder
from pilosa_tpu.storage.wal import REC_OP, REC_TOMBSTONE, TailGone

from cluster_helpers import make_cluster, req, uri


def _mk_holder(tmp_path, name="h", **kw):
    return Holder(str(tmp_path / name), **kw).open()


def _frag(holder, index="i", field="f", shard=0):
    idx = holder.index(index) or holder.create_index(index)
    fld = idx.field(field) or idx.create_field(field)
    return fld.view(VIEW_STANDARD, create=True).fragment(shard, create=True)


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# --------------------------------------------------------- WAL tail feed


class TestWalTail:
    def test_events_in_commit_order(self, tmp_path):
        h = _mk_holder(tmp_path)
        try:
            frag = _frag(h)
            for i in range(20):
                frag.set_bit(1, i)
            h.wal.barrier()
            events, next_seq, durable = h.wal.read_tail(0)
            assert [e[0] for e in events] == list(range(1, 21))
            assert all(e[1] == REC_OP for e in events)
            assert all(e[2] == "i/f/standard/0" for e in events)
            assert next_seq == durable == 20
            # resume mid-stream: strictly after `since`
            events, next_seq, _ = h.wal.read_tail(15)
            assert [e[0] for e in events] == [16, 17, 18, 19, 20]
        finally:
            h.close()

    def test_attached_consumer_drains_to_empty(self, tmp_path):
        h = _mk_holder(tmp_path)
        try:
            frag = _frag(h)
            frag.set_bit(1, 1)
            h.wal.barrier()
            durable = h.wal.durable_seq()
            events, next_seq, d2 = h.wal.read_tail(durable)
            assert events == [] and next_seq == d2 == durable
        finally:
            h.close()

    def test_seq_past_durable_is_gone(self, tmp_path):
        """A consumer holding a cursor from a PREVIOUS process
        incarnation (seq space reset) must be told to restart, not fed
        a silently different history."""
        h = _mk_holder(tmp_path)
        try:
            with pytest.raises(TailGone):
                h.wal.read_tail(10_000)
        finally:
            h.close()

    def test_tombstones_ride_the_feed(self, tmp_path):
        h = _mk_holder(tmp_path)
        try:
            frag = _frag(h)
            frag.set_bit(1, 1)
            h.create_index("j")
            h.delete_index("j")
            h.wal.barrier()
            events, _, _ = h.wal.read_tail(0)
            tombs = [(e[2]) for e in events if e[1] == REC_TOMBSTONE]
            assert tombs == ["j/"]
        finally:
            h.close()

    def test_cursor_survives_segment_rotation(self, tmp_path,
                                              monkeypatch):
        """The cursor contract across rotation: a registered consumer
        can fall several SEGMENTS behind and still read every event in
        order — rotation must never drop feed history it pins."""
        monkeypatch.setattr(wal_mod, "SEGMENT_MAX_BYTES", 2048)
        h = _mk_holder(tmp_path)
        try:
            h.wal.register_cursor("lagger", 0)
            frag = _frag(h)
            for i in range(300):
                frag.set_bit(1, i)
            h.wal.barrier()
            assert len(h.wal._segments) > 2, "rotation never happened"
            got = []
            pos = 0
            while True:
                events, next_seq, durable = h.wal.read_tail(
                    pos, max_bytes=4096)
                got.extend(e[0] for e in events)
                if next_seq <= pos:
                    break
                pos = next_seq
                h.wal.register_cursor("lagger", pos)
                if pos >= durable:
                    break
            assert got == list(range(1, 301))
        finally:
            h.close()

    def test_gc_reclaims_oldest_first_past_dropped_cursor(
            self, tmp_path, monkeypatch):
        monkeypatch.setattr(wal_mod, "SEGMENT_MAX_BYTES", 2048)
        h = _mk_holder(tmp_path)
        try:
            h.wal.register_cursor("c", 0)
            frag = _frag(h)
            for i in range(150):
                frag.set_bit(1, i)
            h.wal.barrier()
            # pinned: the full feed is still readable
            events, _, _ = h.wal.read_tail(0, max_bytes=1 << 20)
            assert events and events[0][0] == 1
            h.wal.drop_cursor("c")
            for i in range(150, 300):
                frag.set_bit(1, i)
            h.wal.barrier()
            assert h.wal.tail_floor() > 0, "GC never advanced the floor"
            with pytest.raises(TailGone) as ei:
                h.wal.read_tail(0)
            assert ei.value.floor == h.wal.tail_floor()
            # the still-retained suffix reads fine from the floor
            events, _, durable = h.wal.read_tail(h.wal.tail_floor(),
                                                 max_bytes=1 << 20)
            assert events and events[-1][0] == durable
        finally:
            h.close()

    def test_retention_budget_forces_lagging_cursor_off(
            self, tmp_path, monkeypatch):
        """cdc-max-retention-bytes is a hard ceiling: a stalled
        consumer cannot pin unbounded disk — the WAL force-reclaims and
        the consumer gets TailGone (-> snapshot restart) instead."""
        monkeypatch.setattr(wal_mod, "SEGMENT_MAX_BYTES", 2048)
        h = _mk_holder(tmp_path)
        try:
            h.wal.cdc_retention_bytes = 4096
            h.wal.register_cursor("stalled", 0)
            frag = _frag(h)
            for i in range(400):
                frag.set_bit(1, i)
            h.wal.barrier()
            assert h.wal.metrics()["cdc_forced_reclaims_total"] > 0
            with pytest.raises(TailGone):
                h.wal.read_tail(0)
        finally:
            h.close()

    def test_tombstone_pinned_segment_survives_rotation(
            self, tmp_path, monkeypatch):
        """A segment whose only unconsumed records are tombstones is
        still feed history: GC must hold it for the lagging cursor
        exactly like op segments."""
        monkeypatch.setattr(wal_mod, "SEGMENT_MAX_BYTES", 2048)
        h = _mk_holder(tmp_path)
        try:
            h.wal.register_cursor("c", 0)
            frag = _frag(h)
            frag.set_bit(1, 1)
            h.create_index("doomed")
            h.delete_index("doomed")
            for i in range(200):
                frag.set_bit(1, i + 2)
            h.wal.barrier()
            events, _, _ = h.wal.read_tail(0, max_bytes=1 << 20)
            tombs = [e for e in events if e[1] == REC_TOMBSTONE]
            assert tombs and tombs[0][2] == "doomed/"
        finally:
            h.close()


# ------------------------------------------------------ frame wire fuzz


class TestFeedFrames:
    EVENTS = [
        (7, REC_OP, "i/f/standard/0", b"\x01" * 11),
        (8, REC_TOMBSTONE, "i/", b""),
        (9, REC_OP, "i/g/standard/3", bytes(range(40))),
    ]

    def test_roundtrip(self):
        buf = encode_events(self.EVENTS)
        assert list(iter_frames(buf)) == self.EVENTS

    def test_truncation_at_every_offset_stops_cleanly(self):
        """The shmring fuzz shape on the wire body: cut the stream at
        EVERY byte offset — the reader yields a whole-frame prefix,
        never raises, never yields a partial record."""
        buf = encode_events(self.EVENTS)
        for cut in range(len(buf)):
            got = list(iter_frames(buf[:cut]))
            assert got == self.EVENTS[: len(got)], f"cut {cut}"

    def test_corruption_in_record_bytes_stops_never_yields_garbage(self):
        """Flip one byte at every offset of the RECORD portion of the
        first frame (header, key, body — everything the producer's CRC
        or magic covers): the stream stops at or before that frame;
        any frame that does decode is byte-identical to an original."""
        buf = bytearray(encode_events(self.EVENTS))
        first_rec_end = len(encode_events(self.EVENTS[:1]))
        for off in range(8, first_rec_end):  # skip the seq prefix
            mutated = bytearray(buf)
            mutated[off] ^= 0xFF
            got = list(iter_frames(bytes(mutated)))
            for ev in got:
                assert ev in self.EVENTS, f"offset {off} yielded {ev!r}"
            assert self.EVENTS[0] not in got or mutated[off] == buf[off]


# ------------------------------------------------------- HTTP tail route


@pytest.fixture
def tail_server(tmp_path):
    from pilosa_tpu.server.api import API
    from pilosa_tpu.server.http import serve_in_thread

    holder = Holder(str(tmp_path / "data")).open()
    api = API(holder)
    server, port, _ = serve_in_thread(api)
    yield f"http://localhost:{port}", holder
    server.shutdown()
    server.server_close()
    holder.close()


def _get(url):
    r = urllib.request.Request(url, method="GET")
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


class TestWalTailRoute:
    def test_attach_then_poll(self, tail_server):
        base, holder = tail_server
        frag = _frag(holder)
        for i in range(5):
            frag.set_bit(1, i)
        holder.wal.barrier()
        # attach: no `since` -> empty body, cursor = durable high-water
        st, headers, body = _get(f"{base}/internal/wal/tail")
        assert st == 200 and body == b""
        durable = int(headers[DURABLE_SEQ_HEADER])
        assert int(headers[NEXT_SEQ_HEADER]) == durable == 5
        frag.set_bit(1, 99)
        holder.wal.barrier()
        st, headers, body = _get(
            f"{base}/internal/wal/tail?since={durable}")
        assert st == 200
        events = list(iter_frames(body))
        assert [(e[0], e[2]) for e in events] == [(6, "i/f/standard/0")]
        assert int(headers[NEXT_SEQ_HEADER]) == 6

    def test_cursor_registration_pins(self, tail_server):
        base, holder = tail_server
        _get(f"{base}/internal/wal/tail?cursor=it")
        assert "it" in holder.wal.cursors()

    def test_gone_is_410_with_restart_hint(self, tail_server):
        base, holder = tail_server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/internal/wal/tail?since=12345")
        assert ei.value.code == 410
        detail = json.loads(ei.value.read())
        assert detail["restartFrom"] == holder.wal.durable_seq()
        assert "floor" in detail

    def test_unknown_cursor_poll_is_410(self, tail_server):
        # the cursor registry is in-memory: a poll naming a cursor the
        # producer never registered (it restarted, or force-reclaimed
        # the laggard) must 410 even when `since` still lands inside
        # the fresh seq space — the silent-gap hard edge
        base, holder = tail_server
        frag = _frag(holder)
        for i in range(5):
            frag.set_bit(1, i)
        holder.wal.barrier()
        _get(f"{base}/internal/wal/tail?cursor=it")  # attach
        st, _, _ = _get(f"{base}/internal/wal/tail?cursor=it&since=2")
        assert st == 200
        holder.wal.drop_cursor("it")  # what a producer restart does
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/internal/wal/tail?cursor=it&since=2")
        assert ei.value.code == 410

    def test_bad_params_are_400(self, tail_server):
        base, _ = tail_server
        for q in ("since=xyz", "max-bytes=0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/internal/wal/tail?{q}")
            assert ei.value.code == 400, q

    def test_non_group_durability_is_501(self, tmp_path):
        from pilosa_tpu.server.api import API
        from pilosa_tpu.server.http import serve_in_thread

        holder = Holder(str(tmp_path / "d"),
                        durability_mode="flush-only").open()
        api = API(holder)
        server, port, _ = serve_in_thread(api)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://localhost:{port}/internal/wal/tail")
            assert ei.value.code == 501
        finally:
            server.shutdown()
            server.server_close()
            holder.close()


# ------------------------------------- cluster-safe result cache (CDC)


@pytest.fixture
def _fresh_cache():
    from pilosa_tpu.serving.rescache import (
        ResultCache,
        set_global_result_cache,
    )

    yield
    set_global_result_cache(ResultCache(0))


def _query(base, index, pql):
    return req("POST", f"{base}/index/{index}/query", pql.encode())


def _seed_two_shard(servers):
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    base = uri(servers[0])
    req("POST", f"{base}/index/i", {})
    req("POST", f"{base}/index/i/field/f", {})
    for s in range(4):  # spread shards so SOME are remote to node0
        _query(base, "i", f"Set({s * SHARD_WIDTH + 5}, f=1)")


class TestClusterCache:
    def test_pre_cdc_cluster_edges_refuse_with_reason(
            self, tmp_path, _fresh_cache):
        servers = make_cluster(tmp_path, 2, result_cache_bytes=8 << 20)
        try:
            _seed_two_shard(servers)
            base = uri(servers[0])
            for _ in range(3):
                assert _query(base, "i", "Count(Row(f=1))")[
                    "results"] == [4]
            snap = req("GET", f"{base}/debug/rescache")
            assert snap["refusals"].get("cluster-no-cdc", 0) > 0
            assert "cdc" not in snap  # no tailer -> no lag gauge
        finally:
            for s in servers:
                s.close()

    def test_cdc_lifts_the_refusal_and_invalidates_remote_writes(
            self, tmp_path, _fresh_cache):
        """The tentpole oracle: with tailers live, a cluster-edge
        result caches (hit on re-read) AND a write landing on the
        OTHER node invalidates it — read-your-writes holds cluster-
        wide, within the staleness the tail poll allows."""
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        servers = make_cluster(tmp_path, 2, result_cache_bytes=8 << 20,
                               cdc_enabled=True, cdc_poll_interval=0.02)
        try:
            _seed_two_shard(servers)
            s0 = uri(servers[0])
            _wait(lambda: req("GET", f"{s0}/debug/rescache")
                  .get("cdc", {}).get("live"), msg="cdc live on node0")

            # `live` means the tailers are polling, not that the seed
            # writes' events have drained — a fill racing the catch-up
            # invalidations refuses on the version fence (by design,
            # counted as a fill race), so poll until a fill lands and
            # the re-read HITS instead of demanding the first fill win
            def cached_hit():
                before = req("GET", f"{s0}/debug/rescache")
                assert _query(s0, "i", "Count(Row(f=1))")[
                    "results"] == [4]
                after = req("GET", f"{s0}/debug/rescache")
                return (after["result_cache_hits_total"]
                        > before["result_cache_hits_total"])

            _wait(cached_hit,
                  msg="cluster-edge result cached despite live CDC")
            # write through the PEER: its WAL event must reach node0's
            # tailer and invalidate the cached edge result
            s1 = uri(servers[1])
            _query(s1, "i", f"Set({7 * SHARD_WIDTH + 5}, f=1)")

            def fresh():
                return _query(s0, "i",
                              "Count(Row(f=1))")["results"] == [5]

            _wait(fresh, msg="remote write to invalidate node0's cache")
            lag = req("GET", f"{s0}/debug/rescache")["cdc"]["peerLag"]
            assert len(lag) == 1  # one peer tailed
        finally:
            for s in servers:
                s.close()


# ----------------------------------------------------- read replicas


class TestFollower:
    def test_follower_serves_stale_bounded_reads(self, tmp_path,
                                                 _fresh_cache):
        from pilosa_tpu.server import Server, ServerConfig

        primary = Server(ServerConfig(
            data_dir=str(tmp_path / "p"), port=0, name="p",
            anti_entropy_interval=0, heartbeat_interval=0,
            use_mesh=False,
        )).open()
        follower = None
        try:
            pbase = uri(primary)
            req("POST", f"{pbase}/index/i", {})
            req("POST", f"{pbase}/index/i/field/f", {})
            for c in range(10):
                _query(pbase, "i", f"Set({c}, f=1)")
            follower = Server(ServerConfig(
                data_dir=str(tmp_path / "r"), port=0, name="r",
                anti_entropy_interval=0, heartbeat_interval=0,
                use_mesh=False, cdc_follow=pbase,
                cdc_poll_interval=0.02, cdc_staleness_budget=30.0,
            )).open()
            fbase = uri(follower)
            _wait(lambda: follower.api.follower.staleness_s() < 30,
                  msg="follower initial sync")
            # bulk-synced data serves
            assert _query(fbase, "i",
                          "Count(Row(f=1))")["results"] == [10]
            # post-sync writes flow through the tail
            _query(pbase, "i", "Set(99, f=1)")

            def caught_up():
                return _query(fbase, "i",
                              "Count(Row(f=1))")["results"] == [11]

            _wait(caught_up, msg="tail apply on follower")
            # followers are read replicas: writes 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _query(fbase, "i", "Set(1, f=2)")
            assert ei.value.code == 403
            # schema writes too
            with pytest.raises(urllib.error.HTTPError) as ei:
                req("POST", f"{fbase}/index/other", {})
            assert ei.value.code == 403
        finally:
            if follower is not None:
                follower.close()
            primary.close()

    def test_staleness_header_sheds_503_with_retry_after(
            self, tmp_path, _fresh_cache):
        from pilosa_tpu.server import Server, ServerConfig

        primary = Server(ServerConfig(
            data_dir=str(tmp_path / "p"), port=0, name="p",
            anti_entropy_interval=0, heartbeat_interval=0,
            use_mesh=False,
        )).open()
        follower = None
        try:
            pbase = uri(primary)
            req("POST", f"{pbase}/index/i", {})
            req("POST", f"{pbase}/index/i/field/f", {})
            _query(pbase, "i", "Set(1, f=1)")
            follower = Server(ServerConfig(
                data_dir=str(tmp_path / "r"), port=0, name="r",
                anti_entropy_interval=0, heartbeat_interval=0,
                use_mesh=False, cdc_follow=pbase,
                cdc_poll_interval=0.02, cdc_staleness_budget=30.0,
            )).open()
            fbase = uri(follower)
            _wait(lambda: follower.api.follower.staleness_s() < 30,
                  msg="follower initial sync")
            # an impossible budget: real staleness is always > 1us
            r = urllib.request.Request(
                f"{fbase}/index/i/query",
                data=b"Count(Row(f=1))", method="POST")
            r.add_header("X-Pilosa-Max-Staleness", "1us")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=30)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            # malformed budget is the caller's bug: 400, not a shed
            r = urllib.request.Request(
                f"{fbase}/index/i/query",
                data=b"Count(Row(f=1))", method="POST")
            r.add_header("X-Pilosa-Max-Staleness", "soon")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=30)
            assert ei.value.code == 400
            # a generous budget passes on a caught-up follower
            assert _query(fbase, "i",
                          "Count(Row(f=1))")["results"] == [1]
            m = follower.api.cdc_metrics()
            assert m["cdc_follower"] == 1
            assert m["cdc_follower_staleness_seconds"] >= 0
        finally:
            if follower is not None:
                follower.close()
            primary.close()


# -------------------------------------------------- as-of restore (PIT)


class TestAsOfRestore:
    def _ledger_holder(self, tmp_path):
        h = _mk_holder(tmp_path, "src")
        frag = _frag(h)
        for i in range(10):
            frag.set_bit(1, i)
        h.wal.barrier()
        return h, frag

    def _cols(self, dst):
        h = Holder(str(dst)).open()
        try:
            frag = h.index("i").field("f").view(
                VIEW_STANDARD).fragment(0)
            return sorted(frag.row_columns(1).tolist())
        finally:
            h.close()

    def test_every_ledger_point_restores_bit_exactly(self, tmp_path):
        """The acceptance oracle: record (seq -> expected state) after
        every acked write, then EVERY recorded seq restores to exactly
        that state — adds, a clear, across two generations."""
        h, frag = self._ledger_holder(tmp_path)
        bk = tmp_path / "bk"
        try:
            backup_holder(h, str(bk))
            ledger = {}
            cols = set(range(10))
            for i in range(10, 24):
                frag.set_bit(1, i)
                cols.add(i)
                h.wal.barrier()
                ledger[h.wal.durable_seq()] = sorted(cols)
            frag.clear_bit(1, 3)
            cols.discard(3)
            h.wal.barrier()
            ledger[h.wal.durable_seq()] = sorted(cols)
            backup_holder(h, str(bk))
            for seq, want in ledger.items():
                dst = tmp_path / f"r{seq}"
                m = restore_holder(str(bk), str(dst), as_of=seq)
                assert self._cols(dst) == want, f"as_of={seq}"
                assert m["asOfSeq"] == seq
        finally:
            h.close()

    def test_boundary_as_of_needs_no_replay(self, tmp_path):
        h, _ = self._ledger_holder(tmp_path)
        bk = tmp_path / "bk"
        try:
            m1 = backup_holder(h, str(bk))
            dst = tmp_path / "r"
            m = restore_holder(str(bk), str(dst), as_of=m1["walSeq"])
            assert m["replayedOps"] == 0
            assert self._cols(dst) == list(range(10))
        finally:
            h.close()

    def test_as_of_past_latest_generation_errors(self, tmp_path):
        h, _ = self._ledger_holder(tmp_path)
        try:
            m1 = backup_holder(h, str(tmp_path / "bk"))
            with pytest.raises(ValueError, match="past the latest"):
                restore_holder(str(tmp_path / "bk"),
                               str(tmp_path / "r"),
                               as_of=m1["walSeq"] + 1)
        finally:
            h.close()

    def test_tombstone_inside_window_refuses(self, tmp_path):
        h, frag = self._ledger_holder(tmp_path)
        bk = tmp_path / "bk"
        try:
            backup_holder(h, str(bk))
            frag.set_bit(1, 50)
            h.wal.barrier()
            mid = h.wal.durable_seq()
            h.delete_index("i")
            jfrag = _frag(h, index="j")
            jfrag.set_bit(1, 1)  # gen2.walSeq lands PAST the tombstone
            h.wal.barrier()
            backup_holder(h, str(bk))
            # replaying THROUGH the deletion is refused...
            with pytest.raises(ValueError, match="deletion"):
                restore_holder(str(bk), str(tmp_path / "r1"),
                               as_of=mid + 1)
            # ...but up to just before it is fine
            restore_holder(str(bk), str(tmp_path / "r2"), as_of=mid)
            assert self._cols(tmp_path / "r2") == sorted(
                set(range(10)) | {50})
        finally:
            h.close()

    def test_generation_and_as_of_are_exclusive(self, tmp_path):
        h, _ = self._ledger_holder(tmp_path)
        try:
            m1 = backup_holder(h, str(tmp_path / "bk"))
            with pytest.raises(ValueError, match="not both"):
                restore_holder(str(tmp_path / "bk"),
                               str(tmp_path / "r"),
                               generation=1, as_of=m1["walSeq"])
        finally:
            h.close()

    def test_fragment_born_inside_window_is_synthesized(self, tmp_path):
        """First write to a brand-new shard lands between generations:
        replay must create the fragment from an empty snapshot, not
        drop the ops."""
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        h, frag = self._ledger_holder(tmp_path)
        bk = tmp_path / "bk"
        try:
            backup_holder(h, str(bk))
            f2 = _frag(h, shard=3)
            f2.set_bit(4, 7)
            h.wal.barrier()
            seq = h.wal.durable_seq()
            frag.set_bit(1, 60)  # push gen2's walSeq past `seq` so the
            h.wal.barrier()      # restore goes through REPLAY, not the
            backup_holder(h, str(bk))  # generation's own content walk
            dst = tmp_path / "r"
            m = restore_holder(str(bk), str(dst), as_of=seq)
            assert m["replayedOps"] >= 1
            h2 = Holder(str(dst)).open()
            try:
                got = h2.index("i").field("f").view(
                    VIEW_STANDARD).fragment(3).row_columns(4).tolist()
                assert got == [7]
            finally:
                h2.close()
        finally:
            h.close()

    def test_backup_registers_pin_cursor(self, tmp_path):
        h, _ = self._ledger_holder(tmp_path)
        try:
            backup_holder(h, str(tmp_path / "bk"))
            names = list(h.wal.cursors())
            assert any(n.startswith("backup:") for n in names)
        finally:
            h.close()

    def test_non_grouped_wal_backups_have_no_anchor(self, tmp_path):
        h = _mk_holder(tmp_path, "src", durability_mode="flush-only")
        try:
            frag = _frag(h)
            frag.set_bit(1, 1)
            m = backup_holder(h, str(tmp_path / "bk"))
            assert m["walSeq"] is None and m["walFeed"] is None
            with pytest.raises(ValueError, match="group-durability"):
                restore_holder(str(tmp_path / "bk"),
                               str(tmp_path / "r"), as_of=1)
        finally:
            h.close()
