"""Property tests: vectorized roaring kernels vs the per-container
reference paths (pilosa_tpu/roaring/kernels.py).

The kernels' contract is BYTE-IDENTITY with the per-container
implementations they replaced, so the reference loops live on here
verbatim — every op, digest, decode, and diff is checked against them
over randomized array/bitmap/run mixes plus the degenerate shapes
(empty fragment, full container, single-container, single-bit).
"""

import hashlib

import numpy as np
import pytest

from pilosa_tpu.roaring import kernels, serialize
from pilosa_tpu.roaring.bitmap import RoaringBitmap, ARRAY, BITMAP, RUN
from pilosa_tpu.roaring.format import deserialize, encode_op, OP_ADD
from pilosa_tpu.storage.integrity import block_digests

# ------------------------------------------------- per-container reference


def ref_to_ids(bm: RoaringBitmap) -> np.ndarray:
    """The pre-kernel RoaringBitmap.to_ids, verbatim."""
    parts = []
    for key in bm.keys:
        c = bm._containers.get(key)
        if c is None:
            continue
        lows = c.lows().astype(np.uint64)
        parts.append(lows + (np.uint64(key) << np.uint64(16)))
    if not parts:
        return np.empty(0, np.uint64)
    return np.concatenate(parts)


def ref_dense_range_words32(bm: RoaringBitmap, start: int,
                            stop: int) -> np.ndarray:
    """The pre-kernel RoaringBitmap.dense_range_words32, verbatim."""
    n_containers = (stop - start) >> 16
    out = np.zeros((n_containers, 2048), np.uint32)
    base_key = start >> 16
    for i in range(n_containers):
        c = bm._containers.get(base_key + i)
        if c is not None:
            out[i] = c.dense_words32()
    return out.reshape(-1)


def ref_range_ids(bm: RoaringBitmap, start: int, stop: int) -> np.ndarray:
    ids = ref_to_ids(bm)
    return ids[(ids >= np.uint64(start)) & (ids < np.uint64(stop))]


def ref_op(bm_a: RoaringBitmap, bm_b: RoaringBitmap, op: str) -> np.ndarray:
    """Set-algebra reference on materialized id sets (independent
    formulation, not shared machinery with the kernels)."""
    a = set(ref_to_ids(bm_a).tolist())
    b = set(ref_to_ids(bm_b).tolist())
    out = {"and": a & b, "or": a | b, "xor": a ^ b, "andnot": a - b}[op]
    return np.asarray(sorted(out), np.uint64)


# ---------------------------------------------------------- fragment maker


def make_bitmap(rng: np.random.Generator, n_containers: int,
                kinds: str = "mixed", key_span: int = 64) -> RoaringBitmap:
    """Random bitmap with a controlled container-kind mix. Kinds are
    steered through Container.from_lows by the shape of the lows."""
    bm = RoaringBitmap()
    keys = rng.choice(key_span, size=min(n_containers, key_span),
                      replace=False)
    ids = []
    for key in keys.tolist():
        kind = (rng.choice(["array", "bitmap", "run", "full", "single"])
                if kinds == "mixed" else kinds)
        if kind == "array":
            n = int(rng.integers(1, 2000))
            lows = rng.choice(65536, size=n, replace=False)
        elif kind == "bitmap":
            n = int(rng.integers(4200, 20000))
            lows = rng.choice(65536, size=n, replace=False)
        elif kind == "run":
            starts = np.sort(rng.choice(65000, size=int(rng.integers(1, 8)),
                                        replace=False))
            lows = np.concatenate([
                np.arange(s, min(s + int(rng.integers(20, 400)), 65536))
                for s in starts.tolist()
            ])
        elif kind == "full":
            lows = np.arange(65536)
        else:  # single
            lows = rng.choice(65536, size=1)
        lows = np.unique(lows).astype(np.uint64)
        ids.append(lows + (np.uint64(key) << np.uint64(16)))
    if ids:
        bm.add_ids(np.concatenate(ids))
    return bm


def assert_ids_identical(got: np.ndarray, want: np.ndarray):
    assert got.dtype == np.uint64
    assert got.tobytes() == want.astype(np.uint64).tobytes()


# ----------------------------------------------------------------- to_ids


@pytest.mark.parametrize("seed", range(6))
def test_fragment_ids_matches_reference(seed):
    rng = np.random.default_rng(seed)
    bm = make_bitmap(rng, n_containers=int(rng.integers(1, 40)))
    flat = kernels.flatten(bm)
    assert_ids_identical(kernels.fragment_ids(flat), ref_to_ids(bm))


def test_fragment_ids_empty_and_degenerate():
    assert kernels.fragment_ids(kernels.flatten(RoaringBitmap())).size == 0
    for kind in ("full", "single", "run", "bitmap", "array"):
        rng = np.random.default_rng(hash(kind) % 2**32)
        bm = make_bitmap(rng, 1, kinds=kind)
        assert_ids_identical(
            kernels.fragment_ids(kernels.flatten(bm)), ref_to_ids(bm))


def test_flatten_key_range_subsets():
    rng = np.random.default_rng(7)
    bm = make_bitmap(rng, n_containers=30, key_span=48)
    ids = ref_to_ids(bm)
    for lo, hi in [(0, 15), (16, 31), (5, 5), (40, 200), (100, 120)]:
        flat = kernels.flatten(bm, lo, hi)
        want = ids[((ids >> np.uint64(16)) >= lo)
                   & ((ids >> np.uint64(16)) <= hi)]
        assert_ids_identical(kernels.fragment_ids(flat), want)


def test_range_ids_matches_reference():
    rng = np.random.default_rng(11)
    bm = make_bitmap(rng, n_containers=20, key_span=32)
    for start, stop in [(0, 1 << 20), (1 << 20, 3 << 20), (65536, 131072)]:
        flat = kernels.flatten(bm, start >> 16, (stop - 1) >> 16)
        assert_ids_identical(kernels.range_ids(flat, start, stop),
                             ref_range_ids(bm, start, stop))


# ----------------------------------------------------------- dense decode


@pytest.mark.parametrize("seed", range(6))
def test_dense_words32_matches_reference(seed):
    rng = np.random.default_rng(100 + seed)
    bm = make_bitmap(rng, n_containers=int(rng.integers(1, 30)), key_span=32)
    # decode in 16-container windows (a fragment row) and whole-range
    for base_key, n in [(0, 16), (16, 16), (0, 32), (3, 5)]:
        flat = kernels.flatten(bm, base_key, base_key + n - 1)
        got = kernels.dense_words32(flat, base_key, n)
        want = ref_dense_range_words32(bm, base_key << 16,
                                       (base_key + n) << 16)
        assert got.dtype == np.uint32
        assert got.tobytes() == want.tobytes()


def test_dense_words32_empty_window():
    bm = RoaringBitmap()
    flat = kernels.flatten(bm, 0, 15)
    got = kernels.dense_words32(flat, 0, 16)
    assert got.shape == (16 * 2048,)
    assert not got.any()


# --------------------------------------------------------------- popcount


@pytest.mark.parametrize("seed", range(4))
def test_popcount_matches_cardinality(seed):
    rng = np.random.default_rng(200 + seed)
    bm = make_bitmap(rng, n_containers=int(rng.integers(1, 25)))
    flat = kernels.flatten(bm)
    assert kernels.popcount(flat) == bm.count() == ref_to_ids(bm).size


# ---------------------------------------------------------------- set ops


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_set_ops_match_reference(seed, op):
    rng = np.random.default_rng(300 + seed)
    # overlapping key ranges so every kind×kind pairing occurs
    a = make_bitmap(rng, n_containers=int(rng.integers(1, 20)), key_span=24)
    b = make_bitmap(rng, n_containers=int(rng.integers(1, 20)), key_span=24)
    fn = {"and": kernels.fragment_and, "or": kernels.fragment_or,
          "xor": kernels.fragment_xor, "andnot": kernels.fragment_andnot}[op]
    assert_ids_identical(fn(a, b), ref_op(a, b, op))


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_set_ops_empty_operands(op):
    rng = np.random.default_rng(5)
    a = make_bitmap(rng, 5)
    empty = RoaringBitmap()
    fn = {"and": kernels.fragment_and, "or": kernels.fragment_or,
          "xor": kernels.fragment_xor, "andnot": kernels.fragment_andnot}[op]
    assert_ids_identical(fn(a, empty), ref_op(a, empty, op))
    assert_ids_identical(fn(empty, a), ref_op(empty, a, op))
    assert fn(empty, empty).size == 0


def test_bitmap_bitmap_lane_stays_in_word_space():
    # two pure-bitmap operands share every key: the AND must not
    # materialize either side (set_ops counter moves, ids counter only
    # by the RESULT extraction, which is nonzero — so instead pin
    # correctness of the word lane on a crafted disjoint/overlap case)
    lows_a = np.arange(0, 30000, 2, dtype=np.uint64)
    lows_b = np.arange(0, 30000, 3, dtype=np.uint64)
    a = RoaringBitmap.from_ids(lows_a)
    b = RoaringBitmap.from_ids(lows_b)
    assert a.container(0).kind == BITMAP and b.container(0).kind == BITMAP
    assert_ids_identical(kernels.fragment_and(a, b), ref_op(a, b, "and"))
    assert_ids_identical(kernels.fragment_xor(a, b), ref_op(a, b, "xor"))


def test_galloping_intersect_lopsided():
    big = np.arange(0, 3_000_000, 3, dtype=np.uint64)
    small = np.asarray([0, 5, 9, 2_999_997, 4_000_000], np.uint64)
    got = kernels.intersect_sorted(small, big)
    want = np.intersect1d(small, big)
    assert_ids_identical(got, want)
    got = kernels.setdiff_sorted(small, big)
    want = np.setdiff1d(small, big)
    assert_ids_identical(got, want)


def test_diff_ids():
    rng = np.random.default_rng(17)
    a = make_bitmap(rng, 10, key_span=12)
    b = make_bitmap(rng, 10, key_span=12)
    only_a, only_b = kernels.diff_ids(a, b)
    assert_ids_identical(only_a, ref_op(a, b, "andnot"))
    assert_ids_identical(only_b, ref_op(b, a, "andnot"))


# ---------------------------------------------------------------- digests


@pytest.mark.parametrize("seed", range(4))
def test_digests_identical_through_kernel_ids(seed):
    rng = np.random.default_rng(400 + seed)
    bm = make_bitmap(rng, n_containers=int(rng.integers(1, 30)), key_span=400)
    flat = kernels.flatten(bm)
    assert (block_digests(kernels.fragment_ids(flat))
            == block_digests(ref_to_ids(bm)))


def test_block_slices_matches_per_block_mask():
    rng = np.random.default_rng(21)
    bm = make_bitmap(rng, n_containers=40, key_span=4000)
    ids = ref_to_ids(bm)
    blocks = sorted({int(b) for b, _ in block_digests(ids)})
    got = kernels.block_slices(ids, blocks + [10**6])
    for b in blocks:
        lo = np.uint64(b * 100) << np.uint64(20)
        hi = np.uint64((b + 1) * 100) << np.uint64(20)
        want = ids[(ids >= lo) & (ids < hi)]
        assert_ids_identical(got[b], want)
    assert got[10**6].size == 0


def test_diff_digests():
    local = [(0, "aa"), (1, "bb"), (3, "dd")]
    peer = [(0, "aa"), (1, "XX"), (2, "cc")]
    assert kernels.diff_digests(local, peer) == [1, 2]
    assert kernels.diff_digests(peer, peer) == []
    assert kernels.diff_digests([], peer) == [0, 1, 2]


# ------------------------------------------------------ snapshot fast path


@pytest.mark.parametrize("seed", range(6))
def test_snapshot_ids_matches_deserialize(seed):
    rng = np.random.default_rng(500 + seed)
    bm = make_bitmap(rng, n_containers=int(rng.integers(1, 30)))
    buf = serialize(bm)
    # append an op tail: ops_at must land exactly where deserialize says
    tail = encode_op(OP_ADD, np.asarray([1, 2, 3], np.uint64))
    ids, ops_at = kernels.snapshot_ids(buf + tail)
    want_bm, want_at = deserialize(buf + tail)
    assert ops_at == want_at
    assert_ids_identical(ids, ref_to_ids(want_bm))


def test_snapshot_ids_empty():
    ids, ops_at = kernels.snapshot_ids(serialize(RoaringBitmap()))
    assert ids.size == 0 and ids.dtype == np.uint64
    assert ops_at == 20  # header only


def test_snapshot_ids_rejects_what_deserialize_rejects():
    bm = make_bitmap(np.random.default_rng(3), 5)
    buf = serialize(bm)
    for bad in (buf[:10], buf[:-3], b"\x00" * 40):
        try:
            deserialize(bad)
            ref_raised = False
        except ValueError:
            ref_raised = True
        if ref_raised:
            with pytest.raises(ValueError):
                kernels.snapshot_ids(bad)


def test_snapshot_ids_irregular_falls_back():
    # duplicate container keys: dict semantics (last wins) — the fast
    # parser must detect and defer to the reference decoder
    bm = RoaringBitmap.from_ids(np.asarray([1, 2, 70000], np.uint64))
    buf = bytearray(serialize(bm))
    # rewrite the second descriptor's key to equal the first (key at
    # offset 20 + 16*i)
    buf[20 + 16 : 20 + 16 + 8] = buf[20 : 20 + 8]
    want, _ = deserialize(bytes(buf))
    ids, _ = kernels.snapshot_ids(bytes(buf))
    assert_ids_identical(ids, ref_to_ids(want))


# ------------------------------------------------------- live-path parity


def test_bitmap_to_ids_now_kernel_backed():
    """RoaringBitmap.to_ids routes through the kernels and stays
    byte-identical to the reference loop."""
    rng = np.random.default_rng(42)
    bm = make_bitmap(rng, n_containers=25)
    assert_ids_identical(bm.to_ids(), ref_to_ids(bm))


def test_digest_language_unchanged():
    """The blake2b-over-ids digest itself is pinned — kernels feed it,
    never reimplement it."""
    ids = np.asarray([0, 1, (1 << 20) * 100 + 5], np.uint64)
    want = hashlib.blake2b(ids[:2].astype("<u8").tobytes(),
                           digest_size=16).hexdigest()
    assert block_digests(ids)[0] == (0, want)


# ------------------------------------------------- PROFILE cost accounting


class TestProfileContainerAccounting:
    """The batched ``row_words`` path must tally ``containers scanned
    by kind`` exactly as the retired per-container walk did: one
    ``note_containers`` call per kernel invocation whose totals equal
    a per-container recount of the row window."""

    def _fragment_with_known_row(self, tmp_path):
        from pilosa_tpu.storage.fragment import Fragment

        frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
        cols = [
            np.asarray([5, 9, 70000], np.uint64),          # 2 array cont.
            np.arange(3 << 16, (3 << 16) + 5000,            # 1 run cont.
                      dtype=np.uint64),
        ]
        rng = np.random.default_rng(7)
        cols.append(np.unique(rng.integers(                 # 1 bitmap cont.
            5 << 16, 6 << 16, 9000).astype(np.uint64)))
        cols = np.concatenate(cols)
        frag.bulk_import(np.zeros(cols.size, np.uint64), cols)
        return frag

    def _recount_reference(self, frag, row):
        """The per-container reference tally the old path produced."""
        base_key = (row << 20) >> 16
        counts = {ARRAY: 0, BITMAP: 0, RUN: 0}
        for key in range(base_key, base_key + 16):
            c = frag.bitmap._containers.get(key)
            if c is not None and c.n:
                counts[c.kind] += 1
        return counts[ARRAY], counts[BITMAP], counts[RUN]

    def test_row_words_tally_matches_per_container_walk(self, tmp_path):
        from pilosa_tpu.utils.cost import (
            activate_cost, deactivate_cost, new_cost_context,
            set_cost_enabled,
        )

        frag = self._fragment_with_known_row(tmp_path)
        try:
            set_cost_enabled(True)
            ctx = new_cost_context("t", "i")
            tok = activate_cost(ctx)
            try:
                frag.row_words(0)
            finally:
                deactivate_cost(tok)
            got = (ctx.c_array, ctx.c_bitmap, ctx.c_run)
            assert got == self._recount_reference(frag, 0)
            # pinned absolute counts for the constructed mix — a
            # regression here means the batched path's accounting
            # drifted from one-tally-per-kernel-call
            assert got == (2, 1, 1)
            assert ctx.container_scans() == 4
        finally:
            frag.close()

    def test_row_words_tally_accumulates_per_call(self, tmp_path):
        from pilosa_tpu.utils.cost import (
            activate_cost, deactivate_cost, new_cost_context,
            set_cost_enabled,
        )

        frag = self._fragment_with_known_row(tmp_path)
        try:
            set_cost_enabled(True)
            ctx = new_cost_context("t", "i")
            tok = activate_cost(ctx)
            try:
                frag.row_words(0)
                frag.row_words(0)   # second decode tallies again
                frag.row_words(1)   # empty row: zero containers
            finally:
                deactivate_cost(tok)
            assert (ctx.c_array, ctx.c_bitmap, ctx.c_run) == (4, 2, 2)
        finally:
            frag.close()
