"""Batched-evaluation plumbing: split-sum overflow safety (executor/batch).

A per-shard popcount can reach 2^20, so a plain int32 device sum wraps
past ~2^11 full shards; the split lo/hi channels must stay exact there.
"""

import jax.numpy as jnp
import numpy as np

from pilosa_tpu.executor import batch


class TestSplitSum:
    def test_round_trip_small(self):
        x = jnp.asarray(np.array([1, 2, 3], np.int32))
        assert int(batch.merge_split(np.asarray(batch.split_sum(x)))) == 6

    def test_no_int32_wrap_at_shard_scale(self):
        # 4096 shards × (2^20 - 1) per shard ≈ 2^32: wraps a plain int32
        # sum, must be exact through the split channels
        per_shard = (1 << 20) - 1
        n_shards = 4096
        x = jnp.full((n_shards,), per_shard, jnp.int32)
        naive = int(jnp.sum(x))  # documents the wrap this guards against
        got = int(batch.merge_split(np.asarray(batch.split_sum(x))))
        want = per_shard * n_shards
        assert got == want
        assert naive != want  # if XLA ever promotes, revisit the design

    def test_axis_split(self):
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.integers(0, 1 << 20, (1000, 5), dtype=np.int32))
        got = batch.merge_split(np.asarray(batch.split_sum(x, axis=0)))
        np.testing.assert_array_equal(got, np.asarray(x, np.int64).sum(0))

    def test_minmax_merge_counts(self):
        values = jnp.asarray(np.array([5, 9, 9, 0], np.int32))
        counts = jnp.asarray(np.array([2, 3, 4, 0], np.int32))
        packed = np.asarray(batch.minmax_merge(values, counts, want_max=True))
        assert int(packed[0]) == 9
        assert int(batch.merge_split(packed[1:])) == 7

    def test_minmax_merge_empty(self):
        values = jnp.asarray(np.array([7, 8], np.int32))
        counts = jnp.asarray(np.zeros(2, np.int32))
        packed = np.asarray(batch.minmax_merge(values, counts, want_max=False))
        assert int(packed[0]) == 0
        assert int(batch.merge_split(packed[1:])) == 0


class TestCountFastPath:
    """Elementwise-count classification + flat-chunk reduction (the
    count fast path skips the per-shard vmap when bit position can't
    matter)."""

    def test_classification(self):
        and_tree = ("count", ("and", ("leaf", 0), ("leaf", 1)))
        assert batch.count_elementwise_sub(and_tree, (1, 1)) == and_tree[1]
        deep = ("count", ("diff", ("or", ("leaf", 0), ("leaf", 1)),
                          ("xor", ("leaf", 2), ("const0",))))
        assert batch.count_elementwise_sub(deep, (1, 1, 1)) == deep[1]
        # flipall would count the stacked block's zero-padded slots as
        # all-ones under the flat reduction: never fast-path it
        flipped = ("count", ("and", ("leaf", 0), ("flipall", ("leaf", 1))))
        assert batch.count_elementwise_sub(flipped, (1, 1)) is None
        # shift moves bits across word boundaries per shard: no fast path
        shifted = ("count", ("and", ("shift", ("leaf", 0), 0), ("leaf", 1)))
        assert batch.count_elementwise_sub(shifted, (1, 1)) is None
        # BSI compare trees carry rank-2 plane leaves: no fast path
        bsi = ("count", ("bsicmp", ">", 0, ("leaf", 1), 0))
        assert batch.count_elementwise_sub(bsi, (2, 1)) is None
        # non-count reductions never classify
        assert batch.count_elementwise_sub(("and", ("leaf", 0), ("leaf", 1)),
                                           (1, 1)) is None

    def test_count_flat_matches_per_shard_sum(self):
        rng = np.random.default_rng(3)
        # 16 shards x 2^15 words: spans multiple COUNT_CHUNK_WORDS rows
        # only when chunked at the min() fallback; also test tiny blocks
        for s in (1, 16):
            a = rng.integers(0, 1 << 32, (s, 1 << 15), dtype=np.uint32)
            b = rng.integers(0, 1 << 32, (s, 1 << 15), dtype=np.uint32)
            sub = ("and", ("leaf", 0), ("leaf", 1))
            packed = np.asarray(
                batch.count_flat(sub, (jnp.asarray(a), jnp.asarray(b)), ())
            )
            got = int(batch.merge_split(packed))
            want = int(np.bitwise_count(a & b).sum())
            assert got == want, (s, got, want)
