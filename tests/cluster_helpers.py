"""Shared in-process cluster fixtures: HTTP helper, server boot, and a
deterministic multi-shard seed. Used by the serving-pipeline,
cluster-of-meshes, and randomized-churn suites so the request encoding,
ServerConfig surface, and seed layout live in ONE place."""

import json
import urllib.request

from pilosa_tpu.server import Server, ServerConfig
from pilosa_tpu.shardwidth import SHARD_WIDTH


def req(method, url, body=None, raw=False):
    data = (body if isinstance(body, (bytes, type(None)))
            else json.dumps(body).encode())
    r = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        r.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(r, timeout=60) as resp:
        payload = resp.read()
    return payload if raw else json.loads(payload or b"{}")


def uri(s: Server) -> str:
    return f"http://localhost:{s.port}"


def make_cluster(tmp_path, n, replica_n=1, use_mesh=False, prefix="node",
                 **config_kw):
    servers = []
    for i in range(n):
        seeds = [uri(servers[0])] if servers else []
        servers.append(Server(ServerConfig(
            data_dir=str(tmp_path / f"{prefix}{i}"), port=0,
            name=f"{prefix[0]}{i}", replica_n=replica_n, seeds=seeds,
            anti_entropy_interval=0, heartbeat_interval=0,
            use_mesh=use_mesh, **config_kw,
        )).open())
    return servers


def join_node(tmp_path, seed_server, use_mesh=False, replica_n=1,
              name="late", prefix="latenode"):
    """Boot one more node seeded off ``seed_server`` (join-resize)."""
    return Server(ServerConfig(
        data_dir=str(tmp_path / prefix), port=0, name=name,
        replica_n=replica_n, seeds=[uri(seed_server)],
        anti_entropy_interval=0, heartbeat_interval=0, use_mesh=use_mesh,
    )).open()


def seed(node0, n_shards=6):
    """Schema + bits over ``n_shards`` shards + a BSI field.

    Layout (per shard s): row 1 holds cols {s*SW+100..103}, row 2 holds
    {s*SW+100..101} (a SUBSET of row 1, so intersections are
    non-trivial), and BSI field v maps col s*SW+100 -> (s+1)*7.
    """
    req("POST", f"{uri(node0)}/index/i",
        {"options": {"trackExistence": True}})
    req("POST", f"{uri(node0)}/index/i/field/f", {})
    req("POST", f"{uri(node0)}/index/i/field/v",
        {"options": {"type": "int", "min": 0, "max": 1000}})
    for row, per_shard in [(1, 4), (2, 2)]:
        cols = [
            s * SHARD_WIDTH + 100 + c
            for s in range(n_shards) for c in range(per_shard)
        ]
        req("POST", f"{uri(node0)}/index/i/field/f/import",
            {"rows": [row] * len(cols), "columns": cols})
    req("POST", f"{uri(node0)}/index/i/field/v/import-value",
        {"columns": [s * SHARD_WIDTH + 100 for s in range(n_shards)],
         "values": [(s + 1) * 7 for s in range(n_shards)]})
