"""Kernel tests: the engine's fused expression ops vs a numpy set oracle.

Modeled on the reference's exhaustive pairwise container-op tests
(roaring/roaring_test.go randomized ops vs a map oracle — SURVEY.md §4),
but driving the ACTUAL engine path: expr.evaluate lowers the same node
structures the executor compiles, so these cover the fused kernels that
serve queries rather than a parallel ops surface.
"""

import numpy as np
import pytest

from pilosa_tpu.executor import expr
from pilosa_tpu.ops.bitops import shift
from pilosa_tpu.ops.packing import pack_bits, unpack_bits, popcount_words
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

N_BITS = 1 << 14  # small width keeps tests fast; ops are shape-polymorphic
N_WORDS = N_BITS // 32

# Density patterns mirroring roaring's container kinds: sparse ~ array
# containers, dense ~ bitmap containers, runs ~ run containers.
DENSITIES = [0.0005, 0.02, 0.5]


def rand_ids(rng, density):
    mask = rng.random(N_BITS) < density
    return np.nonzero(mask)[0]


def rand_run_ids(rng):
    """Run-heavy set (oracle for run-container-style data)."""
    ids = []
    pos = 0
    while pos < N_BITS:
        run = int(rng.integers(1, 500))
        if rng.random() < 0.5:
            ids.extend(range(pos, min(pos + run, N_BITS)))
        pos += run
    return np.array(ids, dtype=np.int64)


def ev(structure, *leaves, scalars=()):
    return expr.evaluate(
        structure, [pack_bits(ids, N_BITS) for ids in leaves], list(scalars)
    )


def as_set(row):
    return set(unpack_bits(np.asarray(row)).tolist())


L0, L1 = ("leaf", 0), ("leaf", 1)


@pytest.mark.parametrize("da", DENSITIES)
@pytest.mark.parametrize("db", DENSITIES)
def test_pairwise_set_ops(da, db):
    rng = np.random.default_rng(int(da * 1e6) * 31 + int(db * 1e6))
    a_ids, b_ids = rand_ids(rng, da), rand_ids(rng, db)
    sa, sb = set(a_ids.tolist()), set(b_ids.tolist())

    assert as_set(ev(("or", L0, L1), a_ids, b_ids)) == sa | sb
    assert as_set(ev(("and", L0, L1), a_ids, b_ids)) == sa & sb
    assert as_set(ev(("diff", L0, L1), a_ids, b_ids)) == sa - sb
    assert as_set(ev(("xor", L0, L1), a_ids, b_ids)) == sa ^ sb
    assert int(ev(("count", L0), a_ids)) == len(sa)
    assert int(ev(("count", ("and", L0, L1)), a_ids, b_ids)) == len(sa & sb)


def test_run_heavy_ops():
    rng = np.random.default_rng(7)
    a_ids, b_ids = rand_run_ids(rng), rand_run_ids(rng)
    sa, sb = set(a_ids.tolist()), set(b_ids.tolist())
    assert as_set(ev(("or", L0, L1), a_ids, b_ids)) == sa | sb
    assert as_set(ev(("xor", L0, L1), a_ids, b_ids)) == sa ^ sb
    assert int(ev(("count", ("and", L0, L1)), a_ids, b_ids)) == len(sa & sb)


def test_fused_tree_single_pass():
    """A deep tree — Count(Diff(Union(a,b), Xor(b,Not(c)))) — matches set
    algebra (the executor compiles exactly such structures)."""
    rng = np.random.default_rng(11)
    a_ids, b_ids, c_ids = (rand_ids(rng, d) for d in DENSITIES)
    sa, sb, sc = (set(x.tolist()) for x in (a_ids, b_ids, c_ids))
    universe = set(range(N_BITS))
    structure = ("count", ("diff", ("or", ("leaf", 0), ("leaf", 1)),
                           ("xor", ("leaf", 1), ("flipall", ("leaf", 2)))))
    got = int(ev(structure, a_ids, b_ids, c_ids))
    assert got == len((sa | sb) - (sb ^ (universe - sc)))


@pytest.mark.parametrize("n", [0, 1, 31, 32, 33, 64, 100, 1000,
                               -1, -5, -31, -32, -33, -100])
def test_shift(n):
    rng = np.random.default_rng(abs(n) + 1)
    ids = rand_ids(rng, 0.1)
    want = {i + n for i in ids.tolist() if 0 <= i + n < N_BITS}
    # standalone kernel and the fused expr node must agree
    a = pack_bits(ids, N_BITS)
    assert set(unpack_bits(np.asarray(shift(a, n))).tolist()) == want
    assert as_set(ev(("shift", L0, 0), ids, scalars=[n])) == want


def test_row_block_countrows():
    """countrows: per-row popcount over a stacked row-block, optionally
    masked (the TopN/Rows phase-1 kernel)."""
    rng = np.random.default_rng(3)
    rows = [rand_ids(rng, d) for d in (0.001, 0.2, 0.6, 0.0)]
    block = np.stack([pack_bits(r, N_BITS) for r in rows])
    counts = np.asarray(expr.evaluate(("countrows", 0, None), [block], []))
    assert counts.tolist() == [len(r) for r in rows]
    mask_ids = rand_ids(rng, 0.5)
    masked = np.asarray(
        expr.evaluate(("countrows", 0, ("leaf", 1)),
                      [block, pack_bits(mask_ids, N_BITS)], [])
    )
    sm = set(mask_ids.tolist())
    assert masked.tolist() == [len(set(r.tolist()) & sm) for r in rows]


def test_full_shard_width_roundtrip():
    rng = np.random.default_rng(11)
    ids = np.sort(rng.choice(SHARD_WIDTH, size=5000, replace=False))
    words = pack_bits(ids, SHARD_WIDTH)
    assert words.shape == (WORDS_PER_SHARD,)
    assert popcount_words(words) == 5000
    assert int(expr.evaluate(("count", ("leaf", 0)), [words], [])) == 5000
    # offset form: shard-local words decode to global column ids
    # (executor/result.py relies on this for per-shard segments)
    off = unpack_bits(np.asarray(words), offset=SHARD_WIDTH)
    assert off.tolist() == (ids + SHARD_WIDTH).tolist()
