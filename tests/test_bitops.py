"""M0 kernel tests: packed bitwise ops vs a numpy set oracle.

Modeled on the reference's exhaustive pairwise container-op tests
(roaring/roaring_test.go randomized ops vs a map oracle — SURVEY.md §4):
we randomize id sets, run the device kernel, and compare against python
set algebra.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitops
from pilosa_tpu.ops.packing import pack_bits, unpack_bits, popcount_words
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

N_BITS = 1 << 14  # small width keeps tests fast; ops are shape-polymorphic
N_WORDS = N_BITS // 32

# Density patterns mirroring roaring's container kinds: sparse ~ array
# containers, dense ~ bitmap containers, runs ~ run containers.
DENSITIES = [0.0005, 0.02, 0.5]


def rand_ids(rng, density):
    mask = rng.random(N_BITS) < density
    return np.nonzero(mask)[0]


def rand_run_ids(rng):
    """Run-heavy set (oracle for run-container-style data)."""
    ids = []
    pos = 0
    while pos < N_BITS:
        run = int(rng.integers(1, 500))
        if rng.random() < 0.5:
            ids.extend(range(pos, min(pos + run, N_BITS)))
        pos += run
    return np.array(ids, dtype=np.int64)


@pytest.mark.parametrize("da", DENSITIES)
@pytest.mark.parametrize("db", DENSITIES)
def test_pairwise_set_ops(da, db):
    rng = np.random.default_rng(int(da * 1e6) * 31 + int(db * 1e6))
    a_ids, b_ids = rand_ids(rng, da), rand_ids(rng, db)
    a, b = pack_bits(a_ids, N_BITS), pack_bits(b_ids, N_BITS)
    sa, sb = set(a_ids.tolist()), set(b_ids.tolist())

    assert set(unpack_bits(np.asarray(bitops.union(a, b))).tolist()) == sa | sb
    assert set(unpack_bits(np.asarray(bitops.intersect(a, b))).tolist()) == sa & sb
    assert set(unpack_bits(np.asarray(bitops.difference(a, b))).tolist()) == sa - sb
    assert set(unpack_bits(np.asarray(bitops.xor(a, b))).tolist()) == sa ^ sb
    assert int(bitops.count(a)) == len(sa)
    assert int(bitops.intersect_count(a, b)) == len(sa & sb)


def test_run_heavy_ops():
    rng = np.random.default_rng(7)
    a_ids, b_ids = rand_run_ids(rng), rand_run_ids(rng)
    a, b = pack_bits(a_ids, N_BITS), pack_bits(b_ids, N_BITS)
    sa, sb = set(a_ids.tolist()), set(b_ids.tolist())
    assert set(unpack_bits(np.asarray(bitops.xor(a, b))).tolist()) == sa ^ sb
    assert int(bitops.intersect_count(a, b)) == len(sa & sb)


@pytest.mark.parametrize(
    "start,stop",
    [(0, N_BITS), (0, 0), (5, 37), (32, 64), (31, 33), (100, 100), (0, 31),
     (N_BITS - 13, N_BITS), (1000, 9999), (64, 96)],
)
def test_count_range_and_flip(start, stop):
    rng = np.random.default_rng(start * 7919 + stop)
    ids = rand_ids(rng, 0.3)
    a = pack_bits(ids, N_BITS)
    s = set(ids.tolist())
    expected = len([i for i in s if start <= i < stop])
    assert int(bitops.count_range(a, start, stop)) == expected

    flipped = set(unpack_bits(np.asarray(bitops.flip_range(a, start, stop))).tolist())
    expected_flip = (s - set(range(start, stop))) | (set(range(start, stop)) - s)
    assert flipped == expected_flip


@pytest.mark.parametrize("n", [0, 1, 31, 32, 33, 64, 100, 1000,
                               -1, -5, -31, -32, -33, -100])
def test_shift(n):
    rng = np.random.default_rng(abs(n) + 1)
    ids = rand_ids(rng, 0.1)
    a = pack_bits(ids, N_BITS)
    shifted = set(unpack_bits(np.asarray(bitops.shift(a, n))).tolist())
    expected = {i + n for i in ids.tolist() if 0 <= i + n < N_BITS}
    assert shifted == expected


def test_row_block_ops():
    rng = np.random.default_rng(3)
    rows = [rand_ids(rng, d) for d in (0.001, 0.2, 0.6, 0.0)]
    block = np.stack([pack_bits(r, N_BITS) for r in rows])
    counts = np.asarray(bitops.count_rows(block))
    assert counts.tolist() == [len(r) for r in rows]
    nonempty = np.asarray(bitops.rows_any(block))
    assert nonempty.tolist() == [len(r) > 0 for r in rows]


def test_full_shard_width_roundtrip():
    rng = np.random.default_rng(11)
    ids = np.sort(rng.choice(SHARD_WIDTH, size=5000, replace=False))
    words = pack_bits(ids, SHARD_WIDTH)
    assert words.shape == (WORDS_PER_SHARD,)
    assert popcount_words(words) == 5000
    np.testing.assert_array_equal(unpack_bits(words, offset=1 << 20),
                                  ids.astype(np.uint64) + (1 << 20))
    assert int(bitops.count(words)) == 5000
