"""Multi-chip execution tests on the 8-device virtual CPU mesh.

The TPU analog of the reference's in-process cluster fixture
(test.MustRunCluster — SURVEY.md §4): real multi-device SPMD execution
without TPU hardware. Every result is cross-checked against the
single-device Executor on the same holder.
"""

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel import DistExecutor, make_mesh
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import FieldOptions, Holder

N_SHARDS = 13  # deliberately not a multiple of the 8-device mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture
def env(tmp_path, mesh):
    holder = Holder(str(tmp_path / "data")).open()
    idx = holder.create_index("big")
    f = idx.create_field("f")
    g = idx.create_field("g")
    fare = idx.create_field("fare", FieldOptions(type="int", min=-5, max=1000))
    rng = np.random.default_rng(7)
    all_cols = []
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        cols = np.sort(rng.choice(SHARD_WIDTH, 200, replace=False)) + base
        f.view("standard", create=True).fragment(shard, create=True).bulk_import(
            np.repeat([1, 2], 100), cols % SHARD_WIDTH
        )
        for c in cols[::5]:
            g.set_bit(3, int(c))
        for c in cols[:20]:
            fare.set_value(int(c), int(rng.integers(-5, 1000)))
        all_cols.extend(cols.tolist())
    idx.mark_columns_exist(all_cols)
    yield holder, Executor(holder), DistExecutor(holder, mesh)
    holder.close()


def both(env, pql):
    holder, base, dist = env
    (r1,) = base.execute("big", pql)
    (r2,) = dist.execute("big", pql)
    return r1, r2


class TestDistMatchesSingle:
    def test_count(self, env):
        r1, r2 = both(env, "Count(Row(f=1))")
        assert r1 == r2 > 0

    def test_count_intersect(self, env):
        r1, r2 = both(env, "Count(Intersect(Row(f=1), Row(g=3)))")
        assert r1 == r2 > 0

    def test_row_segments(self, env):
        r1, r2 = both(env, "Union(Row(f=2), Row(g=3))")
        assert sorted(r1.segments) == sorted(r2.segments)
        np.testing.assert_array_equal(r1.columns(), r2.columns())

    def test_not_all(self, env):
        r1, r2 = both(env, "Not(Row(f=1))")
        np.testing.assert_array_equal(r1.columns(), r2.columns())
        r1, r2 = both(env, "Count(All())")
        assert r1 == r2

    def test_complex_tree(self, env):
        pql = "Count(Difference(Union(Row(f=1), Row(f=2)), Intersect(Row(g=3), All())))"
        r1, r2 = both(env, pql)
        assert r1 == r2

    def test_sum(self, env):
        r1, r2 = both(env, 'Sum(field="fare")')
        assert (r1.value, r1.count) == (r2.value, r2.count)
        assert r2.count > 0

    def test_sum_filtered(self, env):
        r1, r2 = both(env, 'Sum(Row(fare > 100), field="fare")')
        assert (r1.value, r1.count) == (r2.value, r2.count)

    def test_min_max(self, env):
        for call in ('Min(field="fare")', 'Max(field="fare")'):
            r1, r2 = both(env, call)
            assert (r1.value, r1.count) == (r2.value, r2.count), call

    def test_range_compare(self, env):
        for op in ("<", "<=", ">", ">=", "==", "!="):
            r1, r2 = both(env, f"Range(fare {op} 500)")
            np.testing.assert_array_equal(r1.columns(), r2.columns())

    def test_topn(self, env):
        r1, r2 = both(env, "TopN(f, n=3)")
        assert [(p.id, p.count) for p in r1] == [(p.id, p.count) for p in r2]

    def test_topn_filtered(self, env):
        r1, r2 = both(env, "TopN(f, Row(g=3), n=2)")
        assert [(p.id, p.count) for p in r1] == [(p.id, p.count) for p in r2]

    def test_shift(self, env):
        r1, r2 = both(env, "Shift(Row(f=1), n=7)")
        np.testing.assert_array_equal(r1.columns(), r2.columns())


class TestDistConsistency:
    def test_write_invalidates_stacked_cache(self, env):
        holder, base, dist = env
        (before,) = dist.execute("big", "Count(Row(f=1))")
        holder.index("big").field("f").set_bit(1, 5 * SHARD_WIDTH + 999_999)
        (after,) = dist.execute("big", "Count(Row(f=1))")
        assert after == before + 1

    def test_empty_shard_padding(self, env, mesh):
        """Shard count not divisible by mesh size: padded slots contribute 0."""
        holder, base, dist = env
        (r1,) = base.execute("big", "Count(Row(f=2))")
        (r2,) = dist.execute("big", "Count(Row(f=2))")
        assert r1 == r2

    def test_mesh_subset(self, env):
        holder, base, dist = env
        import jax

        small = make_mesh(n_devices=3)
        dist3 = DistExecutor(holder, small)
        (r1,) = base.execute("big", "Count(Row(f=1))")
        (r3,) = dist3.execute("big", "Count(Row(f=1))")
        assert r1 == r3


class TestDistGroupBy:
    def groups_json(self, res):
        return [g.to_json() for g in res]

    def test_groupby_matches_single(self, env):
        r1, r2 = both(env, "GroupBy(Rows(f), Rows(g))")
        assert self.groups_json(r1) == self.groups_json(r2)
        assert r1  # non-empty

    def test_groupby_with_filter(self, env):
        r1, r2 = both(env, "GroupBy(Rows(f), Rows(g), filter=Row(fare > 100))")
        assert self.groups_json(r1) == self.groups_json(r2)

    def test_groupby_aggregate_sum(self, env):
        r1, r2 = both(env, 'GroupBy(Rows(f), aggregate=Sum(field="fare"))')
        assert self.groups_json(r1) == self.groups_json(r2)
        assert any(g.sum is not None for g in r2)

    def test_groupby_aggregate_sum_with_filter(self, env):
        r1, r2 = both(
            env,
            'GroupBy(Rows(f), Rows(g), filter=Row(fare > 0), aggregate=Sum(field="fare"))',
        )
        assert self.groups_json(r1) == self.groups_json(r2)

    def test_groupby_limit(self, env):
        r1, r2 = both(env, "GroupBy(Rows(f), Rows(g), limit=1)")
        assert self.groups_json(r1) == self.groups_json(r2)
        assert len(r2) == 1

    def test_groupby_having_and_topn_threshold(self, env):
        """Round-4 PQL edges on the mesh path: having filters merged
        groups and threshold floors the exact recount, both matching the
        single-device executor."""
        r1, r2 = both(env, "GroupBy(Rows(f), Rows(g), having=Condition(count > 0))")
        assert self.groups_json(r1) == self.groups_json(r2) and r2
        base_counts = {g.count for g in r2}
        floor = sorted(base_counts)[len(base_counts) // 2]  # drop some
        r1, r2 = both(
            env, f"GroupBy(Rows(f), Rows(g), having=Condition(count >= {floor}))"
        )
        assert self.groups_json(r1) == self.groups_json(r2)
        assert all(g.count >= floor for g in r2)
        r1, r2 = both(env, "TopN(f, n=10, threshold=2)")
        assert [(p.id, p.count) for p in r1] == [(p.id, p.count) for p in r2]

    def test_groupby_level_pruning_path(self, env, monkeypatch):
        """Force the per-dimension prefix-pruning strategy (cross-product
        'too big' for a single level) and check it matches the dense path."""
        import pilosa_tpu.executor.executor as ex_mod

        monkeypatch.setattr(ex_mod, "GROUPBY_DENSE_MAX_GROUPS", 1)
        r1, r2 = both(env, "GroupBy(Rows(f), Rows(g))")
        assert self.groups_json(r1) == self.groups_json(r2)

    def test_groupby_tiny_chunk_budget(self, env, monkeypatch):
        """A mask byte budget so small every level runs one candidate per
        chunk must still produce identical results (chunk concat + unpack)."""
        from pilosa_tpu.executor import batch as batch_mod

        monkeypatch.setattr(batch_mod, "GROUPBY_MASK_BUDGET_BYTES", 1)
        r1, r2 = both(
            env,
            'GroupBy(Rows(f), Rows(g), aggregate=Sum(field="fare"))',
        )
        assert self.groups_json(r1) == self.groups_json(r2)


class TestDistWritePatching:
    def test_write_patches_sharded_leaf_in_place(self, env):
        """A Set() between two mesh queries scatter-patches the
        NamedSharding-resident stacked leaf — no re-decode, no eviction
        (SURVEY.md §7.3 hard part #3 on the SPMD path)."""
        from pilosa_tpu.storage import residency

        holder, base, dist = env
        (c1,) = dist.execute("big", "Count(Row(f=1))")
        cache = residency.global_row_cache()
        misses = cache.misses
        new_col = 2 * SHARD_WIDTH + 3  # not in the rng pattern? ensure:
        idx = holder.index("big")
        frag = idx.field("f").view("standard").fragment(2)
        delta = 0 if frag.contains(1, 3) else 1
        dist.execute("big", f"Set({new_col}, f=1)")
        (c2,) = dist.execute("big", "Count(Row(f=1))")
        assert c2 == c1 + delta
        assert cache.misses == misses  # patched in place, not re-decoded
        assert cache.updates >= 1
        (r_base,) = base.execute("big", "Row(f=1)")
        (r_dist,) = dist.execute("big", "Row(f=1)")
        assert r_base.columns().tolist() == r_dist.columns().tolist()
        assert new_col in set(r_dist.columns().tolist())


class TestDistMicrobatch:
    """Executor.submit on the mesh path: pipelined same-shape reductions
    coalesce into micro-batched SPMD dispatches (one shard_map program of
    B queries), matching the single-device executor's results — the
    serving-path behavior, not just correctness-demo eager dispatch."""

    def test_submit_count_microbatch_coalesces_on_mesh(self, env):
        holder, base, dist = env
        dispatches = []
        orig = dist._program_batched

        def counting(structure, rk, lr, ns, nq):
            dispatches.append(nq)
            return orig(structure, rk, lr, ns, nq)

        dist._program_batched = counting
        try:
            pqls = [
                f"Count(Intersect(Row(f={1 + (i % 2)}), Row(g=3)))"
                for i in range(32)
            ]
            want = [base.execute("big", p)[0] for p in pqls]
            defs = [dist.submit("big", p)[0] for p in pqls]
            got = [d.result() for d in defs]
        finally:
            dist._program_batched = orig
        assert got == want
        # 32 same-shape queries / microbatch_max=16 → exactly 2 dispatches
        assert sum(dispatches) == 32
        assert len(dispatches) == -(-32 // dist.microbatch_max)

    def test_submit_partial_group_flushes_on_resolve(self, env):
        holder, base, dist = env
        pqls = ["Count(Row(f=1))", "Count(Row(f=2))", "Count(Row(g=3))"]
        want = [base.execute("big", p)[0] for p in pqls]
        defs = [dist.submit("big", p)[0] for p in pqls]
        assert dist._pending  # 3 < microbatch_max: group still pending
        assert [d.result() for d in defs] == want
        assert not dist._pending

    def test_submit_bsi_aggregates_microbatch_on_mesh(self, env):
        holder, base, dist = env
        pqls = [
            'Sum(field="fare")',
            'Sum(Row(f=1), field="fare")',
            'Min(field="fare")',
            'Max(field="fare")',
        ]
        want = [base.execute("big", p)[0] for p in pqls]
        defs = [dist.submit("big", p)[0] for p in pqls]
        assert [d.result() for d in defs] == want
