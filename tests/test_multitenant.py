"""Skewed-traffic actuators (ISSUE 12): the write-invalidated result
cache (serving/rescache.py) and heat-driven HBM residency tiering
(storage/tiering.py + the DeviceRowCache host tier).

Covers: cache unit semantics (eligibility, per-field vs index-wide
invalidation, the fill-race version fence, heat-weighted eviction),
read-your-writes through the HTTP cache path (an acked write is never
masked by stale cached bytes — sequential and under concurrent write/
fill races, single-process AND through different mp-serving workers'
rings), the cost-plane satellites (PROFILE resultCacheHit, tenant
ledger billing), the /debug/rescache + /debug/heatmap?tier= surfaces,
metrics exposition, tiering demote/promote/hysteresis/pacing, and the
ServerConfig knob roundtrips."""

import json
import socket
import threading
import time
import urllib.request

import urllib.error

import numpy as np
import pytest

from pilosa_tpu.pql import parse
from pilosa_tpu.serving import rescache
from pilosa_tpu.serving.rescache import (
    ResultCache,
    global_result_cache,
    query_field_deps,
)
from pilosa_tpu.server import Server, ServerConfig
from pilosa_tpu.storage import residency
from pilosa_tpu.storage.heat import HeatMap
from pilosa_tpu.storage.residency import DeviceRowCache
from pilosa_tpu.storage.tiering import ResidencyTierer
from pilosa_tpu.shardwidth import WORDS_PER_SHARD


@pytest.fixture(autouse=True)
def _isolated_result_cache():
    """A fresh disabled global per test: entries are scope-qualified,
    but counters and budget must not leak across tests."""
    rescache.set_global_result_cache(ResultCache(0))
    yield
    rescache.set_global_result_cache(ResultCache(0))


def _req(port, method, path, body=None, headers=None, timeout=30):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body, method=method, headers=headers or {},
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.read()


def _query(port, index, pql, headers=None, path_suffix=""):
    return _req(port, "POST", f"/index/{index}/query{path_suffix}",
                pql.encode(), headers=headers)


# ---------------------------------------------------------------- unit


class TestResultCacheUnit:
    def test_insert_lookup_roundtrip(self):
        c = ResultCache(1 << 20)
        snap = c.version()
        assert c.lookup("s", "i", "Count(Row(f=1))") is None
        assert c.insert("s", "i", "Count(Row(f=1))", b'{"results":[3]}',
                        frozenset({"f"}), snap)
        assert c.lookup("s", "i", "Count(Row(f=1))") == b'{"results":[3]}'
        # whitespace-trim normalization, scope isolation
        assert c.peek("s", "i", "  Count(Row(f=1))  ") == b'{"results":[3]}'
        assert c.peek("other", "i", "Count(Row(f=1))") is None
        m = c.metrics()
        assert m["result_cache_hits_total"] == 1
        assert m["result_cache_misses_total"] == 1
        assert m["result_cache_fills_total"] == 1

    def test_field_precise_invalidation(self):
        c = ResultCache(1 << 20)
        c.insert("s", "i", "Count(Row(f=1))", b"f", frozenset({"f"}),
                 c.version())
        c.insert("s", "i", "Count(Row(g=1))", b"g", frozenset({"g"}),
                 c.version())
        c.invalidate("s", "i", "g", 0)
        assert c.peek("s", "i", "Count(Row(f=1))") == b"f"
        assert c.peek("s", "i", "Count(Row(g=1))") is None
        # a different index's write touches nothing
        c.invalidate("s", "other", "f", 0)
        assert c.peek("s", "i", "Count(Row(f=1))") == b"f"
        c.invalidate("s", "i", "f", 3)
        assert c.peek("s", "i", "Count(Row(f=1))") is None

    def test_wildcard_entries_die_on_any_write(self):
        c = ResultCache(1 << 20)
        # fields=None = depends on the whole index (TopN/Not/All shapes)
        c.insert("s", "i", "TopN(f, n=5)", b"t", None, c.version())
        c.invalidate("s", "i", "unrelated_field", 9)
        assert c.peek("s", "i", "TopN(f, n=5)") is None

    def test_index_wide_invalidation(self):
        c = ResultCache(1 << 20)
        c.insert("s", "i", "Count(Row(f=1))", b"f", frozenset({"f"}),
                 c.version())
        c.invalidate_index_wide("s", "i")
        assert c.peek("s", "i", "Count(Row(f=1))") is None

    def test_fill_race_refused(self):
        """The cutoff discipline: a write landing between the fill's
        snapshot and its insert must refuse the insert — for precise,
        wildcard, AND index-wide events."""
        c = ResultCache(1 << 20)
        snap = c.version()
        c.invalidate("s", "i", "f", 0)
        assert not c.insert("s", "i", "Count(Row(f=1))", b"stale",
                            frozenset({"f"}), snap)
        assert c.peek("s", "i", "Count(Row(f=1))") is None
        assert c.metrics()["result_cache_fill_races_total"] == 1
        # unrelated field's write does NOT refuse a precise fill
        snap = c.version()
        c.invalidate("s", "i", "g", 0)
        assert c.insert("s", "i", "Count(Row(f=1))", b"ok",
                        frozenset({"f"}), snap)
        # ... but DOES refuse a wildcard fill
        snap = c.version()
        c.invalidate("s", "i", "g", 0)
        assert not c.insert("s", "i", "TopN(f)", b"stale", None, snap)
        # index-wide event refuses a precise fill of an untouched field
        snap = c.version()
        c.invalidate_index_wide("s", "i")
        assert not c.insert("s", "i", "Count(Row(h=1))", b"stale",
                            frozenset({"h"}), snap)

    def test_clear_fences_inflight_fills(self):
        c = ResultCache(1 << 20)
        snap = c.version()
        c.clear()
        assert not c.insert("s", "i", "Count(Row(f=1))", b"stale",
                            frozenset({"f"}), snap)

    def test_dep_version_table_bounded(self):
        """Field-cardinality churn must not grow the fence table
        forever: past MAX_DEP_VERSIONS the oldest half is pruned and the
        fill floor rises, so a fill snapshotted before the prune refuses
        (it can no longer prove its deps' history) while a fresh fill
        still lands."""
        from pilosa_tpu.serving.rescache import MAX_DEP_VERSIONS

        c = ResultCache(1 << 20)
        old_snap = c.version()
        for j in range(MAX_DEP_VERSIONS + 10):
            c.invalidate("s", "i", f"churn{j}", 0)
        assert len(c._dep_version) <= MAX_DEP_VERSIONS
        assert not c.insert("s", "i", "Count(Row(f=1))", b"stale",
                            frozenset({"f"}), old_snap)
        assert c.insert("s", "i", "Count(Row(f=1))", b"ok",
                        frozenset({"f"}), c.version())
        assert c.peek("s", "i", "Count(Row(f=1))") == b"ok"

    def test_heat_weighted_eviction(self):
        """Overflow evicts the coldest entries: one hot entry survives
        a burst of one-off fills that would flush a plain LRU."""
        c = ResultCache(4096, half_life_s=300.0)
        payload = b"x" * 64
        assert c.insert("s", "i", "hot", payload, frozenset({"f"}),
                        c.version())
        for _ in range(50):
            c.record_hit("s", "i", "hot")
        for j in range(40):  # ~40 * (64+overhead) >> budget
            c.insert("s", "i", f"cold{j}", payload, frozenset({"f"}),
                     c.version())
        assert c.peek("s", "i", "hot") == payload
        assert c.metrics()["result_cache_evictions_total"] > 0
        assert c.metrics()["result_cache_bytes"] <= 4096

    def test_disabled_budget_zero(self):
        c = ResultCache(0)
        assert not c.enabled
        assert not c.insert("s", "i", "q", b"x", None, c.version())
        assert c.peek("s", "i", "q") is None

    def test_configure_shrink_and_disable(self):
        c = ResultCache(1 << 20)
        c.insert("s", "i", "q", b"x" * 100, None, c.version())
        c.configure(0)
        assert c.peek("s", "i", "q") is None and not c.enabled


class TestFieldDeps:
    @pytest.mark.parametrize("pql,want", [
        ("Count(Row(f=1))", {"f"}),
        ("Row(f=1)", {"f"}),
        ("Count(Intersect(Row(f=1), Row(g=2)))", {"f", "g"}),
        ("Sum(Row(f=1), field=sal)", {"f", "sal"}),
        ("Min(field=sal)", {"sal"}),
        ("Range(fare > 10)", {"fare"}),
        ("Count(Union(Row(a=1), Xor(Row(b=1), Row(c=1))))",
         {"a", "b", "c"}),
        ("Count(Difference(Row(f=1), Row(g=1)))", {"f", "g"}),
    ])
    def test_precise_shapes(self, pql, want):
        assert query_field_deps(parse(pql)) == frozenset(want)

    @pytest.mark.parametrize("pql", [
        "Count(Not(Row(f=1)))",   # existence field
        "All()",                  # existence field
        "TopN(f, n=5)",           # rank cache
        "GroupBy(Rows(f))",       # row enumeration
    ])
    def test_index_wide_shapes(self, pql):
        assert query_field_deps(parse(pql)) is None

    @pytest.mark.parametrize("pql,want", [
        # a Condition key IS the field even when it collides with a
        # parameter name (condition_field applies no reserved filter)
        ("Range(n > 10)", {"n"}),
        ("Count(Row(limit > 5))", {"limit"}),
        # per-call parameters stay skipped without losing precision
        ("Shift(Row(f=1), n=2)", {"f"}),
        ("Row(t=1, from='2019-01-01T00:00', to='2019-12-31T00:00')",
         {"t"}),
    ])
    def test_reserved_name_collisions_precise(self, pql, want):
        assert query_field_deps(parse(pql)) == frozenset(want)

    @pytest.mark.parametrize("pql", [
        # keys the executor reserves for OTHER call shapes are ambiguous
        # here: whether Row(n=1) names a field lives in executor code,
        # so the cache must assume whole-index rather than record a dep
        # set that misses the write ("n"/"field" are legal field names)
        "Count(Intersect(Row(n=1), Row(f=2)))",
        "Count(Row(field=1))",
        "Count(Row(limit=3))",
    ])
    def test_ambiguous_reserved_args_bail_index_wide(self, pql):
        assert query_field_deps(parse(pql)) is None

    def test_batched_import_one_invalidation_event(self, tmp_path):
        """The batched import tail (_apply_batch_locked: mutex + BSI
        paths) issues ONE result-cache write event per batch, like
        _after_rows_added — not one per touched row (a bit_depth-32 BSI
        import would otherwise take the global cache lock ~34x per
        shard and inflate the invalidation counter to match)."""
        from pilosa_tpu.storage.fragment import Fragment

        rescache.set_global_result_cache(ResultCache(1 << 20))
        try:
            frag = Fragment(str(tmp_path / "f"), "i", "f", "standard",
                            0).open()
            cache = rescache.global_result_cache()
            before = cache.metrics()["result_cache_invalidations_total"]
            frag.import_bsi(np.arange(16, dtype=np.uint64),
                            np.arange(16, dtype=np.uint64) + 1, 8)
            after = cache.metrics()["result_cache_invalidations_total"]
            assert after - before == 1
            frag.close()
        finally:
            rescache.set_global_result_cache(ResultCache(0))

    def test_ambiguous_args_mirror_executor_reserved(self):
        """_AMBIGUOUS_ARGS is a hand-copied mirror of the executor's
        reserved-arg set (a module-level import would cycle through the
        fragment write hooks). Drift is a silent RYW hazard: a new
        reserved key unknown to the cache would be recorded as a field
        dependency, and writes to the REAL field would never invalidate
        the entry."""
        from pilosa_tpu.executor.executor import _RESERVED_ARGS
        from pilosa_tpu.serving.rescache import _AMBIGUOUS_ARGS

        assert _AMBIGUOUS_ARGS == set(_RESERVED_ARGS)


# ------------------------------------------------------- http integration


@pytest.fixture
def cache_server(tmp_path):
    server = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0, use_mesh=False,
        result_cache_bytes=8 << 20,
    )).open()
    port = server.port
    _req(port, "POST", "/index/i", b"{}")
    _req(port, "POST", "/index/i/field/f", b"{}")
    _req(port, "POST", "/index/i/field/g", b"{}")
    for col in (1, 2, 70):
        assert _query(port, "i", f"Set({col}, f=1)")[0] == 200
    try:
        yield server
    finally:
        server.close()


class TestServingIntegration:
    def test_hit_serves_identical_bytes(self, cache_server):
        port = cache_server.port
        st1, b1 = _query(port, "i", "Count(Row(f=1))")
        st2, b2 = _query(port, "i", "Count(Row(f=1))")
        assert (st1, st2) == (200, 200) and b1 == b2 == b'{"results":[3]}'
        m = global_result_cache().metrics()
        assert m["result_cache_hits_total"] >= 1
        assert m["result_cache_fills_total"] >= 1

    def test_read_your_writes_after_ack(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(f=1))")
        _query(port, "i", "Count(Row(f=1))")  # cached now
        assert _query(port, "i", "Set(99, f=1)")[0] == 200
        st, body = _query(port, "i", "Count(Row(f=1))")
        assert json.loads(body)["results"] == [4], \
            "acked write masked by a stale cached result"

    def test_import_invalidates(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(g=7))")
        _query(port, "i", "Count(Row(g=7))")
        st, _ = _req(port, "POST", "/index/i/field/g/import",
                     json.dumps({"rows": [7, 7], "columns": [5, 6]})
                     .encode())
        assert st == 200
        st, body = _query(port, "i", "Count(Row(g=7))")
        assert json.loads(body)["results"] == [2]

    def test_unrelated_field_write_keeps_entry(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(f=1))")
        fills = global_result_cache().metrics()["result_cache_fills_total"]
        assert _query(port, "i", "Set(5, g=3)")[0] == 200
        st, body = _query(port, "i", "Count(Row(f=1))")
        assert json.loads(body)["results"] == [3]
        m = global_result_cache().metrics()
        # served from cache: no refill happened after the g write
        assert m["result_cache_fills_total"] == fills
        assert m["result_cache_hits_total"] >= 1

    def test_attr_write_invalidates(self, cache_server):
        port = cache_server.port
        st, b1 = _query(port, "i", "Row(f=1)")
        _query(port, "i", "Row(f=1)")
        assert _query(port, "i", 'SetRowAttrs(f, 1, tag="hot")')[0] == 200
        st, b2 = _query(port, "i", "Row(f=1)")
        assert json.loads(b2)["results"][0]["attrs"] == {"tag": "hot"}, \
            "attr write masked by a stale cached result"

    def test_profile_reports_result_cache_hit(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(f=1))")
        st, body = _query(port, "i", "Count(Row(f=1))",
                          path_suffix="?profile=true")
        prof = json.loads(body)["profile"]
        assert prof["resultCacheHit"] is True
        assert json.loads(body)["results"] == [3]
        # a MISS profile carries the flag too, as False
        st, body = _query(port, "i", "Count(Row(f=2))",
                          path_suffix="?profile=true")
        assert json.loads(body)["profile"]["resultCacheHit"] is False

    def test_ledger_bills_hits(self, cache_server):
        port = cache_server.port
        hdr = {"X-Pilosa-Tenant": "acme"}
        _query(port, "i", "Count(Row(f=1))", headers=hdr)
        for _ in range(3):
            _query(port, "i", "Count(Row(f=1))", headers=hdr)
        st, body = _req(port, "GET", "/debug/tenants")
        rows = {r["tenant"]: r for r in json.loads(body)["tenants"]}
        assert rows["acme"]["queries"] == 4
        assert rows["acme"]["result_cache_hits"] == 3

    def test_debug_rescache_endpoint(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(f=1))")
        _query(port, "i", "Count(Row(f=1))")
        st, body = _req(port, "GET", "/debug/rescache")
        out = json.loads(body)
        assert st == 200 and out["enabled"] is True
        assert out["result_cache_entries"] == 1
        (entry,) = out["entries"]
        assert entry["pql"] == "Count(Row(f=1))"
        assert entry["fields"] == ["f"]
        assert entry["hits"] >= 1
        # k must be positive, like the sibling debug endpoints
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(port, "GET", "/debug/rescache?k=-1")
        assert ei.value.code == 400

    def test_metrics_exposition(self, cache_server):
        port = cache_server.port
        _query(port, "i", "Count(Row(f=1))")
        _query(port, "i", "Count(Row(f=1))")
        st, body = _req(port, "GET", "/metrics")
        text = body.decode()
        for family, mtype in [
            ("pilosa_tpu_result_cache_hits_total", "counter"),
            ("pilosa_tpu_result_cache_bytes", "gauge"),
            ("pilosa_tpu_residency_tier_passes_total", "counter"),
            ("pilosa_tpu_residency_bytes_host", "gauge"),
            ("pilosa_tpu_residency_tier_promotions_total", "counter"),
        ]:
            assert f"# TYPE {family} {mtype}" in text, family
        st, body = _req(port, "GET", "/debug/vars")
        out = json.loads(body)
        assert out["result_cache"]["result_cache_hits_total"] >= 1
        assert "residency_tier_passes_total" in out["residency_tiering"]

    def test_concurrent_write_read_your_writes(self, cache_server):
        """The invalidation-race gate: writers group-committing while
        readers race fills — every writer's own read-after-ack must
        observe its write (rows disjoint per writer, so each thread's
        oracle is exact)."""
        port = cache_server.port
        errors: list = []

        def writer(row):
            try:
                for k in range(12):
                    st, _ = _query(port, "i", f"Set({1000 + k}, g={row})")
                    assert st == 200
                    st, body = _query(port, "i", f"Count(Row(g={row}))")
                    got = json.loads(body)["results"][0]
                    assert got == k + 1, \
                        f"row {row}: acked {k + 1} writes, read {got}"
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                for _ in range(40):
                    _query(port, "i", "Count(Row(g=21))")
                    _query(port, "i", "Count(Row(g=22))")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = ([threading.Thread(target=writer, args=(r,))
                    for r in (21, 22)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors


@pytest.mark.skipif(not hasattr(socket, "SO_REUSEPORT"),
                    reason="multi-process serving needs SO_REUSEPORT")
class TestMpServing:
    def test_read_your_writes_across_worker_rings(self, tmp_path):
        """The mp-serving variant of the oracle: the cache lives
        owner-side, writes arrive via one worker's ring, reads via
        another's (urllib opens a fresh connection per request, so the
        kernel spreads them across the SO_REUSEPORT group)."""
        server = Server(ServerConfig(
            data_dir=str(tmp_path / "mp"), port=0, serving_workers=2,
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
            result_cache_bytes=8 << 20,
        )).open()
        try:
            port = server.port
            _req(port, "POST", "/index/i", b"{}")
            _req(port, "POST", "/index/i/field/f", b"{}")
            for k in range(15):
                st, _ = _query(port, "i", f"Set({k}, f=3)")
                assert st == 200
                st, body = _query(port, "i", "Count(Row(f=3))")
                got = json.loads(body)["results"][0]
                assert got == k + 1, \
                    f"acked {k + 1} writes, worker read {got} (stale)"
            # write-interleaved reads above each refilled (every write
            # invalidated); a quiet stretch of identical reads is
            # cache-served owner-side across whichever workers' rings
            for _ in range(4):
                st, body = _query(port, "i", "Count(Row(f=3))")
                assert json.loads(body)["results"] == [15]
            assert (global_result_cache().metrics()
                    ["result_cache_hits_total"]) >= 1
            st, body = _req(port, "GET", "/debug/tenants")
            rows = {r["tenant"]: r for r in json.loads(body)["tenants"]}
            assert rows["default"]["result_cache_hits"] >= 1
        finally:
            server.close()


# ----------------------------------------------------------- tiering


def _mkrow(seed):
    a = np.zeros(WORDS_PER_SHARD, np.uint32)
    a[seed * 512:seed * 512 + 8] = 5
    return a


class TestTiering:
    def test_demote_promote_cycle(self):
        cache = DeviceRowCache(budget_bytes=64 << 20,
                               host_budget_bytes=8 << 20)
        heat = HeatMap(half_life_s=60.0)
        scope = "/d/i"
        for shard in range(2):
            for row in range(2):
                cache.get_row((scope, "i", "f", "standard", shard, row),
                              lambda r=row: _mkrow(r + 1))
        heat.record_access("i", "f", [0], n=50.0, scope=scope)
        t = ResidencyTierer(cache=cache, heat=heat, interval_s=0,
                            promote_heat=4.0, demote_heat=1.0,
                            min_dwell_s=0)
        out = t.run_pass()
        assert out["demoted"] == 2  # shard 1's two rows
        assert cache.metrics()["residency_entries_host"] == 2
        assert cache.host_bytes > 0
        per_frag, _ = cache.tier_overlay()
        assert per_frag[(scope, "i", "f", 1)]["host"] > 0
        assert per_frag[(scope, "i", "f", 1)]["dense"] == 0
        # heat returns -> the pass promotes (worker-driven)
        heat.record_access("i", "f", [1], n=50.0, scope=scope)
        out = t.run_pass()
        assert out["promoted"] == 2
        assert cache.metrics()["residency_entries_host"] == 0
        # and the data survived the round trip bit-exact
        arr = cache.get_row((scope, "i", "f", "standard", 1, 0),
                            lambda: (_ for _ in ()).throw(
                                AssertionError("should be resident")))
        assert np.array_equal(np.asarray(arr), _mkrow(1))

    def test_plane_stack_tiers_at_field_granularity(self):
        """A BSI plane-stack leaf ('stackp', scope, index, field,
        2+depth, block) is len 6 with an int at [4]: it must classify
        as a stacked-field entry in tier_overlay, not masquerade as a
        fragment under a bogus key whose heat is forever 0 (which
        demoted hot plane stacks every pass, bypassing the field-max
        heat protection)."""
        cache = DeviceRowCache(budget_bytes=64 << 20)
        heat = HeatMap(half_life_s=60.0)
        scope = "/d/i"
        key = ("stackp", scope, "i", "f", 5, (0, 4))
        cache.get_row(key, lambda: _mkrow(1))
        per_frag, per_stack = cache.tier_overlay()
        assert (scope, "i", "f") in per_stack
        assert not any(k[0] == "stackp" for k in per_frag)
        # hot field -> the pass must leave the stack device-resident
        heat.record_access("i", "f", [0], n=50.0, scope=scope)
        t = ResidencyTierer(cache=cache, heat=heat, interval_s=0,
                            promote_heat=4.0, demote_heat=1.0,
                            min_dwell_s=0)
        out = t.run_pass()
        assert out["demoted"] == 0
        assert t.last_decisions()[(scope, "i", "f")] == "resident"
        # cold field -> demoted at field granularity; re-heat -> the
        # pass promotes it back bit-exact
        heat.clear()
        out = t.run_pass()
        assert out["demoted"] == 1
        assert cache.metrics()["residency_entries_host"] == 1
        assert t.last_decisions()[(scope, "i", "f")] == "demoted"
        heat.record_access("i", "f", [0], n=50.0, scope=scope)
        out = t.run_pass()
        assert out["promoted"] == 1
        assert cache.metrics()["residency_entries_host"] == 0
        arr = cache.get_row(key, lambda: (_ for _ in ()).throw(
            AssertionError("should be resident after promote")))
        assert np.array_equal(np.asarray(arr), _mkrow(1))

    def test_host_hit_promotes_on_access(self):
        cache = DeviceRowCache(budget_bytes=64 << 20)
        heat = HeatMap()
        scope = "/d/i"
        key = (scope, "i", "f", "standard", 0, 1)
        cache.get_row(key, lambda: _mkrow(2))
        cache.demote_fragment_to_host(scope, "i", "f", 0)
        assert cache.metrics()["residency_entries_host"] == 1
        arr = cache.get_row(key, lambda: (_ for _ in ()).throw(
            AssertionError("host tier must serve without a decode")))
        assert np.array_equal(np.asarray(arr), _mkrow(2))
        assert cache.host_hits == 1 and cache.tier_promotions == 1
        assert cache.metrics()["residency_entries_host"] == 0

    def test_write_invalidates_host_copy(self):
        cache = DeviceRowCache(budget_bytes=64 << 20)
        scope = "/d/i"
        key = (scope, "i", "f", "standard", 0, 1)
        cache.get_row(key, lambda: _mkrow(1))
        cache.demote_fragment_to_host(scope, "i", "f", 0)
        cache.invalidate(key)  # what _after_row_write does
        assert cache.metrics()["residency_entries_host"] == 0
        # next read decodes fresh (miss), never serves the stale copy
        fresh = _mkrow(3)
        arr = cache.get_row(key, lambda: fresh)
        assert np.array_equal(np.asarray(arr), fresh)

    def test_hysteresis_dwell_blocks_flipflop(self):
        cache = DeviceRowCache(budget_bytes=64 << 20)
        heat = HeatMap(half_life_s=60.0)
        scope = "/d/i"
        key = (scope, "i", "f", "standard", 0, 1)
        cache.get_row(key, lambda: _mkrow(1))
        cache.demote_fragment_to_host(scope, "i", "f", 0)
        heat.record_access("i", "f", [0], n=50.0, scope=scope)
        t = ResidencyTierer(cache=cache, heat=heat, interval_s=0,
                            promote_heat=4.0, demote_heat=1.0,
                            min_dwell_s=3600.0)
        assert t.run_pass()["promoted"] == 1
        heat.clear()  # heat vanishes -> candidate for demotion...
        out = t.run_pass()
        assert out["demoted"] == 0  # ...but the dwell holds it resident
        assert t.last_decisions()[(scope, "i", "f", 0)] == "hold"
        t.min_dwell_s = 0.0
        assert t.run_pass()["demoted"] == 1

    def test_host_budget_bounds_tier(self):
        cache = DeviceRowCache(budget_bytes=64 << 20,
                               host_budget_bytes=6000)
        scope = "/d/i"
        for shard in range(4):
            cache.get_row((scope, "i", "f", "standard", shard, 1),
                          lambda s=shard: _mkrow(s + 1))
            cache.demote_fragment_to_host(scope, "i", "f", shard)
        assert cache.host_bytes <= 6000
        assert cache.evictions > 0

    def test_pacer_shapes_promotions(self):
        from pilosa_tpu.parallel.pacer import RepairPacer

        cache = DeviceRowCache(budget_bytes=64 << 20)
        heat = HeatMap(half_life_s=60.0)
        scope = "/d/i"
        for row in range(3):
            cache.get_row((scope, "i", "f", "standard", 0, row),
                          lambda r=row: _mkrow(r + 1))
        cache.demote_fragment_to_host(scope, "i", "f", 0)
        heat.record_access("i", "f", [0], n=50.0, scope=scope)
        pacer = RepairPacer(max_bytes_per_sec=65536)
        pacer.consume(2 * 65536)  # drain the burst: next debit overdraws
        t = ResidencyTierer(cache=cache, heat=heat, interval_s=0,
                            promote_heat=4.0, demote_heat=1.0,
                            min_dwell_s=0, pacer=pacer)
        t0 = time.monotonic()
        out = t.run_pass()
        assert out["promoted"] == 3
        assert out["pacedSleepS"] > 0, \
            "promotion uploads must debit the pacer's token bucket"
        assert (t.metrics()
                ["residency_tier_paced_sleep_seconds_total"]) > 0
        assert time.monotonic() - t0 >= out["pacedSleepS"] * 0.5

    def test_heatmap_tier_view(self, tmp_path):
        """GET /debug/heatmap?tier=true shows the tiering decisions
        beside raw heat — resident vs host vs cold, with the last
        pass's verdicts."""
        server = Server(ServerConfig(
            data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
            residency_promote_interval=3600.0,  # worker parked: manual
            residency_promote_heat=3.0, residency_demote_heat=0.5,
            heat_half_life=0.4,
        )).open()
        try:
            port = server.port
            assert server.api.tierer is not None
            for name in ("hot", "cold"):
                _req(port, "POST", f"/index/{name}", b"{}")
                _req(port, "POST", f"/index/{name}/field/f", b"{}")
                _query(port, name, "Set(1, f=1)")
                _query(port, name, "Count(Row(f=1))")
            time.sleep(1.3)  # both cool below demote-heat
            for _ in range(12):
                _query(port, "hot", "Count(Row(f=1))")  # re-heat hot
            out = server.api.tierer.run_pass()
            assert out["demoted"] >= 1
            st, body = _req(port, "GET", "/debug/heatmap?tier=true&k=50")
            snap = json.loads(body)
            assert snap["tiering"]["enabled"] is True
            tiers = {(r["index"], r["field"]): r.get("tier")
                     for r in snap["shards"]}
            assert tiers[("cold", "f")] == "host"
            assert tiers[("hot", "f")] in ("resident", "compressed")
            decisions = {r["index"]: r.get("tierDecision")
                         for r in snap["shards"] if "tierDecision" in r}
            assert decisions.get("cold") == "demoted"
            # serving keeps working across the tier transition
            st, body = _query(port, "cold", "Count(Row(f=1))")
            assert (st, json.loads(body)["results"]) == (200, [1])
        finally:
            server.close()


# ------------------------------------------------------------- config


class TestKnobs:
    def test_roundtrip(self):
        cfg = ServerConfig.from_dict({
            "result-cache-bytes": "33554432",
            "residency-promote-interval": "1m30s",
            "residency-promote-heat": "6.5",
            "residency-demote-heat": "2.5",
            "residency-host-tier-bytes": "2147483648",
        })
        assert cfg.result_cache_bytes == 33554432
        assert cfg.residency_promote_interval == 90.0
        assert cfg.residency_promote_heat == 6.5
        assert cfg.residency_demote_heat == 2.5
        assert cfg.residency_host_tier_bytes == 2 << 30
        d = cfg.to_dict()
        assert d["result-cache-bytes"] == 33554432
        assert d["residency-promote-interval"] == 90.0
        cfg2 = ServerConfig.from_dict(d)
        assert cfg2.to_dict() == d

    def test_snake_case_fallback(self):
        cfg = ServerConfig.from_dict({
            "result_cache_bytes": 1024,
            "residency_promote_interval": 2.0,
        })
        assert cfg.result_cache_bytes == 1024
        assert cfg.residency_promote_interval == 2.0

    @pytest.mark.parametrize("kwargs", [
        {"result_cache_bytes": -1},
        {"residency_promote_interval": -1.0},
        {"residency_demote_heat": -0.5},
        {"residency_host_tier_bytes": -1},
        # promote must exceed demote: the gap is the hysteresis band
        {"residency_promote_heat": 1.0, "residency_demote_heat": 1.0},
        {"residency_promote_heat": 0.5, "residency_demote_heat": 2.0},
    ])
    def test_validation(self, kwargs):
        with pytest.raises(ValueError):
            ServerConfig(**kwargs)

    def test_generate_config_covers_knobs(self):
        from pilosa_tpu.cli import _DEFAULT_TOML

        for knob in ("result-cache-bytes", "residency-promote-interval",
                     "residency-promote-heat", "residency-demote-heat",
                     "residency-host-tier-bytes"):
            assert knob in _DEFAULT_TOML, knob

    def test_server_wires_cache_and_tierer(self, tmp_path):
        server = Server(ServerConfig(
            data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
            result_cache_bytes=1 << 20,
            residency_promote_interval=3600.0,
        )).open()
        try:
            assert global_result_cache().budget_bytes == 1 << 20
            assert server.api.tierer is not None
            assert server.api.tierer.promote_heat == 4.0
            # tiering shares the repair pacer (never starves serving)
            assert (server.api.tierer.pacer
                    is server.api.cluster.client.pacer)
        finally:
            server.close()
        # a default (cache-off) server later disables the global again
        server2 = Server(ServerConfig(
            data_dir=str(tmp_path / "d2"), port=0,
            anti_entropy_interval=0, heartbeat_interval=0,
            use_mesh=False,
        )).open()
        try:
            assert not global_result_cache().enabled
            assert server2.api.tierer is None
        finally:
            server2.close()
