"""Query cost plane (ISSUE 8): PQL PROFILE, per-tenant usage
accounting, per-shard heat telemetry, and SLO burn-rate monitoring.

Covers the tentpole end to end: single-node and 3-node stitched
profiles (with the span-tree reconciliation oracle), the tenant ledger
+ /debug/tenants top-K view, the heat map's skewed-workload ranking and
decay, the SLO engine's burst-flip behavior, knob roundtrips, and the
/metrics exposition of the new families.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.cluster_helpers import make_cluster, req, seed, uri

from pilosa_tpu.qos.slo import SLOEngine, SLOObjective
from pilosa_tpu.server import Server, ServerConfig
from pilosa_tpu.storage.heat import HeatMap, global_heat
from pilosa_tpu.utils.cost import (
    CostLedger,
    cost_enabled,
    current_cost,
    set_cost_enabled,
)


@pytest.fixture(autouse=True)
def _fresh_cost_plane():
    """Cost plane on + empty global heat for every test (the heat map
    is process-global like the tracer)."""
    set_cost_enabled(True)
    global_heat().clear()
    yield
    set_cost_enabled(True)
    global_heat().clear()


@pytest.fixture()
def server(tmp_path):
    s = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0,
    )).open()
    yield s
    s.close()


def _seed_one(s: Server, index="i", n_shards=2):
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    idx = s.holder.create_index(index)
    f = idx.create_field("f")
    for shard in range(n_shards):
        frag = f.view(VIEW_STANDARD, create=True).fragment(
            shard, create=True)
        frag.bulk_import(
            np.array([1, 1, 1, 2, 2], np.uint64),
            np.array([10, 11, 12, 10, 11], np.uint64),
        )
    s.api.cluster.note_local_shards(index, list(range(n_shards)))


def _post(s, path, body=b""):
    return req("POST", f"{uri(s)}{path}", body=body)


# ------------------------------------------------------------- PROFILE


def test_profile_single_node_structure(server):
    _seed_one(server)
    out = _post(server, "/index/i/query?profile=true",
                b"Count(Intersect(Row(f=1), Row(f=2)))")
    assert out["results"] == [4]
    prof = out["profile"]
    assert prof["node"] == server.api.cluster.local.id
    assert prof["index"] == "i"
    (call,) = prof["calls"]
    assert call["name"] == "Count"
    # AST children mirror the parsed tree
    (inter,) = call["children"]
    assert inter["name"] == "Intersect"
    assert [c["name"] for c in inter["children"]] == ["Row", "Row"]
    # measured counters: fresh server → residency misses decode roaring
    # containers; the per-leaf records carry field + container kinds
    assert call["deviceMs"] > 0
    assert call["dispatches"] >= 1
    assert call["shards"] == 2
    totals = prof["totals"]
    assert totals["rowCacheMisses"] > 0
    assert totals["bytesMoved"] > 0
    containers = totals["containers"]
    assert containers["array"] + containers["bitmap"] + containers["run"] > 0
    leaves = call["leaves"]
    assert {l["field"] for l in leaves} == {"f"}
    assert sorted(l["row"] for l in leaves) == [1, 2]


def test_profile_repeat_hits_caches(server):
    _seed_one(server)
    q = b"Count(Row(f=1))"
    _post(server, "/index/i/query?profile=true", q)
    out = _post(server, "/index/i/query?profile=true", q)
    (call,) = out["profile"]["calls"]
    # identical PQL → parse memo → plan-cache hit; warm leaves → either
    # the operand memo or the residency cache answers (no re-decode)
    assert call["planCacheHit"] is True
    assert out["profile"]["totals"]["containers"] == {
        "array": 0, "bitmap": 0, "run": 0}
    assert (call["operandMemoHit"]
            or out["profile"]["totals"]["rowCacheHits"] > 0)


def test_profile_rows_materialized(server):
    _seed_one(server)
    out = _post(server, "/index/i/query?profile=true", b"Row(f=1)")
    (call,) = out["profile"]["calls"]
    assert call["rowsMaterialized"] == 6  # 3 cols x 2 shards
    assert sorted(out["results"][0]["columns"])[:3] == [10, 11, 12]


def test_profile_absent_without_param(server):
    _seed_one(server)
    out = _post(server, "/index/i/query", b"Count(Row(f=1))")
    assert "profile" not in out


def test_profile_legacy_serving_path(tmp_path):
    s = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0,
    )).open()
    try:
        s.api.serve_fastlane = False
        _seed_one(s)
        out = _post(s, "/index/i/query?profile=true", b"Count(Row(f=1))")
        assert out["results"] == [3 * 2]
        assert out["profile"]["calls"][0]["name"] == "Count"
    finally:
        s.close()


def test_profile_error_requests_carry_no_profile(server):
    _seed_one(server)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server, "/index/i/query?profile=true", b"Count(Row(nope=1))")
    assert ei.value.code == 400


def test_profile_wall_reconciles_with_span_tree(server):
    """Acceptance oracle: a profiled AND traced request's per-call wall
    total must reconcile with the span tree's executor.Execute duration
    (both envelopes wrap the same resolve loop). Uses a fresh query
    shape so compile time puts the durations at ms scale where the
    +/-10%% comparison is meaningful."""
    from pilosa_tpu.utils.tracing import global_tracer

    _seed_one(server)
    tracer = global_tracer()
    tracer.sample_rate = 1.0
    tracer.clear()
    try:
        out = _post(server, "/index/i/query?profile=true",
                    b"Count(Xor(Row(f=1), Row(f=2)))")
        prof_wall = sum(c["wallMs"] for c in out["profile"]["calls"])

        def find(node, name):
            if node["name"] == name:
                return node
            for c in node.get("children", []):
                hit = find(c, name)
                if hit is not None:
                    return hit
            return None

        execs = [find(t, "executor.Execute") for t in tracer.recent()]
        execs = [e for e in execs if e is not None]
        assert execs, "traced request produced no executor.Execute span"
        span_ms = execs[-1]["durationMs"]
        assert span_ms > 1.0  # compile puts this at ms scale
        assert prof_wall == pytest.approx(span_ms, rel=0.10)
    finally:
        tracer.sample_rate = 0.0
        tracer.clear()


# --------------------------------------------------------- 3-node PROFILE


def test_profile_three_node_stitched(tmp_path):
    servers = make_cluster(tmp_path, 3)
    try:
        seed(servers[0], n_shards=6)
        time.sleep(0.2)
        out = req(
            "POST",
            f"{uri(servers[0])}/index/i/query?profile=true",
            body=b"Count(Row(f=1))",
        )
        total = out["results"][0]
        prof = out["profile"]
        # one stitched tree: the coordinator's calls plus one grafted
        # per-node profile per remote leg, each a full profile whose
        # calls ran REMOTELY (rpc legs profile on their own node)
        remote_nodes = {r["node"] for r in prof["remote"]}
        assert len(remote_nodes) == 2
        assert prof["node"] not in remote_nodes
        for leg in prof["remote"]:
            sub = leg["profile"]
            assert sub["calls"], "remote leg returned an empty profile"
            assert sub["calls"][0]["name"] == "Count"
            assert sub["node"] in remote_nodes
        # per-stage reconciliation: shard coverage across the
        # coordinator + grafted legs equals the query's shard set
        local_shards = prof["totals"]["shards"]
        leg_shards = sum(leg["shards"] for leg in prof["remote"])
        assert local_shards + leg_shards == 6
        assert total == 4 * 6  # seed: row 1 holds 4 cols per shard
    finally:
        for s in servers:
            s.close()


def test_profile_three_node_trace_and_profile_agree(tmp_path):
    """Run ONE request with both planes on: the span tree's remote
    children and the profile's grafted legs must name the same peers."""
    from pilosa_tpu.utils.tracing import global_tracer

    servers = make_cluster(tmp_path, 3)
    tracer = global_tracer()
    try:
        seed(servers[0], n_shards=6)
        time.sleep(0.2)
        tracer.sample_rate = 1.0
        tracer.clear()
        out = req(
            "POST",
            f"{uri(servers[0])}/index/i/query?profile=true",
            body=b"Count(Row(f=2))",
        )
        prof_nodes = {r["node"] for r in out["profile"]["remote"]}

        span_nodes = set()

        def walk(node):
            if node["name"] == "rpc.query":
                span_nodes.add(node["tags"].get("node"))
            for c in node.get("children", []):
                walk(c)

        for t in tracer.recent():
            walk(t)
        assert prof_nodes
        assert prof_nodes == span_nodes
    finally:
        tracer.sample_rate = 0.0
        tracer.clear()
        for s in servers:
            s.close()


# ------------------------------------------------------------- ledger


def test_tenant_ledger_and_debug_endpoint(server):
    _seed_one(server)
    for tenant, n in (("acme", 6), ("beta", 2)):
        for _ in range(n):
            r = urllib.request.Request(
                f"{uri(server)}/index/i/query",
                data=b"Count(Row(f=1))", method="POST",
                headers={"X-Pilosa-Tenant": tenant},
            )
            urllib.request.urlopen(r, timeout=30).read()
    out = req("GET", f"{uri(server)}/debug/tenants?k=1&by=queries")
    by_tenant = {r["tenant"]: r for r in out["tenants"]}
    assert by_tenant["acme"]["queries"] == 6
    assert by_tenant["beta"]["queries"] == 2
    assert by_tenant["acme"]["egress_bytes"] > 0
    assert by_tenant["acme"]["device_ms"] >= 0
    # top-K offender view honors k and the requested column
    assert len(out["top"]) == 1
    assert out["top"][0]["tenant"] == "acme"
    assert out["totals"]["queries_total"] == 8


def test_tenant_ledger_counts_ingest(server):
    _seed_one(server)
    r = urllib.request.Request(
        f"{uri(server)}/index/i/field/f/import",
        data=json.dumps({"rows": [5, 5, 5], "columns": [1, 2, 3]}).encode(),
        method="POST",
        headers={"Content-Type": "application/json",
                 "X-Pilosa-Tenant": "loader"},
    )
    urllib.request.urlopen(r, timeout=30).read()
    out = req("GET", f"{uri(server)}/debug/tenants")
    row = next(r for r in out["tenants"] if r["tenant"] == "loader")
    assert row["ingest_rows"] == 3


def test_ledger_unknown_sort_column_400(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        req("GET", f"{uri(server)}/debug/tenants?by=bogus")
    assert ei.value.code == 400


def test_ledger_overflow_bucket():
    led = CostLedger(max_pairs=3)
    for i in range(10):
        led.add_ingest(f"t{i}", "i", 1)
    snap = led.snapshot()
    assert len(snap) == 4  # 3 real pairs + the one overflow bucket
    other = next(r for r in snap if r["tenant"] == "__other__")
    assert other["ingest_rows"] == 7  # everything past the cap
    assert led.metrics()["ingest_rows_total"] == 10  # totals stay exact


def test_cost_kill_switch(server):
    _seed_one(server)
    set_cost_enabled(False)
    try:
        assert current_cost() is None
        out = _post(server, "/index/i/query", b"Count(Row(f=1))")
        assert out["results"] == [6]
        assert server.api.cost.snapshot() == []
        assert global_heat().metrics()["accesses_total"] == 0
    finally:
        set_cost_enabled(True)
    assert cost_enabled()


# ------------------------------------------------------------- heat map


def test_heatmap_ranks_skewed_two_index_workload(server):
    _seed_one(server, index="hot", n_shards=2)
    _seed_one(server, index="cold", n_shards=2)
    for _ in range(9):
        _post(server, "/index/hot/query", b"Count(Row(f=1))")
    _post(server, "/index/cold/query", b"Count(Row(f=1))")
    out = req("GET", f"{uri(server)}/debug/heatmap?k=50")
    rows = [r for r in out["shards"] if r["field"] == "f"]
    hottest = rows[0]
    assert hottest["index"] == "hot"
    by_index = {}
    for r in rows:
        by_index.setdefault(r["index"], 0)
        by_index[r["index"]] += r["access"]
    assert by_index["hot"] > by_index["cold"] * 3
    # residency overlay: the queried leaves are device-resident
    assert any(r["resident"] for r in rows)
    assert out["halfLifeS"] == 300.0


def test_heatmap_counts_writes(server):
    _seed_one(server)
    _post(server, "/index/i/query", b"Set(7, f=9)")
    out = req("GET", f"{uri(server)}/debug/heatmap")
    row = next(r for r in out["shards"]
               if r["index"] == "i" and r["field"] == "f")
    assert row["writes"] >= 1


def test_heat_ignores_background_writes(server):
    """Fragment writes OUTSIDE a request cost context (anti-entropy
    repair, direct maintenance) must not skew the promote/demote
    signal; edge imports record at the API layer instead."""
    _seed_one(server)  # direct frag.bulk_import — no ctx, no API route
    rows = [r for r in global_heat().hottest(20)
            if r["index"] == "i" and r["field"] == "f"]
    assert all(r["writes"] == 0 for r in rows)
    # an edge HTTP import DOES record write heat (API-layer hook)
    _post(server, "/index/i/field/f/import",
          json.dumps({"rows": [3, 3], "columns": [1, 2]}).encode())
    row = next(r for r in global_heat().hottest(20)
               if r["index"] == "i" and r["field"] == "f"
               and r["shard"] == 0)
    assert row["writes"] >= 2


def test_debug_k_must_be_positive(server):
    for path in ("/debug/tenants?k=-1", "/debug/heatmap?k=-3"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("GET", f"{uri(server)}{path}")
        assert ei.value.code == 400


def test_heatmap_k_zero_is_full_table(server):
    """``?k=0`` = the FULL heat table — the exact request
    ``client.heatmap`` (the autopilot coordinator's peer heat gather)
    sends. Rejecting or capping it makes every peer read cold and the
    planner skip 'in-budget' forever, silently."""
    from pilosa_tpu.parallel.client import InternalClient

    _seed_one(server, index="hot2", n_shards=2)
    _seed_one(server, index="cold2", n_shards=2)
    for _ in range(3):
        _post(server, "/index/hot2/query", b"Count(Row(f=1))")
    full = req("GET", f"{uri(server)}/debug/heatmap?k=0")
    capped = req("GET", f"{uri(server)}/debug/heatmap?k=1")
    assert len(capped["shards"]) == 1
    assert len(full["shards"]) > 1
    # and over the planner's actual wire path
    wired = InternalClient().heatmap(uri(server))
    assert {(r["index"], r["field"], r["shard"]) for r in wired["shards"]} \
        == {(r["index"], r["field"], r["shard"]) for r in full["shards"]}


def test_roaring_import_bills_submitted_bits(server):
    """Re-importing an identical roaring payload must bill the same
    ingest_rows as the first import (rows SUBMITTED, like the
    row/value routes) — not zero because nothing changed."""
    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize

    _seed_one(server)
    data = serialize(RoaringBitmap.from_ids(
        np.array([9 << 20 | 5, 9 << 20 | 6, 9 << 20 | 7], np.uint64)))
    for _ in range(2):  # second import changes ZERO bits
        r = urllib.request.Request(
            f"{uri(server)}/index/i/field/f/import-roaring/0",
            data=data, method="POST",
            headers={"X-Pilosa-Tenant": "loader"},
        )
        urllib.request.urlopen(r, timeout=30).read()
    out = req("GET", f"{uri(server)}/debug/tenants")
    row = next(r for r in out["tenants"] if r["tenant"] == "loader")
    assert row["ingest_rows"] == 6


def test_heat_decay_half_life():
    heat = HeatMap(half_life_s=0.05)
    heat.record_access("i", "f", [0], n=8.0)
    time.sleep(0.1)  # two half-lives
    (row,) = heat.hottest(1)
    assert row["access"] == pytest.approx(2.0, rel=0.5)


def test_heat_prune_bounds_table():
    heat = HeatMap()
    for shard in range(300):
        heat.record_access("i", "f", [shard])
    heat._maybe_prune(max_entries=100)
    assert heat.metrics()["tracked_shards"] <= 100


# ------------------------------------------------------------------ SLO


def test_slo_objective_parsing():
    o = SLOObjective.parse("reads:latency:100ms:0.99")
    assert o.kind == "latency" and o.threshold_s == pytest.approx(0.1)
    o2 = SLOObjective.parse("avail:errors:0.999")
    assert o2.kind == "errors" and o2.target == 0.999
    for bad in ("nope", "x:latency:0.99", "x:errors:2.0",
                "x:latency:abcms:0.9", "x:weird:0.9"):
        with pytest.raises(ValueError):
            SLOObjective.parse(bad)


def test_slo_burst_flips_burn_rate_within_window():
    eng = SLOEngine.from_config(
        ["reads:latency:50ms:0.99"], ["2s", "10s"])
    for _ in range(100):
        eng.record(0.001)  # healthy traffic
    rows = eng.burn_rates()
    assert rows[0]["windows"]["2s"]["burnRate"] == 0.0
    assert rows[0]["breach"] is False
    # injected latency burst: evaluation is lazy, so the very next
    # scrape inside the window sees it burning
    for _ in range(10):
        eng.record(0.2)
    rows = eng.burn_rates()
    assert rows[0]["windows"]["2s"]["burnRate"] > 1.0
    assert rows[0]["breach"] is True


def test_slo_error_objective_and_multiwindow_and():
    eng = SLOEngine.from_config(["avail:errors:0.9"], ["1s", "3600s"])
    for _ in range(50):
        eng.record(0.001, error=False)
    time.sleep(1.1)  # healthy history ages OUT of the fast window only
    for _ in range(5):
        eng.record(0.001, error=True)
    rows = eng.burn_rates()
    w = rows[0]["windows"]
    assert w["1s"]["burnRate"] > 1.0          # all-bad fast window
    assert w["3600s"]["burnRate"] < 1.0        # diluted slow window
    assert rows[0]["breach"] is False          # multi-window AND holds


def test_slo_http_surface(tmp_path):
    s = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0,
        slo_objectives=["reads:latency:1us:0.99"],
        slo_windows=["2s", "5s"],
    )).open()
    try:
        _seed_one(s)
        _post(s, "/index/i/query", b"Count(Row(f=1))")  # always > 1us
        out = req("GET", f"{uri(s)}/debug/slo")
        assert out["windows"] == [2, 5]
        (obj,) = out["objectives"]
        assert obj["name"] == "reads"
        assert obj["windows"]["2s"]["bad"] >= 1
        assert obj["breach"] is True
        metrics = req("GET", f"{uri(s)}/metrics", raw=True).decode()
        assert ('pilosa_tpu_slo_breach{objective="reads"} 1'
                in metrics)
        assert 'pilosa_tpu_slo_burn_rate{objective="reads",window="2s"}' \
            in metrics
    finally:
        s.close()


def test_slo_durations_match_sibling_knob_grammar():
    """SLO specs live in the same TOML as every other knob — compound
    Go-style durations must parse (review finding: a narrower grammar
    rejected '1m30s' that heat-half-life accepts)."""
    eng = SLOEngine.from_config(["r:latency:1m30s:0.99"], ["1m30s", "2h"])
    assert eng.objectives[0].threshold_s == pytest.approx(90.0)
    assert eng.windows_s == (90.0, 7200.0)
    assert SLOObjective.parse("r:latency:0.25:0.9").threshold_s == 0.25


def test_ledger_metrics_rank_per_family():
    """The ingest-heavy tenant must appear in tenant_ingest_rows_total
    even when the series cap drops it from the device-ms ranking."""
    led = CostLedger()
    for i in range(4):
        led.record_query(f"q{i}", "i", None, 0.5)  # wall_ms heavy
    led.add_ingest("loader", "i", 10_000)
    text = led.prometheus_lines("p", max_series=2)
    ingest_lines = [l for l in text.splitlines()
                    if l.startswith("p_tenant_ingest_rows_total{")]
    assert any('tenant="loader"' in l and l.endswith(" 10000")
               for l in ingest_lines), text


def test_profile_param_rejected_on_protobuf_accept(server):
    """?profile=true with a protobuf Accept must 400 (the profile rides
    only the JSON envelope) instead of silently paying the overhead and
    dropping the tree."""
    _seed_one(server)
    r = urllib.request.Request(
        f"{uri(server)}/index/i/query?profile=true",
        data=b"Count(Row(f=1))", method="POST",
        headers={"Accept": "application/x-protobuf"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r, timeout=30)
    assert ei.value.code == 400


def test_slo_invalid_objective_fails_config():
    with pytest.raises(ValueError):
        ServerConfig(slo_objectives=["bogus"])
    with pytest.raises(ValueError):
        ServerConfig(slo_objectives=["x:latency:10ms:1.5"])


# ------------------------------------------------------- knobs / metrics


def test_slow_query_ring_knob(tmp_path):
    cfg = ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0, slow_query_ring=3, long_query_time=1e-9,
        heat_half_life=7.0,
    )
    # TOML/env roundtrip
    rt = ServerConfig.from_dict(cfg.to_dict())
    assert rt.slow_query_ring == 3
    assert rt.heat_half_life == 7.0
    s = Server(cfg).open()
    try:
        assert s.api.long_queries.maxlen == 3
        assert global_heat().half_life_s == 7.0
        _seed_one(s)
        for i in range(5):
            _post(s, "/index/i/query", b"Count(Row(f=1))")
        out = req("GET", f"{uri(s)}/debug/queries/slow")
        assert len(out["queries"]) == 3  # ring capped at the knob
        assert out["total"] == 5
    finally:
        s.close()
    with pytest.raises(ValueError):
        ServerConfig(slow_query_ring=0)
    with pytest.raises(ValueError):
        ServerConfig(heat_half_life=0)


def test_metrics_families_have_metadata(server):
    _seed_one(server)
    _post(server, "/index/i/query?profile=true", b"Count(Row(f=1))")
    text = req("GET", f"{uri(server)}/metrics", raw=True).decode()
    typed = {line.split(" ")[2] for line in text.splitlines()
             if line.startswith("# TYPE ")}
    for family in ("pilosa_tpu_tenant_queries_total",
                   "pilosa_tpu_tenant_device_ms_total",
                   "pilosa_tpu_tenant_egress_bytes_total",
                   "pilosa_tpu_heat_accesses_total",
                   "pilosa_tpu_heat_shard",
                   "pilosa_tpu_slo_events_total",
                   "pilosa_tpu_slo_breach",
                   "pilosa_tpu_slo_burn_rate"):
        assert family in typed, family
    # every tagged sample's family is declared (no TYPE orphans in the
    # new blocks)
    for line in text.splitlines():
        if line.startswith(("pilosa_tpu_tenant_", "pilosa_tpu_heat_",
                            "pilosa_tpu_slo_")) and "{" in line:
            family = line.split("{", 1)[0]
            assert family in typed, line


def test_tenant_label_escaping_keeps_metrics_parseable(server):
    """A client-controlled tenant header with quotes/backslashes must
    not corrupt the exposition page (review finding: one request could
    take ALL of the node's metrics dark for every scraper)."""
    _seed_one(server)
    r = urllib.request.Request(
        f"{uri(server)}/index/i/query", data=b"Count(Row(f=1))",
        method="POST",
        headers={"X-Pilosa-Tenant": 'evil"} 1 back\\slash'},
    )
    urllib.request.urlopen(r, timeout=30).read()
    text = req("GET", f"{uri(server)}/metrics", raw=True).decode()
    assert 'tenant="evil\\"} 1 back\\\\slash"' in text
    # every sample line still parses: name{labels} value
    import re

    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{([a-zA-Z_][a-zA-Z0-9_]*='
        r'"(\\.|[^"\\])*",?)*\})? [^ ]+$'
    )
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert sample.match(line), line


def test_heat_write_only_workload_bounded():
    """record_write alone must trigger pruning too (review finding: a
    bulk-ingest phase with no reads grew the table without bound)."""
    heat = HeatMap()
    for shard in range(300):
        heat.record_write("i", "f", shard)
    heat._maybe_prune(max_entries=100)
    assert heat.metrics()["tracked_shards"] <= 100


def test_heat_scope_separates_holders():
    """Two holders in one process (in-process clusters) must not merge
    their heat under identical index/field names."""
    heat = HeatMap()
    heat.record_access("i", "f", [0], n=5.0, scope="/data/a")
    heat.record_access("i", "f", [0], n=1.0, scope="/data/b")
    rows = heat.hottest(10)
    assert len(rows) == 2
    assert rows[0]["scope"] == "/data/a" and rows[0]["access"] == 5.0
    assert rows[1]["scope"] == "/data/b" and rows[1]["access"] == 1.0


def test_legacy_path_bills_egress(tmp_path):
    """serve_fastlane=False responses must feed egress_bytes like the
    fast lane (review finding: the legacy JSON path skipped the
    ledger, under-billing that node's tenants forever)."""
    s = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, anti_entropy_interval=0,
        heartbeat_interval=0,
    )).open()
    try:
        s.api.serve_fastlane = False
        _seed_one(s)
        _post(s, "/index/i/query", b"Count(Row(f=1))")
        (row,) = s.api.cost.snapshot()
        assert row["egress_bytes"] > 0
    finally:
        s.close()


def test_profile_disabled_plane_is_marked(server):
    """?profile=true with the kill switch off must say so, not return a
    plausible-looking all-zero tree."""
    _seed_one(server)
    set_cost_enabled(False)
    try:
        out = _post(server, "/index/i/query?profile=true",
                    b"Count(Row(f=1))")
        assert out["results"] == [6]
        assert out["profile"] == {
            "disabled": True,
            "reason": "cost plane is disabled on this node"}
    finally:
        set_cost_enabled(True)


def test_debug_vars_includes_cost_plane(server):
    _seed_one(server)
    _post(server, "/index/i/query", b"Count(Row(f=1))")
    snap = req("GET", f"{uri(server)}/debug/vars")
    assert snap["tenants"]["queries_total"] == 1
    assert "tracked_shards" in snap["heat"]
    assert snap["slo"]["objectives"] == 0
