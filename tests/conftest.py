"""Test configuration: run JAX on an 8-device virtual CPU mesh.

Mirrors the reference's test strategy of exercising multi-node logic in one
process without a real cluster (test.MustRunCluster — SURVEY.md §4): we
exercise multi-chip sharding logic without TPUs by forcing 8 host CPU
devices.

Note: this image's sitecustomize imports jax at interpreter startup (to
register the axon TPU plugin), so JAX_PLATFORMS in os.environ is captured
before conftest runs — we must switch platforms via jax.config instead.
Setting JAX_PLATFORMS=cpu in the *parent* environment hangs the axon
registration, so don't do that either; for subprocesses spawned by tests,
drop PALLAS_AXON_POOL_IPS to skip axon registration entirely.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# For any subprocess tests spawn: skip axon registration + force CPU there.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True)
def _fresh_global_row_cache():
    """Isolate the process-global device residency cache per test: leaves
    are keyed by (index, field, ...) names, which recur across tests that
    forget to close their holder."""
    from pilosa_tpu.storage import residency

    residency.global_row_cache().clear()
    yield
